#!/usr/bin/env python
"""Entry point: TPU host runner (run_worker.py parity).  See
distributed_llms_tpu/cli/host_main.py."""

from distributed_llms_tpu.cli.host_main import main

if __name__ == "__main__":
    main()
