# Container image for the coordinator and worker hosts.
# The reference left containerization as future scope
# (implementation.md:85-103); this image runs either role:
#   docker run ... dlt-coordinator --metrics-port 9100
#   docker run ... dlt-host --host <coordinator> --port 65432
# On TPU VMs, base on a TPU-enabled JAX image instead and the same
# entry points apply (jax[tpu] resolves the libtpu runtime).
FROM python:3.12-slim

# g++ enables the native IO tier (distributed_llms_tpu/native); the package
# falls back to pure-Python IO without it, so this is an optimization.
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY distributed_llms_tpu ./distributed_llms_tpu
RUN pip install --no-cache-dir .[hf]

# control plane / Prometheus exposition
EXPOSE 65432 9100

ENTRYPOINT ["dlt-coordinator"]
CMD ["--serve", "--metrics-port", "9100"]
