"""Test harness: force an 8-device CPU fake mesh.

The axon TPU plugin ignores the JAX_PLATFORMS env var, so we must set the
platform via jax.config *before* any backend initialization.  8 fake CPU
devices exercise the same Mesh/pjit/ppermute code paths as a TPU slice
(SURVEY §4: the reference has no distributed tests at all; this is the
strategy it was missing).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: OPT-IN ONLY (set DLT_TEST_CACHE_DIR).
#
# It was the default for one round and cut warm-run jit waits ~5x — but
# XLA:CPU *executable* serialization is not reliable for this suite's
# largest programs: two independent full-suite runs on 2026-07-31
# SEGFAULTED inside the persistent cache, one in
# compilation_cache.get_executable_and_time (deserialize; the machine-
# feature-mismatch warnings XLA prints there explicitly threaten SIGILL)
# and one in put_executable_and_time (executable.serialize()), both on the
# speculative-decoding while_loop programs with quantized-draft leaves.
# jax_persistent_cache_enable_xla_caches="none" does NOT help — it strips
# XLA-internal sub-caches from entries; the top-level executable
# serialization is the crash site.  A green-but-slower suite beats a fast
# one that segfaults at random, so every run compiles cold unless a cache
# dir is explicitly requested.  CI does NOT request one either (ci.yml
# dropped it in the same change: prefix-restored caches would also cross
# heterogeneous runner CPU generations — the exact machine-feature
# mismatch XLA's loader warns may SIGILL); this knob exists for local
# iteration on a single box at the operator's own risk.  (One knob only:
# to disable, unset DLT_TEST_CACHE_DIR.)
_cache_dir = os.environ.get("DLT_TEST_CACHE_DIR")
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none")

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image):
    coroutine tests run under asyncio.run."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_collection_modifyitems(config, items):
    """``@pytest.mark.fragile_xla_cpu`` — the SINGLE definition of the
    fresh-process isolation mechanism: XLA:CPU segfaults
    nondeterministically in backend_compile_and_load once a long-lived
    process accumulates ~300 tests of compile history (the crash follows
    whatever compiles LAST, not a specific program — see
    tests/runtime/test_isolated.py).  Marked tests skip in the main
    process and run inside test_isolated.py's fresh subprocess
    (DLT_RUN_ISOLATED=1).  Tests carrying the marker must also be listed
    in test_isolated.ISOLATED or they silently lose coverage."""
    if os.environ.get("DLT_RUN_ISOLATED") == "1":
        return
    skip = pytest.mark.skip(
        reason="compile-heavy/fragile on the long-lived XLA:CPU suite "
               "process; exercised fresh-process by "
               "tests/runtime/test_isolated.py"
    )
    for item in items:
        if "fragile_xla_cpu" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
