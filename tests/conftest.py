"""Test harness: force an 8-device CPU fake mesh.

The axon TPU plugin ignores the JAX_PLATFORMS env var, so we must set the
platform via jax.config *before* any backend initialization.  8 fake CPU
devices exercise the same Mesh/pjit/ppermute code paths as a TPU slice
(SURVEY §4: the reference has no distributed tests at all; this is the
strategy it was missing).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-bound on the 1-core
# fake mesh (~23 min cold), and XLA recompiles identical programs every
# run.  A warm cache cuts the heavy jit waits ~5x (measured 10.8s -> 1.9s
# on the pipelined train step).  Safe on one machine; set DLT_TEST_NO_CACHE=1
# to measure cold-compile behavior.  CI persists the directory via
# actions/cache.
if os.environ.get("DLT_TEST_NO_CACHE") != "1":
    _cache_dir = os.environ.get(
        "DLT_TEST_CACHE_DIR",
        os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "dlt-jax-test-cache"
        ),
    )
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # No XLA:CPU AOT results in the cache: reloading them spews bogus
    # machine-feature-mismatch warnings (XLA pseudo-features like
    # prefer-no-scatter) on every test; the jit-program cache alone gives
    # the ~5x warm-run win.
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none")

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image):
    coroutine tests run under asyncio.run."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
