"""Continuous batching on a GSPMD data/tensor-parallel mesh (VERDICT r3
next-step 5).

Done-criterion pinned here: mixed budgets through a dp x tp engine match
solo decodes token-for-token — the batcher changes scheduling, never
results, on a mesh exactly as on one device.  The KV cache shards over the
mesh ('data' on the batch axis); the scheduling state (last_tok, valid,
active, budget) is constrained replicated so the host loop would stay in
lockstep on a multi-process mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.parallel import api as api_lib
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new, eos_id=-1):
    arr = jnp.asarray([ids], jnp.int32)
    lens = jnp.asarray([len(ids)], jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, arr, lens, jax.random.key(9), max_new_tokens=n_new,
        eos_id=eos_id, pad_id=0,
    )
    toks = np.asarray(out)[0].tolist()
    if eos_id >= 0 and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
    return toks


def _mesh_batcher(cfg, params, devices8, data, model, **kw):
    pm = api_lib.make_parallel_model(
        cfg, MeshConfig(data=data, model=model),
        devices=devices8[: data * model],
    )
    return ContinuousBatcher(
        cfg, pm.shard_params(params), parallel=pm, **kw
    )


def test_mesh_mixed_budgets_match_solo(tiny, devices8):
    """dp=2 x tp=4: mixed prompt lengths and budgets, more requests than
    slots (slot reuse mid-flight) — every request matches its solo decode."""
    cfg, params = tiny
    reqs = [
        ([7, 1, 9], 6),
        ([4, 4, 4, 4, 4, 4], 12),
        ([100, 3, 5, 2], 3),
        ([9, 8, 7, 6, 5], 9),
        ([11, 12], 15),
        ([42], 8),
    ]
    b = _mesh_batcher(
        cfg, params, devices8, data=2, model=4,
        batch_slots=4, max_len=64, chunk_steps=4,
    )
    # Scheduling state must be replicated (multi-process lockstep contract)
    # while the shared cache batch axis shards over 'data'.
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    # Scheduling state lives as host numpy mirrors (identical on every
    # process of a multi-host mesh); only the cache stays on-device, with
    # its batch axis sharded over 'data'.
    assert isinstance(b.active, np.ndarray) and isinstance(b.last_tok, np.ndarray)
    assert not b.cache.k.sharding.is_fully_replicated
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"request {rid} diverged"


def test_mesh_batcher_prefix_caching(tiny, devices8):
    """Prefix-cached admission on the mesh: suffix-only prefill reuses the
    registered prefix KV; results equal the full-prompt solo decode."""
    cfg, params = tiny
    b = _mesh_batcher(
        cfg, params, devices8, data=2, model=4,
        batch_slots=2, max_len=64, chunk_steps=4,
    )
    prefix = [3, 1, 4, 1, 5]
    b.register_prefix("sys", prefix)
    suffix = [9, 2, 6]
    rid = b.submit(suffix, max_new_tokens=8, prefix="sys")
    res = b.run()
    assert res[rid] == solo(cfg, params, prefix + suffix, 8)


@pytest.mark.fragile_xla_cpu  # shared marker — tests/conftest.py
def test_mesh_batcher_penalties_match_single_device(tiny, devices8):
    """Per-request presence/frequency penalties on a dp x tp mesh: the
    [B, V] output histogram rides decode_chunk replicated (scheduling
    state), so the penalized row matches the single-device penalized
    batcher token-for-token and its unpenalized neighbor stays solo-exact."""
    cfg, params = tiny
    ids, n = [7, 1, 9], 20
    other = ([4, 4, 4, 4], 9)

    ref = ContinuousBatcher(cfg, params, batch_slots=2, max_len=96,
                            chunk_steps=4)
    r_pen = ref.submit(ids, max_new_tokens=n, presence_penalty=1.5,
                       frequency_penalty=1.5)
    r_other = ref.submit(other[0], max_new_tokens=other[1])
    ref_res = ref.run()

    b = _mesh_batcher(
        cfg, params, devices8, data=2, model=4,
        batch_slots=2, max_len=96, chunk_steps=4,
    )
    m_pen = b.submit(ids, max_new_tokens=n, presence_penalty=1.5,
                     frequency_penalty=1.5)
    m_other = b.submit(other[0], max_new_tokens=other[1])
    res = b.run()
    assert res[m_pen] == ref_res[r_pen]
    assert res[m_other] == ref_res[r_other]


def test_mesh_batcher_rejects_pipe_and_seq(tiny, devices8):
    cfg, params = tiny
    pm = api_lib.make_parallel_model(cfg, MeshConfig(pipe=2, model=4))
    with pytest.raises(ValueError, match="data/tensor-parallel"):
        ContinuousBatcher(cfg, params, parallel=pm, batch_slots=2, max_len=32)


def test_mesh_batcher_rejects_undivisible_slots(tiny, devices8):
    cfg, params = tiny
    pm = api_lib.make_parallel_model(cfg, MeshConfig(data=8))
    with pytest.raises(ValueError, match="data"):
        ContinuousBatcher(cfg, params, parallel=pm, batch_slots=6, max_len=32)


def test_engine_mesh_continuous_batcher(tiny, devices8, tmp_path):
    """The product path: InferenceEngine.from_store on a dp x tp mesh hands
    out a mesh-capable batcher (engine.continuous_batcher), and the worker's
    mixed-budget endpoint would use it rather than the grouped fallback."""
    from distributed_llms_tpu.checkpoint import store as store_lib
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg, params = tiny
    store_lib.save_shards(params, str(tmp_path), num_shards=1, model_config=cfg)
    eng = InferenceEngine.from_store(
        str(tmp_path), rt=RuntimeConfig(max_decode_steps=8),
        mesh_cfg=MeshConfig(data=2, model=4),
    )
    b = eng.continuous_batcher(batch_slots=2, max_len=64)
    assert b.pm is not None
    rid = b.submit([5, 6, 7], max_new_tokens=5)
    res = b.run()
    assert res[rid] == solo(cfg, params, [5, 6, 7], 5)
    # Slot counts that don't divide the 'data' axis round UP in the engine
    # (every caller — REPL, worker, library — must serve on any dp shape).
    assert eng.continuous_batcher(batch_slots=3, max_len=64).b == 4
