"""Config-5 (Llama-3-70B) AOT compile smoke (VERDICT r3 next-step 9).

Runs tools/aot_70b_smoke.py in a subprocess: the 16 fake devices must be
configured before JAX backend init, and this suite's conftest already pinned
an 8-device CPU backend in-process.  The smoke AOT-compiles the full 70B
serving forward (prefill + decode, int8-resident weights, pp4 x tp4) from
abstract sharded inputs — GSPMD partitioning and the per-chip memory math
are validated with zero parameter bytes allocated.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_aot_70b_smoke_compiles():
    """~40 s subprocess compile; runs in default suites (addopts does not
    filter 'slow') — the marker lets local iteration skip it with
    -m "not slow"."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "aot_70b_smoke.py"), "16"],
        capture_output=True, text=True, timeout=2400, env=env, cwd=REPO,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "AOT_70B_SMOKE OK" in r.stdout
