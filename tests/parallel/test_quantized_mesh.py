"""Quantized-resident serving ON A MESH (SURVEY §7 hard part 6, VERDICT r2
next-step 9, r3 next-step 7): mesh placement keeps QuantizedTensor leaves —
data and scale sharded under the plain weight's PartitionSpec, scale blocks
refined where a shard boundary would split a block — instead of rehydrating
to full dtype.  The GSPMD forward routes quantized contractions through the
custom_partitioning kernel wrapper whenever the kernel would run (per-shard
Pallas tiles; the bandwidth win applies to plain-TP serving), falling back
to dequantize+einsum on non-TPU backends or DLT_QUANT_MATMUL_SPMD=0.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_llms_tpu.checkpoint import quantize as quant_lib
from distributed_llms_tpu.checkpoint import store as store_lib
from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.parallel import api as api_lib
from distributed_llms_tpu.runtime.engine import InferenceEngine


def _qleaves(tree):
    return [
        x for x in jax.tree.leaves(
            tree, is_leaf=lambda x: isinstance(x, quant_lib.QuantizedTensor)
        )
        if isinstance(x, quant_lib.QuantizedTensor)
    ]


def test_scale_refinement_is_exact(devices8):
    """Sharding the blocked axis over more shards than block granularity
    allows refines scales (repeat) — dequantized values must be identical."""
    mesh = Mesh(np.array(devices8).reshape(8), ("model",))
    w = jax.random.normal(jax.random.key(0), (64, 256), jnp.float32)
    qt = quant_lib.quantize(w, bits=8, block=128)  # 2 blocks; 8 shards of 32
    placed = api_lib._place_quantized(qt, P(None, "model"), mesh, "w")
    assert placed.scale.shape[-1] == 8  # refined 128 -> 32-wide blocks
    np.testing.assert_array_equal(
        np.asarray(quant_lib.dequantize(qt)), np.asarray(quant_lib.dequantize(placed))
    )
    # data really is sharded over 'model'
    assert placed.data.sharding.spec == P(None, "model")


def test_unshardable_leaf_replicates(devices8):
    """A spec that would shard the int4 pack axis at the last dim replicates
    (loudly) instead of corrupting."""
    mesh = Mesh(np.array(devices8).reshape(8), ("model",))
    w = jax.random.normal(jax.random.key(0), (64, 256), jnp.float32)
    qt = quant_lib.quantize(w, bits=4, block=128, pack_axis=-1)  # legacy layout
    placed = api_lib._place_quantized(qt, P(None, "model"), mesh, "w")
    assert placed.data.sharding.spec == P()
    np.testing.assert_array_equal(
        np.asarray(quant_lib.dequantize(qt)), np.asarray(quant_lib.dequantize(placed))
    )


@pytest.mark.parametrize("quantization", ["int8", "int4"])
def test_tp_mesh_serves_quantized_resident(tmp_path, devices8, quantization):
    """data=2 x model=4 mesh: block weights stay quantized on the mesh and
    generation matches the single-device quantized engine token-for-token.
    model=4 over intermediate_size=176 with quant_block=32 forces scale
    refinement (per-shard 44 % 32 != 0 -> 4-wide blocks) in the real path."""
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(
        params, str(tmp_path), num_shards=2, model_config=cfg,
        quantization=quantization, quant_block=32,
    )
    rt = RuntimeConfig(max_decode_steps=6, serve_quantized=True)
    ref = InferenceEngine.from_store(str(tmp_path), rt=rt)
    eng = InferenceEngine.from_store(
        str(tmp_path), rt=rt, mesh_cfg=MeshConfig(data=2, model=4)
    )
    qleaves = _qleaves(eng.params["blocks"])
    assert qleaves, "mesh placement rehydrated the quantized tree"
    # Sharded, not replicated: at least one leaf's data spans the model axis.
    assert any(
        "model" in jax.tree_util.tree_leaves(
            [n for n in q.data.sharding.spec if n is not None]
        )
        for q in qleaves
    )
    out_ref = ref.generate_text(["hello quantized mesh"], max_new_tokens=6)
    out = eng.generate_text(["hello quantized mesh"], max_new_tokens=6)
    assert out.tokens.tolist() == out_ref.tokens.tolist()


@pytest.mark.parametrize(
    "case,wshape,wspec,xshape,xspec,k_lead,eq",
    [
        # Shapes chosen so the LOCAL shard is kernel-tileable (block=128,
        # local n a multiple of 128) — the Pallas program, not the dequant
        # fallback, is what runs per shard (asserted via the spy below).
        ("w_in N-sharded", (256, 1024), P(None, "model"), (4, 256),
         P("data", None), 1, "md,df->mf"),
        ("wq head-sharded", (256, 4, 128), P(None, "model", None), (4, 256),
         P("data", None), 1, "md,dhk->mhk"),
        ("wo K-sharded psum", (4, 128, 256), P("model", None, None),
         (4, 4, 128), P("data", "model", None), 2, "mhk,hkd->md"),
        ("x batched 3d", (256, 1024), P(None, "model"), (2, 3, 256),
         P("data", None, None), 1, "btd,df->btf"),
    ],
)
def test_spmd_kernel_wrapper_partitions(
    devices8, monkeypatch, case, wshape, wspec, xshape, xspec, k_lead, eq
):
    """DLT_QUANT_MATMUL_SPMD=1: the custom_partitioning wrapper runs the
    kernel program per shard under GSPMD (interpret mode on CPU) — N-sharded
    weights embarrassingly parallel, K-sharded wo with a psum — matching the
    dense dequant+einsum exactly.  (The block *scan* cannot take this path
    yet — custom_partitioning under lax.scan hits a JAX op_sharding
    unflattening bug — so this pins the op-level contract.)"""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from distributed_llms_tpu.checkpoint.quantize import dequantize, quantize
    from distributed_llms_tpu.ops import quant_matmul as qm

    monkeypatch.setenv("DLT_QUANT_MATMUL_SPMD", "1")
    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    qm._qmm_spmd.cache_clear()  # fresh wrapper so the spy below is seen
    kernel_calls = []
    orig = qm._quant_matmul_2d
    monkeypatch.setattr(
        qm, "_quant_matmul_2d",
        lambda *a, **kw: kernel_calls.append(1) or orig(*a, **kw),
    )
    mesh = Mesh(np.array(devices8).reshape(2, 4), ("data", "model"))
    w = jax.random.normal(jax.random.key(0), wshape, jnp.float32)
    qt = quantize(w, bits=8, block=128)
    sharded = type(qt)(
        data=jax.device_put(qt.data, NamedSharding(mesh, wspec)),
        scale=jax.device_put(qt.scale, NamedSharding(mesh, wspec)),
        bits=qt.bits, orig_shape=qt.orig_shape, pack_axis=qt.pack_axis,
    )
    x = jax.device_put(
        jax.random.normal(jax.random.key(1), xshape, jnp.float32),
        NamedSharding(mesh, xspec),
    )
    token = qm._SPMD_FALLBACK.set(True)
    try:
        f = jax.jit(lambda x_, d_, s_: qm.quant_contract(
            x_,
            type(qt)(data=d_, scale=s_, bits=qt.bits,
                     orig_shape=qt.orig_shape, pack_axis=qt.pack_axis),
            k_lead, eq,
        ))
        y = f(x, sharded.data, sharded.scale)
    finally:
        qm._SPMD_FALLBACK.reset(token)
    assert kernel_calls, "Pallas kernel program was not run under the wrapper"
    ref = jnp.einsum(eq, x, dequantize(qt, x.dtype))
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("stacked_xs", [False, True])
def test_spmd_kernel_wrapper_under_scan(devices8, monkeypatch, stacked_xs):
    """The wrapper compiles and matches the dense reference INSIDE a
    ``lax.scan`` — both with scan-invariant (closed-over) weights, the shape
    of the decode loop, and with stacked weights scanned as xs, the shape of
    the layer loop.  Earlier JAX releases failed here (op_sharding superdim
    KeyError — the round-3 reason GSPMD serving was forced onto the
    dequant+einsum fallback); this pins the fix the default path now relies
    on.  If it regresses after a JAX upgrade, set DLT_QUANT_MATMUL_SPMD=0."""
    from jax.sharding import NamedSharding

    from distributed_llms_tpu.checkpoint.quantize import dequantize, quantize
    from distributed_llms_tpu.ops import quant_matmul as qm

    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    monkeypatch.delenv("DLT_QUANT_MATMUL_SPMD", raising=False)  # auto
    qm._qmm_spmd.cache_clear()
    kernel_calls = []
    orig = qm._quant_matmul_2d
    monkeypatch.setattr(
        qm, "_quant_matmul_2d",
        lambda *a, **kw: kernel_calls.append(1) or orig(*a, **kw),
    )
    mesh = Mesh(np.array(devices8).reshape(2, 4), ("data", "model"))
    # Local N per 'model' shard must stay kernel-tileable (>=128, block 128)
    # or the wrapper's per-shard dispatch takes its internal dequant branch
    # and the spy below would prove nothing.
    L, d = 3, 1024
    w = jax.random.normal(jax.random.key(0), (L, d, d), jnp.float32) * d**-0.5
    qt = quant_lib.quantize(w, bits=8, block=128)
    wspec = P(None, None, "model")
    data = jax.device_put(qt.data, NamedSharding(mesh, wspec))
    scale = jax.device_put(qt.scale, NamedSharding(mesh, wspec))
    x = jax.device_put(
        jax.random.normal(jax.random.key(1), (4, d), jnp.float32),
        NamedSharding(mesh, P("data", None)),
    )

    def layer(c, d_, s_):
        q = type(qt)(data=d_, scale=s_, bits=qt.bits,
                     orig_shape=(d, d), pack_axis=qt.pack_axis)
        return qm.quant_contract(c, q, 1, "md,df->mf")

    if stacked_xs:
        def f(x_, d_, s_):
            return jax.lax.scan(
                lambda c, xs: (layer(c, *xs), None), x_, (d_, s_)
            )[0]
    else:
        def f(x_, d_, s_):
            def body(c, _):
                return layer(c, d_[0], s_[0]), None
            return jax.lax.scan(body, x_, None, length=L)[0]

    token = qm._SPMD_FALLBACK.set(True)
    try:
        y = jax.jit(f)(x, data, scale)
    finally:
        qm._SPMD_FALLBACK.reset(token)
    assert kernel_calls, "kernel program did not run under the scan"
    ref = np.asarray(x)
    wd = np.asarray(dequantize(qt, jnp.float32))
    if stacked_xs:
        for i in range(L):
            ref = ref @ wd[i]
    else:
        for _ in range(L):
            ref = ref @ wd[0]
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)


def test_tp_mesh_quantized_kernel_active(tmp_path, devices8, monkeypatch):
    """VERDICT r3 next-step 7 done-criterion: plain-TP (GSPMD) quantized
    serving dispatches the fused kernel program (spy on _quant_matmul_2d —
    the Pallas program itself, wrapped by custom_partitioning) under the
    layer scan AND the decode scan, and the tokens match fallback serving
    exactly."""
    from distributed_llms_tpu.ops import quant_matmul as qm

    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, intermediate_size=256,
        num_heads=2, num_kv_heads=2,  # hd=128: local TP shards stay tileable
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_dir = str(tmp_path / "s")
    store_lib.save_shards(
        params, store_dir, num_shards=1, model_config=cfg, quantization="int8",
        quant_block=128,
    )
    rt = RuntimeConfig(max_decode_steps=4, serve_quantized=True)
    monkeypatch.setenv("DLT_QUANT_MATMUL", "fallback")
    ref = InferenceEngine.from_store(store_dir, rt=rt)
    out_ref = ref.generate_text(["kernel under gspmd"], max_new_tokens=4)

    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    monkeypatch.delenv("DLT_QUANT_MATMUL_SPMD", raising=False)  # auto: on
    qm._qmm_spmd.cache_clear()
    kernel_calls = []
    orig = qm._quant_matmul_2d
    monkeypatch.setattr(
        qm, "_quant_matmul_2d",
        lambda *a, **kw: kernel_calls.append(1) or orig(*a, **kw),
    )
    eng = InferenceEngine.from_store(
        store_dir, rt=rt, mesh_cfg=MeshConfig(data=4, model=2)
    )
    assert _qleaves(eng.params["blocks"])
    out = eng.generate_text(["kernel under gspmd"], max_new_tokens=4)
    assert kernel_calls, "fused kernel was not dispatched under GSPMD serving"
    assert out.tokens.tolist() == out_ref.tokens.tolist()


def test_tp_mesh_quantized_spmd_kill_switch(tmp_path, devices8, monkeypatch):
    """DLT_QUANT_MATMUL_SPMD=0 restores the round-3 dequant+einsum fallback
    under GSPMD (the hardware-day escape hatch) — same tokens, no kernel."""
    from distributed_llms_tpu.ops import quant_matmul as qm

    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_dir = str(tmp_path / "s")
    store_lib.save_shards(
        params, store_dir, num_shards=1, model_config=cfg, quantization="int8",
        quant_block=32,
    )
    rt = RuntimeConfig(max_decode_steps=4, serve_quantized=True)
    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    monkeypatch.setenv("DLT_QUANT_MATMUL_SPMD", "0")
    kernel_calls = []
    orig = qm._quant_matmul_2d
    monkeypatch.setattr(
        qm, "_quant_matmul_2d",
        lambda *a, **kw: kernel_calls.append(1) or orig(*a, **kw),
    )
    ref = InferenceEngine.from_store(store_dir, rt=rt)
    out_ref = ref.generate_text(["kill switch"], max_new_tokens=4)
    n_single = len(kernel_calls)  # single-device engine: kernel allowed
    eng = InferenceEngine.from_store(
        store_dir, rt=rt, mesh_cfg=MeshConfig(data=2, model=4)
    )
    out = eng.generate_text(["kill switch"], max_new_tokens=4)
    assert len(kernel_calls) == n_single, "kill switch did not disable wrapper"
    assert out.tokens.tolist() == out_ref.tokens.tolist()


@pytest.mark.parametrize("quantization", ["int8"])
def test_pipelined_mesh_serves_quantized_resident(tmp_path, devices8, quantization):
    """pipe=2 x model=2 (+data=2) mesh: staged quantized blocks flow through
    the shard_map pipeline and the wavefront decode, matching single-device."""
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(
        params, str(tmp_path), num_shards=2, model_config=cfg,
        quantization=quantization, quant_block=32,
    )
    rt = RuntimeConfig(max_decode_steps=6, serve_quantized=True, microbatches=2)
    ref = InferenceEngine.from_store(str(tmp_path), rt=rt)
    eng = InferenceEngine.from_store(
        str(tmp_path), rt=rt, mesh_cfg=MeshConfig(data=2, pipe=2, model=2)
    )
    assert _qleaves(eng.params["blocks"]), "pipeline staging rehydrated"
    prompts = ["hello quantized pipeline", "second row"]
    out_ref = ref.generate_text(prompts, max_new_tokens=6)
    out = eng.generate_text(prompts, max_new_tokens=6)
    assert out.tokens.tolist() == out_ref.tokens.tolist()


def test_pipelined_mesh_kernel_inside_shard_map(tmp_path, devices8, monkeypatch):
    """Unlike the GSPMD path (custom_partitioning + scan is blocked by a JAX
    bug), the PIPELINED mesh runs blocks inside shard_map where operands are
    already local — the fused kernel dispatch (_qmm_flat) runs under the
    layer scan there.  On CPU the Pallas interpreter loses vma, so the
    numerically-identical flat-dequant branch executes (same limitation and
    same answer as ops/flash.py's interpret path); on real TPU the kernel
    lowers with vma declared.  A spy proves the kernel dispatch path (not
    the einsum fallback) ran; tokens must match fallback serving exactly."""
    from distributed_llms_tpu.ops import quant_matmul as qm

    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, intermediate_size=256,
        num_heads=2, num_kv_heads=2,  # hd = 128
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_dir = str(tmp_path / "s")
    store_lib.save_shards(
        params, store_dir, num_shards=1, model_config=cfg, quantization="int8",
        quant_block=128,
    )
    rt = RuntimeConfig(max_decode_steps=4, serve_quantized=True, microbatches=2)
    monkeypatch.setenv("DLT_QUANT_MATMUL", "fallback")
    ref = InferenceEngine.from_store(store_dir, rt=rt)
    out_ref = ref.generate_text(["kernel in pipeline"], max_new_tokens=4)

    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    dispatch_calls = []
    orig = qm._qmm_flat
    monkeypatch.setattr(
        qm, "_qmm_flat",
        lambda *a, **kw: dispatch_calls.append(1) or orig(*a, **kw),
    )
    eng = InferenceEngine.from_store(
        store_dir, rt=rt, mesh_cfg=MeshConfig(pipe=2, model=4)
    )
    assert _qleaves(eng.params["blocks"])
    out = eng.generate_text(["kernel in pipeline"], max_new_tokens=4)
    assert dispatch_calls, "kernel dispatch did not run inside the pipeline"
    assert out.tokens.tolist() == out_ref.tokens.tolist()
