"""Fake-mesh (8 CPU devices) integration tests: TP/DP GSPMD forward,
pipeline equivalence + gradients, combined dp*pp*tp generation, train step.
This is the multi-device test strategy the reference lacked entirely
(SURVEY §4: "How multi-node is tested without a cluster: it isn't")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig
from distributed_llms_tpu.core.mesh import build_mesh
from distributed_llms_tpu.models import model, presets
from distributed_llms_tpu.parallel import pipeline as pl
from distributed_llms_tpu.parallel import specs as specs_lib
from distributed_llms_tpu.parallel import stages
from distributed_llms_tpu.parallel.api import make_parallel_model
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.tokenizer import pad_batch


@pytest.fixture(scope="module")
def gpt2():
    cfg = presets.get_preset("gpt2-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    return cfg, params


def test_stage_partition_contiguous():
    sizes = [10, 1, 1, 10, 1, 1, 10, 2]
    a = stages.partition_contiguous(sizes, 3)
    assert a.num_stages == 3
    assert a.boundaries[0] == 0 and a.boundaries[-1] == len(sizes)
    costs = [sum(sizes[a.boundaries[i]:a.boundaries[i + 1]]) for i in range(3)]
    assert max(costs) == 12  # optimal: [10,1,1] [10,1,1] [10,2]
    assert a.stage_of(0) == 0 and a.stage_of(7) == 2


def test_pack_greedy_balances():
    packing = stages.pack_greedy({"a": 8, "b": 7, "c": 4, "d": 3}, 2)
    bins = {}
    for k, b in packing.items():
        bins.setdefault(b, 0)
        bins[b] += {"a": 8, "b": 7, "c": 4, "d": 3}[k]
    assert sorted(bins.values()) == [11, 11]


def test_tp_dp_forward_matches_single_device(gpt2, devices8):
    cfg, params = gpt2
    toks = jax.random.randint(jax.random.key(1), (4, 6), 0, cfg.vocab_size, dtype=jnp.int32)
    ref, _ = model.forward(params, cfg, toks)

    mesh = build_mesh(MeshConfig(data=2, model=4))
    sharded = specs_lib.shard_params(params, cfg, mesh)
    out, _ = jax.jit(lambda p, t: model.forward(p, cfg, t))(sharded, toks)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-4, atol=1e-4)


def test_pipeline_matches_plain_blocks(gpt2, devices8):
    cfg, params = gpt2
    mesh = build_mesh(MeshConfig(data=1, pipe=4, model=2))
    B, T = 4, 6
    toks = jax.random.randint(jax.random.key(1), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    x = model.embed(params, cfg, toks, positions)
    y_ref, _, _ = model.run_blocks(x, params["blocks"], cfg, positions, None, None, None)
    staged = pl.split_stages(params["blocks"], 4)
    y_pipe, _ = pl.pipeline_blocks(mesh, cfg, staged, x, positions, num_microbatches=2)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_pipe), rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match(gpt2, devices8):
    cfg, params = gpt2
    mesh = build_mesh(MeshConfig(data=1, pipe=2, model=1, seq=4))
    B, T = 4, 6
    toks = jax.random.randint(jax.random.key(1), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    x = model.embed(params, cfg, toks, positions)

    def loss_plain(blocks):
        y, _, _ = model.run_blocks(x, blocks, cfg, positions, None, None, None)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    def loss_pipe(staged):
        y, _ = pl.pipeline_blocks(mesh, cfg, staged, x, positions, num_microbatches=2)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    g_plain = jax.grad(loss_plain)(params["blocks"])
    g_pipe = pl.merge_stages(jax.grad(loss_pipe)(pl.split_stages(params["blocks"], 2)))
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5)


def test_dp_pp_tp_generation_matches_single_device(gpt2, devices8):
    cfg, params = gpt2
    rows = [[7, 1, 9], [4, 4, 4, 4, 4, 4], [100, 3, 5, 2], [9, 8, 7, 6, 5]]
    arr, lens = pad_batch(rows, pad_id=0)
    ref = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4,
    )
    pm = make_parallel_model(cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=2)
    sharded = pm.shard_params(params)
    out = gen_lib.generate_tokens(
        sharded, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4, forward_fn=pm.as_forward_fn(), make_cache=pm.as_make_cache(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


def test_neox_blocks_shard_and_generate(devices8):
    """GPT-NeoX layout (parallel residual + partial rotary + biasful
    LayerNorm blocks, no position table): dp x pp x tp generation matches
    the single device exactly."""
    from distributed_llms_tpu.models import presets

    cfg = presets.get_preset("neox-tiny", vocab_size=512, num_layers=4)
    params = model.init_params(jax.random.key(5), cfg)
    assert "wpe" not in params["embed"]
    rows = [[7, 1, 9], [4, 4, 4, 4], [100, 3, 5, 2], [9, 8]]
    arr, lens = pad_batch(rows, pad_id=0)
    ref = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4,
    )
    pm = make_parallel_model(cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=2)
    sharded = pm.shard_params(params)
    out = gen_lib.generate_tokens(
        sharded, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4, forward_fn=pm.as_forward_fn(), make_cache=pm.as_make_cache(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


def test_qkv_bias_blocks_shard_and_generate(devices8):
    """Qwen2-style llama blocks (cfg.qkv_bias): the bias leaves shard with
    their head axes over 'model' and dp x pp x tp generation matches the
    single device exactly."""
    import dataclasses

    from distributed_llms_tpu.models import presets

    cfg = dataclasses.replace(
        presets.get_preset("llama-tiny", vocab_size=512), qkv_bias=True
    )
    params = model.init_params(jax.random.key(3), cfg)
    assert "bq" in params["blocks"]["attn"]
    rows = [[7, 1, 9], [4, 4, 4, 4], [100, 3, 5, 2], [9, 8]]
    arr, lens = pad_batch(rows, pad_id=0)
    ref = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4,
    )
    pm = make_parallel_model(cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=2)
    sharded = pm.shard_params(params)
    out = gen_lib.generate_tokens(
        sharded, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=4, forward_fn=pm.as_forward_fn(), make_cache=pm.as_make_cache(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_fused_wavefront_decode_matches_single_device(gpt2, devices8, microbatches):
    """The fused decode schedule (pipeline never drains between tokens,
    max(M,P) ticks per token round vs M+P-1) is numerically identical to the
    single-device loop, for M below/at/above P."""
    cfg, params = gpt2
    rows = [[7, 1, 9], [4, 4, 4, 4, 4, 4], [100, 3, 5, 2], [9, 8, 7, 6, 5]]
    arr, lens = pad_batch(rows, pad_id=0)
    ref = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=5,
    )
    pm = make_parallel_model(
        cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=microbatches
    )
    sharded = pm.shard_params(params)
    out = gen_lib.generate_tokens(
        sharded, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=5, forward_fn=pm.as_forward_fn(),
        make_cache=pm.as_make_cache(), decode_fn=pm.as_decode_fn(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


def test_fused_decode_eos_freezing_matches(gpt2, devices8):
    """EOS-aware freezing (rows stop and pad-fill) through the wavefront."""
    cfg, params = gpt2
    rows = [[7, 1, 9], [4, 4, 4, 4], [100, 3, 5, 2], [9, 8, 7, 6, 5]]
    arr, lens = pad_batch(rows, pad_id=0)
    ref = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=6,
    )
    eos = int(np.asarray(ref)[0, 1])  # a token greedy decoding actually emits
    kw = dict(max_new_tokens=6, eos_id=eos, pad_id=0)
    ref_e = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0), **kw
    )
    assert (np.asarray(ref_e) == eos).any()
    pm = make_parallel_model(cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=2)
    out_e = gen_lib.generate_tokens(
        pm.shard_params(params), cfg, jnp.asarray(arr), jnp.asarray(lens),
        jax.random.key(0), forward_fn=pm.as_forward_fn(),
        make_cache=pm.as_make_cache(), decode_fn=pm.as_decode_fn(), **kw
    )
    assert np.asarray(ref_e).tolist() == np.asarray(out_e).tolist()


def test_train_step_decreases_loss(devices8):
    from distributed_llms_tpu.runtime import train

    cfg = presets.get_preset("gpt2-tiny", num_layers=2)
    params = model.init_params(jax.random.key(0), cfg)
    pm = make_parallel_model(cfg, MeshConfig(data=2, pipe=2, model=2), num_microbatches=2)
    params = pm.shard_params(params)
    trainer = train.Trainer(cfg, train.default_optimizer(1e-2), parallel=pm)
    opt_state = trainer.init(params)
    step = trainer.make_step()
    tokens = jax.random.randint(jax.random.key(2), (4, 9), 0, cfg.vocab_size, dtype=jnp.int32)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, None)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_seq_parallel_forward_matches_single_device(gpt2, devices8):
    """Sequence parallelism: ring attention over 'seq' == dense attention."""
    cfg, params = gpt2
    B, T = 2, 16
    toks = jax.random.randint(jax.random.key(3), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    ref, _ = model.forward(params, cfg, toks)

    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4))
    sharded = pm.shard_params(params)
    out, cache = pm.forward(sharded, toks)
    assert cache is None
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-4, atol=1e-4)


def test_seq_parallel_train_step(devices8):
    """Training differentiates through the ppermute ring."""
    from distributed_llms_tpu.runtime import train

    cfg = presets.get_preset("gpt2-tiny", num_layers=2)
    params = model.init_params(jax.random.key(0), cfg)
    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4))
    params = pm.shard_params(params)
    trainer = train.Trainer(cfg, train.default_optimizer(1e-2), parallel=pm)
    opt_state = trainer.init(params)
    step = trainer.make_step()
    tokens = jax.random.randint(jax.random.key(2), (4, 17), 0, cfg.vocab_size, dtype=jnp.int32)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, None)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_seq_parallel_cached_generation_matches(gpt2, devices8):
    """Long-context decode (SURVEY §5.7): prompt KV sharded over 'seq' (two-
    region cache), decode merges partial softmax stats with one psum — tokens
    must match the single-device loop exactly."""
    cfg, params = gpt2
    B, T, N = 2, 16, 6
    prompt = jax.random.randint(jax.random.key(5), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    lens = jnp.array([16, 11], jnp.int32)
    ref = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(0), max_new_tokens=N
    )
    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4))
    out = gen_lib.generate_tokens(
        pm.shard_params(params), cfg, prompt, lens, jax.random.key(0),
        max_new_tokens=N, forward_fn=pm.as_forward_fn(),
        make_cache=pm.as_make_cache(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


def test_seq_parallel_ulysses_cached_generation_matches(gpt2, devices8):
    """Same decode path behind a Ulysses prefill, composed with TP."""
    import dataclasses

    cfg, params = gpt2
    cfg_u = dataclasses.replace(cfg, attn_impl="ulysses")
    B, T, N = 2, 16, 5
    prompt = jax.random.randint(jax.random.key(6), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    lens = jnp.array([16, 9], jnp.int32)
    ref = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(0), max_new_tokens=N
    )
    pm = make_parallel_model(cfg_u, MeshConfig(data=2, seq=2, model=2))
    out = gen_lib.generate_tokens(
        pm.shard_params(params), cfg_u, prompt, lens, jax.random.key(0),
        max_new_tokens=N, forward_fn=pm.as_forward_fn(),
        make_cache=pm.as_make_cache(),
    )
    assert np.asarray(ref).tolist() == np.asarray(out).tolist()


def test_seq_parallel_cache_requires_prompt_len(gpt2, devices8):
    """The session path (no prompt_len) fails loudly, not silently densely."""
    cfg, _ = gpt2
    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4))
    with pytest.raises(ValueError, match="prompt_len"):
        pm.init_cache(batch=2, max_len=32)


def test_seq_plus_pipe_rejected(devices8):
    cfg = presets.get_preset("gpt2-tiny")
    with pytest.raises(ValueError, match="seq"):
        make_parallel_model(cfg, MeshConfig(pipe=2, seq=2, data=2))


def test_seq_parallel_falls_back_on_custom_mask(gpt2, devices8):
    """A caller-supplied attn_mask must not be dropped by the ring path."""
    cfg, params = gpt2
    B, T = 2, 16
    toks = jax.random.randint(jax.random.key(3), (B, T), 0, cfg.vocab_size, dtype=jnp.int32)
    # Mask out the first 4 keys entirely (plus causal).
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    from distributed_llms_tpu.models import layers
    k_valid = jnp.broadcast_to(jnp.arange(T) >= 4, (B, T))
    mask = layers.causal_mask(positions, positions, k_valid)
    ref, _ = model.forward(params, cfg, toks, attn_mask=mask)
    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4))
    out, _ = pm.forward(pm.shard_params(params), toks, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-4, atol=1e-4)
