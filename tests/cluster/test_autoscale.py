"""Elastic fleet autoscaling (cluster/autoscale.py + the fleet's
add_replica/remove_replica) — ROADMAP item 4's elastic half.

Two layers:

1. DECISIONS (no servers): the control loop against a stub fleet —
   load-signal arithmetic, hysteresis streaks, the cooldown quiet
   period, min/max clamps, least-committed victim choice, and the
   fleet.scale_up / fleet.scale_down drill semantics (a failed or
   vetoed action degrades cleanly and retries after the cooldown).
2. CHAOS ACCEPTANCE (tiny model, live fleet + router): a bursty
   3-tenant storm drives at least one scale-UP and one graceful
   scale-DOWN mid-storm, with one injected ``fleet.scale_up`` failure
   absorbed cleanly before the retry succeeds; every completed request
   is byte-exact vs an unfaulted FIXED-fleet reference, every shed is a
   structured 429/503 with (per-tenant) Retry-After, and every
   surviving replica's page pool audits clean.
"""

import asyncio
import json

import pytest

import jax

from distributed_llms_tpu.cluster.autoscale import Autoscaler
from distributed_llms_tpu.cluster.fleet import ReplicaFleet
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.router import ReplicaRouter
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

PAGE = 16


# -- decision logic against a stub fleet (no servers) ------------------------


class _StubHandle:
    def __init__(self, name, committed=0, inflight=0, state="healthy"):
        self.name = name
        self.committed_tokens = committed
        self.inflight = set(range(inflight))
        self.state = state

    def routable(self, now):
        return self.state == "healthy"


class _StubFleet:
    """The surface Autoscaler consumes: handles + add/remove."""

    def __init__(self, *handles):
        self.replicas = list(handles)
        self.added = 0
        self.removed: list[str] = []
        self.fail_adds = 0  # > 0: the next add_replica raises (real
        #                     provision failure, not a drill)

    async def add_replica(self, factory=None, name=None):
        if self.fail_adds > 0:
            self.fail_adds -= 1
            raise RuntimeError("provision failed")
        self.added += 1
        h = _StubHandle(name or f"r{len(self.replicas)}")
        self.replicas.append(h)
        return h

    async def remove_replica(self, name, drain_timeout_s=30.0):
        self.removed.append(name)
        self.replicas = [h for h in self.replicas if h.name != name]


def _scaler(fleet, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_load", 0.8)
    kw.setdefault("down_load", 0.2)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("replica_capacity_tokens", 100)
    return Autoscaler(fleet, **kw)


def _run(coro):
    return asyncio.run(coro)


async def _ticks(sc, n, settle=0.0):
    sc._loop = asyncio.get_running_loop()
    out = []
    for _ in range(n):
        out.append(await sc.tick())
        if settle:
            await asyncio.sleep(settle)
    return out


def test_signals_and_load_arithmetic():
    async def fn():
        fleet = _StubFleet(_StubHandle("a", committed=60, inflight=2),
                           _StubHandle("b", committed=20, inflight=1),
                           _StubHandle("dead", state="dead"))
        sc = _scaler(fleet)
        sc._loop = asyncio.get_running_loop()
        sig = sc.signals()
        assert sig["replicas"] == 2          # dead handles don't count
        assert sig["routable"] == 2
        assert sig["committed_tokens"] == 80
        assert sig["queue_depth"] == 3
        assert sig["load"] == pytest.approx(80 / 200)
        assert METRICS.get_gauge("autoscale.load") == pytest.approx(0.4)
        assert METRICS.get_gauge("autoscale.replicas") == 2

    _run(fn())


def test_hysteresis_then_scale_up_and_max_clamp():
    async def fn():
        fleet = _StubFleet(_StubHandle("a", committed=95))
        sc = _scaler(fleet, max_replicas=2, hysteresis=3)
        # Two hot ticks: streak building, no action yet (noise filter).
        assert await _ticks(sc, 2) == [None, None]
        assert fleet.added == 0
        # Third consecutive hot tick: scale up.
        assert (await _ticks(sc, 1)) == ["up"]
        assert fleet.added == 1 and len(fleet.replicas) == 2
        # At max_replicas: hot forever, never past the ceiling.  (The
        # new stub replica holds no tokens, so load halves — pin it hot.)
        fleet.replicas[1].committed_tokens = 95
        assert all(a is None for a in await _ticks(sc, 5))
        assert len(fleet.replicas) == 2

    _run(fn())


def test_scale_down_graceful_least_committed_and_min_clamp():
    async def fn():
        fleet = _StubFleet(_StubHandle("busy", committed=30, inflight=2),
                           _StubHandle("idle", committed=1))
        sc = _scaler(fleet, hysteresis=2)
        acts = await _ticks(sc, 2)
        assert acts == [None, "down"]
        assert fleet.removed == ["idle"]     # least committed drains away
        # At the floor: cold forever, never below min_replicas.
        assert all(a is None for a in await _ticks(sc, 5))
        assert len(fleet.replicas) == 1

    _run(fn())


def test_cooldown_spaces_actions():
    async def fn():
        fleet = _StubFleet(_StubHandle("a", committed=95))
        sc = _scaler(fleet, max_replicas=4, hysteresis=1, cooldown_s=0.2)
        assert (await _ticks(sc, 1))[0] == "up"
        fleet.replicas[-1].committed_tokens = 95  # still hot
        # Inside the cooldown: hot ticks take no action.
        assert all(a is None for a in await _ticks(sc, 3))
        assert fleet.added == 1
        await asyncio.sleep(0.25)
        assert (await _ticks(sc, 1))[0] == "up"  # cooldown lapsed

    _run(fn())


def test_scale_up_drill_and_real_failure_degrade_cleanly():
    """An injected fleet.scale_up raise AND a real provision failure
    both: count autoscale.scale_failures, leave the fleet unchanged,
    and retry after the cooldown — the controller never dies."""
    async def fn():
        plane = FaultPlane.parse("fleet.scale_up:raise@1")
        fleet = _StubFleet(_StubHandle("a", committed=95))
        sc = _scaler(fleet, max_replicas=3, hysteresis=1, cooldown_s=0.05,
                     faults=plane)
        f0 = METRICS.get_counter("autoscale.scale_failures")
        assert (await _ticks(sc, 1))[0] is None  # drill ate attempt 1
        assert fleet.added == 0 and plane.rules[0].fired == 1
        assert METRICS.get_counter("autoscale.scale_failures") == f0 + 1
        await asyncio.sleep(0.06)
        # Real provision failure on attempt 2: same clean degrade.
        fleet.fail_adds = 1
        assert (await _ticks(sc, 1))[0] is None
        assert METRICS.get_counter("autoscale.scale_failures") == f0 + 2
        assert len(fleet.replicas) == 1
        await asyncio.sleep(0.06)
        # Attempt 3 lands.
        assert (await _ticks(sc, 1))[0] == "up"
        assert fleet.added == 1

    _run(fn())


def test_scale_down_veto_drill():
    async def fn():
        plane = FaultPlane.parse("fleet.scale_down:drop@1")
        fleet = _StubFleet(_StubHandle("a"), _StubHandle("b"))
        sc = _scaler(fleet, hysteresis=1, cooldown_s=0.0, faults=plane)
        assert (await _ticks(sc, 1))[0] is None  # vetoed
        assert len(fleet.replicas) == 2
        assert (await _ticks(sc, 1))[0] == "down"  # next attempt drains
        assert len(fleet.replicas) == 1

    _run(fn())


def test_scale_down_raise_drill_counts_failure_and_retries():
    """An injected fleet.scale_down raise counts autoscale.scale_failures,
    leaves the fleet intact, and the next cold tick drains normally."""
    async def fn():
        plane = FaultPlane.parse("fleet.scale_down:raise@1")
        fleet = _StubFleet(_StubHandle("a"), _StubHandle("b"))
        sc = _scaler(fleet, hysteresis=1, cooldown_s=0.0, faults=plane)
        f0 = METRICS.get_counter("autoscale.scale_failures")
        assert (await _ticks(sc, 1))[0] is None  # drill ate the drain
        assert len(fleet.replicas) == 2
        assert plane.rules[0].fired == 1
        assert METRICS.get_counter("autoscale.scale_failures") == f0 + 1
        assert (await _ticks(sc, 1))[0] == "down"  # retry drains
        assert len(fleet.replicas) == 1

    _run(fn())


def test_autoscaler_validation():
    fleet = _StubFleet(_StubHandle("a"))
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(fleet, min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        Autoscaler(fleet, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="down_load"):
        Autoscaler(fleet, up_load=0.5, down_load=0.6)
    with pytest.raises(ValueError, match="hysteresis"):
        Autoscaler(fleet, hysteresis=0)


# -- chaos acceptance: live elastic fleet under a 3-tenant storm -------------


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _replica_batcher(tiny, faults=None):
    cfg, params = tiny
    tok = ByteTokenizer()
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=2, max_len=96, chunk_steps=4,
        paged_pages=8, page_size=PAGE, prefix_cache=True,
        tenant_weights="gold:4,agg:1,free:1", tenant_max_rows=2,
    )


@pytest.fixture(scope="module")
def warmed(tiny):
    """Warm the process-wide jit cache with the replicas' program shapes
    so scaled-up replicas probe healthy in milliseconds, not compile
    time (the test_router pattern)."""
    b = _replica_batcher(tiny)
    for prompt in ("warm short", "a much longer warming prompt xxxx",
                   "warm short"):
        b.submit(prompt, max_new_tokens=4)
        b.run()
    return tiny


def _server_factory(tiny):
    def make_server():
        return InferenceServer(
            _replica_batcher(tiny), model_name="tiny", host="127.0.0.1",
            port=0, batcher_factory=lambda: _replica_batcher(tiny),
            watchdog_timeout_s=5.0,
            tenant_weights={"gold": 4.0, "agg": 1.0, "free": 1.0},
            # agg's allowance: 1 x 30 tok/s x 2 s = 60 tokens per window
            # — the storm offers it ~5x that, so real per-tenant sheds
            # happen mid-storm.
            tenant_quota_tps=30.0, tenant_rate_window_s=2.0,
        )

    return make_server


async def _request(host, port, body, tenant=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode()
    hdr = f"X-Tenant: {tenant}\r\n" if tenant else ""
    writer.write(
        f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n{hdr}"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    raw = await reader.read()
    writer.close()
    return status, headers, json.loads(raw) if raw.strip() else {}


def _expected_texts(tiny, reqs):
    """Unfaulted FIXED-fleet reference: one roomy batcher serves every
    prompt solo — exactness at temp 0 is batching-, replica-, and
    fleet-size-invariant, so every storm completion must match these
    bytes whatever replica (original or scaled-up) served it."""
    cfg, params = tiny
    tok = ByteTokenizer()
    b = ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=4, max_len=96, chunk_steps=4, paged_pages=40,
        page_size=PAGE,
    )
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return {p: tok.decode(res[rid]) for rid, (p, n) in zip(rids, reqs)}


def test_elastic_fleet_chaos_storm(warmed):
    """THE acceptance test (ISSUE 15): bursty 3-tenant storm against a
    min=1/max=2 elastic fleet.  One injected fleet.scale_up failure
    degrades cleanly, the retry scales up mid-storm, the tail's idle
    ticks drain a replica away gracefully while a trickle still serves;
    completions byte-exact, sheds structured, pools audit clean."""
    tiny = warmed
    gold = [(f"gold tenant request {i} !!", 8) for i in range(3)]
    free = [(f"free rider number {i}", 6) for i in range(3)]
    agg = [(f"aggressor flood item {i} padded out to length", 10)
           for i in range(8)]
    wants = _expected_texts(tiny, gold + free + agg)
    plane = FaultPlane.parse("fleet.scale_up:raise@1")

    async def driver():
        fleet = ReplicaFleet([_server_factory(tiny)],
                             probe_interval_s=0.05, probe_timeout_s=2.0,
                             faults=plane)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=ByteTokenizer(), page_size=PAGE,
                               faults=plane)
        await fleet.start()
        host, port = await router.start()
        scaler = Autoscaler(
            fleet, min_replicas=1, max_replicas=2, up_load=0.15,
            down_load=0.05, hysteresis=2, cooldown_s=0.05,
            drain_timeout_s=20.0, replica_capacity_tokens=112,
            faults=plane,
        )
        scaler._loop = asyncio.get_running_loop()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)
            results: dict[str, tuple[int, dict, dict]] = {}

            async def one(prompt, n, tenant):
                results[prompt] = await _request(
                    host, port, {"prompt": prompt, "max_tokens": n},
                    tenant=tenant,
                )

            # Storm: gold + free pace out; the aggressor BURSTS (its
            # offered token mass ~5x its quota window).
            tasks = []

            async def storm():
                for i, (p, n) in enumerate(agg):
                    tasks.append(asyncio.ensure_future(one(p, n, "agg")))
                    await asyncio.sleep(0.03)
                for (p, n), (q, m) in zip(gold, free):
                    tasks.append(asyncio.ensure_future(one(p, n, "gold")))
                    tasks.append(asyncio.ensure_future(one(q, m, "free")))
                    await asyncio.sleep(0.05)

            storm_task = asyncio.ensure_future(storm())
            # Mid-storm control ticks: committed-token load crosses
            # up_load -> hysteresis x2 -> attempt 1 is EATEN by the
            # injected fleet.scale_up raise (clean degrade), the retry
            # after the cooldown scales up for real.
            f0 = METRICS.get_counter("autoscale.scale_failures")
            scaled_up = False
            for _ in range(300):
                await asyncio.sleep(0.02)
                await scaler.tick()
                if len(fleet.replicas) == 2:
                    scaled_up = True
                    break
            assert scaled_up, "the storm never drove a scale-up"
            assert plane.rules[0].fired == 1, "the drill never fired"
            assert METRICS.get_counter(
                "autoscale.scale_failures") >= f0 + 1
            await storm_task
            await asyncio.gather(*tasks)
            # Scale-down mid-traffic: a trickle keeps the fleet serving
            # while the idle ticks drain one replica away GRACEFULLY.
            trickle = [(f"tail trickle {i}", 4) for i in range(3)]
            twants = _expected_texts(tiny, trickle)

            async def tail():
                for p, n in trickle:
                    await one(p, n, "gold")
                    await asyncio.sleep(0.1)

            tail_task = asyncio.ensure_future(tail())
            scaled_down = False
            for _ in range(400):
                await asyncio.sleep(0.02)
                await scaler.tick()
                if len(fleet.replicas) == 1:
                    scaled_down = True
                    break
            assert scaled_down, "the idle tail never drove a scale-down"
            assert METRICS.get_counter("autoscale.scale_downs") >= 1
            await tail_task
            # -- the acceptance ledger ---------------------------------
            completed = sheds = 0
            for prompt, (status, headers, body) in results.items():
                n_want = dict(gold + free + agg + trickle)[prompt]
                if status == 200:
                    completed += 1
                    want = {**wants, **twants}[prompt]
                    assert body["choices"][0]["text"] == want, prompt
                else:
                    # Every shed is STRUCTURED: 429/503 + Retry-After +
                    # machine-readable overloaded_error.
                    sheds += 1
                    assert status in (429, 503), (prompt, status)
                    assert "retry-after" in headers, prompt
                    assert body["error"]["type"] == "overloaded_error"
            assert completed >= len(gold) + len(free) + len(trickle), \
                "storm starved the paced tenants"
            # The aggressor really was throttled by ITS quota (not
            # silently starved): per-tenant sheds carry the reason.
            tenant_sheds = [
                r for r in results.values()
                if r[0] == 429 and r[2]["error"].get("reason")
                == "tenant_quota"
            ]
            assert tenant_sheds, "aggressor was never quota-shed"
            assert METRICS.get_counter("tenant.shed.agg") >= 1
            # Surviving replicas' pools audit clean.
            for h in fleet.replicas:
                h.server.batcher.assert_pool_consistent()
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 550))


def test_fleet_add_remove_replica_live(warmed):
    """ReplicaFleet.add_replica boots + registers a routable replica
    (served through the router); remove_replica drains it away
    gracefully and returns the capacity — no respawn, handle gone."""
    tiny = warmed

    async def driver():
        fleet = ReplicaFleet([_server_factory(tiny)],
                             probe_interval_s=0.05, probe_timeout_s=2.0)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=ByteTokenizer(), page_size=PAGE)
        await fleet.start()
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)
            h = await fleet.add_replica()
            assert h.name == "r1" and len(fleet.replicas) == 2
            assert h.state == "healthy"  # add_replica waits for the probe
            s, _, b = await _request(
                host, port, {"prompt": "served elastically",
                             "max_tokens": 4})
            assert s == 200, b
            await fleet.remove_replica("r1", drain_timeout_s=10.0)
            assert len(fleet.replicas) == 1
            assert "r1" not in fleet._by_name
            # Still serving on the survivor.
            s, _, _ = await _request(
                host, port, {"prompt": "still here", "max_tokens": 4})
            assert s == 200
            # Scaled-up names never collide with drained-away ones.
            h2 = await fleet.add_replica()
            assert h2.name == "r2"
            await fleet.remove_replica("r2")
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 300))
