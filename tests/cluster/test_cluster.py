"""Control-plane tests: real asyncio sockets on localhost (the reference
faked its wire with mocked sockets, SURVEY §4; these run the actual stack),
plus fault-injection: dead-worker eviction and task retry — capabilities the
reference planned (plan.md:430-436) but never built.  The fault paths are
provoked DETERMINISTICALLY via runtime/faults.py (drop heartbeats, sever a
reply connection) instead of killing tasks and sleeping past wall-clock
deadlines."""

import asyncio
import json

import pytest

from distributed_llms_tpu.cluster import protocol
from distributed_llms_tpu.cluster.client import CoordinatorClient
from distributed_llms_tpu.cluster.coordinator import Coordinator
from distributed_llms_tpu.cluster.worker import WorkerHost
from distributed_llms_tpu.core.config import ClusterConfig, RuntimeConfig
from distributed_llms_tpu.runtime.faults import FaultPlane


def fast_cfg(**kw):
    return ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=0.6,
        connect_retry_s=0.1, connect_max_retries=3, task_timeout_s=10.0, **kw
    )


# ---------------------------------------------------------------------------
# KV-handoff frames (cluster/kv_transfer.py over KV_PAGES / KV_ACK)
# ---------------------------------------------------------------------------

import numpy as np

from distributed_llms_tpu.cluster import kv_transfer
from distributed_llms_tpu.runtime.batcher import PrefixCache


def _kv_payload(page_size=4, n_pages=2, tid="tx1"):
    ids = list(range(1, page_size * n_pages + 3))  # a few suffix tokens too
    digests = PrefixCache.page_digests(ids, page_size, n_pages)
    shape = (2, n_pages, page_size, 1, 2)  # [L, P, BLK, KVH, HD]
    k = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
    return kv_transfer.KVTransferPayload(
        transfer_id=tid, token_ids=ids[: page_size * n_pages],
        page_size=page_size, digests=digests, k_pages=k, v_pages=k + 1.0,
    )


async def _kv_receiver(faults=None):
    """A minimal decode-side listener: verified payloads land in
    ``imported``; duplicates dedup on digests exactly like the batcher's
    import does.  Returns (server, port, stats, imported)."""
    stats = kv_transfer.ReceiverStats()
    imported: list = []
    resident: set = set()

    async def import_fn(payload):
        if all(d in resident for d in payload.digests):
            return True, "duplicate"
        resident.update(payload.digests)
        imported.append(payload)
        return True, "imported"

    async def handle(reader, writer):
        await kv_transfer.handle_kv_connection(
            reader, writer, page_digests_fn=PrefixCache.page_digests,
            import_fn=import_fn, faults=faults, stats=stats,
        )

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1], stats, imported


@pytest.mark.asyncio
async def test_kv_frame_roundtrip_and_dup_delivery_idempotent():
    """A KV_PAGES frame round-trips verified; re-delivering the SAME
    transfer (a retry racing a delayed ack) acks ok WITHOUT re-importing
    — idempotence via the digest check, the dup-safety the sender's
    retry loop leans on."""
    server, port, stats, imported = await _kv_receiver()
    try:
        msg = kv_transfer.encode_kv_pages(_kv_payload())
        r1 = await kv_transfer.send_kv_pages("127.0.0.1", port, msg,
                                             attempt_s=5.0)
        assert r1.ok and r1.reason == "imported" and r1.attempts == 1
        r2 = await kv_transfer.send_kv_pages("127.0.0.1", port, msg,
                                             attempt_s=5.0)
        assert r2.ok and r2.reason == "duplicate"
        assert len(imported) == 1  # the payload landed exactly once
        assert stats.duplicates == 1
        got = imported[0]
        np.testing.assert_array_equal(got.k_pages, _kv_payload().k_pages)
        np.testing.assert_array_equal(got.v_pages, _kv_payload().v_pages)
    finally:
        server.close()


@pytest.mark.asyncio
async def test_kv_frame_drop_times_out_then_retry_succeeds():
    """A dropped frame (receiver pretends it was lost; no ack) times the
    sender out; the jittered retry delivers."""
    plane = FaultPlane()
    rule = plane.add("xfer.recv", "drop", when="1")
    server, port, stats, imported = await _kv_receiver(faults=plane)
    try:
        msg = kv_transfer.encode_kv_pages(_kv_payload(tid="txdrop"))
        res = await kv_transfer.send_kv_pages(
            "127.0.0.1", port, msg, attempt_s=0.3, max_retries=3,
            backoff_base_s=0.01,
        )
        assert res.ok and res.attempts == 2
        assert rule.fired == 1
        assert len(imported) == 1
    finally:
        server.close()


@pytest.mark.asyncio
async def test_kv_corrupt_payload_rejected_then_clean_retry_succeeds():
    """An in-flight bit-flip fails the receiver's checksum verify and is
    NACKed (never imported); the clean retry succeeds."""
    plane = FaultPlane()
    rule = plane.add("xfer.send", "corrupt", when="1")
    server, port, stats, imported = await _kv_receiver()
    try:
        msg = kv_transfer.encode_kv_pages(_kv_payload(tid="txcorrupt"))
        res = await kv_transfer.send_kv_pages(
            "127.0.0.1", port, msg, faults=plane, attempt_s=5.0,
            max_retries=2, backoff_base_s=0.01,
        )
        assert res.ok and res.attempts == 2
        assert rule.fired == 1
        assert stats.rejected == 1
        assert stats.last_reason == "imported"
        assert len(imported) == 1
    finally:
        server.close()


@pytest.mark.asyncio
async def test_kv_send_drop_swallowed_then_retry_succeeds():
    """A sender-side drop (the wire never sees the frame) times the
    sender out on the missing ack; the retry delivers — the mirror of the
    receiver-side drop drill above."""
    plane = FaultPlane()
    rule = plane.add("xfer.send", "drop", when="1")
    server, port, stats, imported = await _kv_receiver()
    try:
        msg = kv_transfer.encode_kv_pages(_kv_payload(tid="txsdrop"))
        res = await kv_transfer.send_kv_pages(
            "127.0.0.1", port, msg, faults=plane, attempt_s=0.3,
            max_retries=3, backoff_base_s=0.01,
        )
        assert res.ok and res.attempts == 2
        assert rule.fired == 1
        assert stats.rejected == 0  # swallowed, never seen — not NACKed
        assert len(imported) == 1
    finally:
        server.close()


@pytest.mark.asyncio
async def test_kv_recv_corrupt_nacked_then_clean_retry_succeeds():
    """A receiver-side bit-flip (corruption after the wire, before
    verify) fails the checksum and is NACKed; the byte-identical retry
    arrives clean and imports."""
    plane = FaultPlane()
    rule = plane.add("xfer.recv", "corrupt", when="1")
    server, port, stats, imported = await _kv_receiver(faults=plane)
    try:
        msg = kv_transfer.encode_kv_pages(_kv_payload(tid="txrcorrupt"))
        res = await kv_transfer.send_kv_pages(
            "127.0.0.1", port, msg, attempt_s=5.0, max_retries=2,
            backoff_base_s=0.01,
        )
        assert res.ok and res.attempts == 2
        assert rule.fired == 1
        assert stats.rejected == 1
        assert len(imported) == 1
    finally:
        server.close()


def test_kv_digest_chain_mismatch_rejected():
    """A frame whose digests do not commit to its carried tokens (a
    sender-side hashing bug: checksum INTACT, chain wrong) must be
    rejected — publishing those pages would serve wrong KV to every
    later prefix match."""
    p = _kv_payload()
    wrong = _kv_payload()
    wrong.token_ids = [t + 1 for t in wrong.token_ids]  # different prompt,
    #   digests left as the original prompt's — checksum recomputed clean
    msg = kv_transfer.encode_kv_pages(wrong)
    msg["payload"]["digests"] = [d.hex() for d in p.digests]
    import base64 as _b64
    kb = _b64.b64decode(msg["payload"]["k"])
    vb = _b64.b64decode(msg["payload"]["v"])
    msg["payload"]["checksum"] = kv_transfer.checksum(
        wrong.token_ids, p.digests, kb, vb
    )
    got, reason = kv_transfer.verify_and_decode(
        msg, PrefixCache.page_digests
    )
    assert got is None and reason == "digest mismatch"


@pytest.mark.asyncio
async def test_kv_oversized_frame_rejected_at_send(monkeypatch):
    """A transfer exceeding MAX_FRAME fails LOUDLY at the sender with a
    permanent (non-retried) failure — never a silent connection drop or
    a half-written stream."""
    server, port, stats, imported = await _kv_receiver()
    try:
        monkeypatch.setattr(protocol, "MAX_FRAME", 4096)
        msg = kv_transfer.encode_kv_pages(
            _kv_payload(page_size=16, n_pages=8, tid="txbig")
        )
        res = await kv_transfer.send_kv_pages("127.0.0.1", port, msg,
                                              max_retries=3)
        assert not res.ok and res.attempts == 0
        assert "frame too large" in res.reason
        assert not imported
    finally:
        server.close()


# ---------------------------------------------------------------------------
# protocol framing
# ---------------------------------------------------------------------------

def test_encode_decode_roundtrip():
    msg = protocol.message("REGISTER", {"capabilities": {"platform": "cpu"}})
    raw = protocol.encode(msg)
    n, flags = protocol.decode_header(raw[:8])
    assert n == len(raw) - 8
    assert flags == 0  # small frame: uncompressed
    assert json.loads(raw[8:]) == msg


def test_encode_compresses_large_frames():
    big = protocol.message("RESULT", {"text": ["x" * 100_000]})
    raw = protocol.encode(big)
    n, flags = protocol.decode_header(raw[:8])
    assert flags == 1
    assert n < 10_000  # zlib shrank 100kB of 'x'
    import zlib

    assert json.loads(zlib.decompress(raw[8:])) == big


@pytest.mark.asyncio
async def test_compressed_and_batched_over_the_wire():
    """Large (compressed) frames and BATCH frames round-trip through the real
    coordinator socket."""
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", coord.port)
        big_caps = {"note": "y" * 50_000}
        await protocol.send_messages(
            writer,
            [
                protocol.message("REGISTER", {"worker_id": "b", "capabilities": big_caps}),
                protocol.message("HEARTBEAT", {}),
            ],
        )
        ack = await protocol.receive_message(reader, timeout=5)
        assert ack["type"] == "REGISTER_ACK"
        for _ in range(50):
            if "b" in coord.workers:
                break
            await asyncio.sleep(0.02)
        assert coord.workers["b"].capabilities == big_caps
        writer.close()
    finally:
        await coord.stop()


def test_unbatch_rejects_nested_and_invalid():
    with pytest.raises(protocol.ProtocolError, match="messages"):
        protocol.unbatch({"type": "BATCH", "payload": {}})
    with pytest.raises(protocol.ProtocolError, match="invalid batched"):
        protocol.unbatch(protocol.batch([protocol.batch([])]))


def test_encode_rejects_unknown_type():
    with pytest.raises(protocol.ProtocolError, match="unknown message type"):
        protocol.encode({"type": "EVIL"})


def test_decode_rejects_oversized():
    import struct

    with pytest.raises(protocol.ProtocolError, match="too large"):
        protocol.decode_header(struct.pack(">Q", protocol.MAX_FRAME + 1))


@pytest.mark.asyncio
async def test_receive_timeout():
    coord = Coordinator(fast_cfg())
    host, port = await coord.start()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", coord.port)
        with pytest.raises(asyncio.TimeoutError):
            await protocol.receive_message(reader, timeout=0.2)
        writer.close()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_protocol_frame_faults_close_delay_drop():
    """The fault plane wired into protocol framing: close severs the
    stream mid-request, delay stalls a frame, drop swallows one on receive
    — all deterministic, all through the REAL coordinator socket."""
    import time

    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        # close: the client's GET_STATUS send dies with a connection error.
        protocol.set_fault_plane(
            FaultPlane.parse("proto.send/GET_STATUS:close@1")
        )
        with pytest.raises(ConnectionError, match="fault injection"):
            async with CoordinatorClient("127.0.0.1", coord.port) as c:
                await c.status()
        # delay: the same request completes, measurably later.
        protocol.set_fault_plane(
            FaultPlane.parse("proto.send/GET_STATUS:delay@1:0.2")
        )
        t0 = time.perf_counter()
        async with CoordinatorClient("127.0.0.1", coord.port) as c:
            status = await c.status()
        assert time.perf_counter() - t0 >= 0.2
        assert "workers" in status
        # drop on receive: the first RESULT frame is "lost in flight"; the
        # client's read times out even though the coordinator answered.
        protocol.set_fault_plane(
            FaultPlane.parse("proto.recv/RESULT:drop@1")
        )
        with pytest.raises(asyncio.TimeoutError):
            async with CoordinatorClient("127.0.0.1", coord.port) as c:
                await c.request("GET_STATUS", timeout=0.5)
    finally:
        protocol.set_fault_plane(None)  # global hook: ALWAYS uninstall
        await coord.stop()


# ---------------------------------------------------------------------------
# registration / heartbeat / eviction
# ---------------------------------------------------------------------------

class FakeEngine:
    def generate_text(self, prompts, max_new_tokens=None):
        import types

        n = max_new_tokens or 4
        return types.SimpleNamespace(
            text=[p + "!" for p in prompts],
            generated_tokens=n * len(prompts),
            seconds=0.01,
            tokens_per_second=float(n * len(prompts)) / 0.01,
        )


def fake_factory(store_dir, shards, rt):
    return FakeEngine()


async def start_worker(coord, factory=fake_factory, **kw):
    w = WorkerHost("127.0.0.1", coord.port, cfg=fast_cfg(), engine_factory=factory, **kw)
    task = asyncio.create_task(w.run())
    for _ in range(100):
        if w.worker_id is not None:
            break
        await asyncio.sleep(0.02)
    assert w.worker_id is not None, "worker failed to register"
    return w, task


@pytest.mark.asyncio
async def test_register_heartbeat_and_eviction():
    """Deadline eviction (reference never evicted: D10), provoked by FAULT
    INJECTION: the worker stays alive but a `worker.heartbeat:drop@1+` rule
    swallows every beat — exactly a silently-wedged host, with no task
    killing and no fixed sleeps (poll loops bound the waits)."""
    plane = FaultPlane()
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w, wt = await start_worker(coord, faults=plane)
        assert w.worker_id in coord.workers
        # heartbeats keep it alive past the timeout window
        await asyncio.sleep(0.9)
        assert w.worker_id in coord.workers

        # Arm the fault mid-run: every subsequent heartbeat is dropped.
        rule = plane.add("worker.heartbeat", "drop", when="1+")
        for _ in range(200):  # poll-wait for the deadline eviction
            if w.worker_id not in coord.workers:
                break
            await asyncio.sleep(0.05)
        assert w.worker_id not in coord.workers
        assert rule.fired >= 1  # beats were really dropped, not just late
        wt.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_stable_id_reregistration_survives_stale_close():
    """A host restarting under a stable id (e.g. its StatefulSet pod name)
    replaces its registration; the stale connection's close must not evict
    the fresh one."""
    import dataclasses

    coord = Coordinator(dataclasses.replace(fast_cfg(), heartbeat_timeout_s=60.0))
    await coord.start()
    try:
        async def register(wid):
            reader, writer = await asyncio.open_connection("127.0.0.1", coord.port)
            await protocol.send_message(
                writer, protocol.message("REGISTER", {"worker_id": wid, "capabilities": {}})
            )
            ack = await protocol.receive_message(reader, timeout=5)
            assert ack["payload"]["worker_id"] == wid
            return reader, writer

        r1, w1 = await register("pod-0")
        old_info = coord.workers["pod-0"]
        r2, w2 = await register("pod-0")  # restart: same id, new connection
        assert coord.workers["pod-0"].writer is not old_info.writer
        # Stale socket closes (either side) -> registration must survive.
        w1.close()
        await asyncio.sleep(0.3)
        assert "pod-0" in coord.workers
        assert coord.workers["pod-0"].writer is not old_info.writer
        w2.close()
        await asyncio.sleep(0.3)
        assert "pod-0" not in coord.workers  # real close still evicts
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_stable_id_rejoin_replaces_shards(tmp_path):
    """A stable-id rejoin is a fresh process with nothing loaded: the
    coordinator must re-send PLACE_SHARDS for its assignment instead of
    routing generates at an empty worker."""
    import dataclasses

    coord = Coordinator(dataclasses.replace(fast_cfg(), heartbeat_timeout_s=60.0))
    await coord.start()
    try:
        async def register(wid):
            reader, writer = await asyncio.open_connection("127.0.0.1", coord.port)
            await protocol.send_message(
                writer, protocol.message("REGISTER", {"worker_id": wid, "capabilities": {}})
            )
            ack = await protocol.receive_message(reader, timeout=5)
            assert ack["type"] == "REGISTER_ACK"
            return reader, writer

        r1, w1 = await register("pod-0")
        coord.plan_shards(2, store_dir=str(tmp_path))
        # Drain the initial PLACE_SHARDS (ack it so place_shards resolves).
        place_task = asyncio.create_task(coord.place_shards())
        msg = await protocol.receive_message(r1, timeout=5)
        assert msg["type"] == "PLACE_SHARDS"
        await protocol.send_message(
            w1, protocol.message("RESULT", {"loaded": [0, 1], "resident": "x"},
                                 msg_id=msg["msg_id"])
        )
        await place_task

        # Restart: same id, new connection -> expect a fresh PLACE_SHARDS.
        w1.close()
        r2, w2 = await register("pod-0")
        msg2 = await protocol.receive_message(r2, timeout=5)
        assert msg2["type"] == "PLACE_SHARDS"
        assert sorted(msg2["payload"]["shards"]) == [0, 1]
        w2.close()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_plan_place_generate_roundtrip(tmp_path):
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w, wt = await start_worker(coord)
        coord.plan_shards(2, store_dir=str(tmp_path))
        assert set(coord.shard_assignment) == {0, 1}
        placed = await coord.place_shards()
        assert placed[w.worker_id]["loaded"] == [0, 1]
        out = await coord.generate(["hello"], max_new_tokens=3)
        assert out["text"] == ["hello!"]
        wt.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_task_retry_on_worker_death(tmp_path):
    """Task dispatched to a worker that dies mid-flight is retried on the
    survivor (planned in the reference, never built).  Deterministic via
    fault injection: the victim's `worker.result/GENERATE:close@1` rule
    severs its connection at the exact moment it would reply — no
    sleep-until-in-flight sampling, no task cancellation."""
    calls = []

    def factory(store_dir, shards, rt):
        calls.append(shards)
        return FakeEngine()

    # The dispatcher picks the lowest idle worker id, and ids assign in
    # registration order — the FIRST worker is deterministically the victim.
    victim_plane = FaultPlane.parse("worker.result/GENERATE:close@1")
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w1, t1 = await start_worker(coord, factory=factory,
                                    rt=RuntimeConfig(), faults=victim_plane)
        w2, t2 = await start_worker(coord, factory=factory)
        coord.plan_shards(2, store_dir=str(tmp_path))
        await coord.place_shards()
        assert len(calls) == 2  # both workers built engines

        out = await asyncio.wait_for(
            coord.generate(["x"], max_new_tokens=2), timeout=15
        )
        assert out["text"] == ["x!"]
        assert victim_plane.rules[0].fired == 1  # the victim really died
        assert w1.worker_id not in coord.workers  # ...and was evicted
        for t in (t1, t2):
            t.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_task_retry_on_injected_handler_fault(tmp_path):
    """An InjectedFault inside a worker's command handler surfaces as an
    ERROR reply and the coordinator retries — the handler-crash leg of the
    retry contract, distinct from connection death above."""
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        plane = FaultPlane.parse("worker.handle/GENERATE:raise@1")
        w, wt = await start_worker(coord, faults=plane)
        coord.plan_shards(1, store_dir=str(tmp_path))
        await coord.place_shards()
        out = await asyncio.wait_for(
            coord.generate(["y"], max_new_tokens=2), timeout=15
        )
        assert out["text"] == ["y!"]
        assert plane.rules[0].fired == 1
        assert w.worker_id in coord.workers  # handler crash, not death
        wt.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_dispatch_drop_times_out_submitter_then_retry_lands(tmp_path):
    """A coordinator.dispatch drop models the dispatch lost in flight:
    the task stays assigned and unanswered, the submitter's wait_for
    timeout fires, and a fresh submit dispatches normally — the
    submitter-timeout leg of the retry contract."""
    plane = FaultPlane.parse("coordinator.dispatch/GENERATE:drop@1")
    coord = Coordinator(fast_cfg(), faults=plane)
    await coord.start()
    try:
        w, wt = await start_worker(coord)
        coord.plan_shards(1, store_dir=str(tmp_path))
        await coord.place_shards()
        with pytest.raises(asyncio.TimeoutError):
            await coord.generate(["z"], max_new_tokens=2, timeout=1.0)
        assert plane.rules[0].fired == 1
        out = await asyncio.wait_for(
            coord.generate(["z"], max_new_tokens=2), timeout=15
        )
        assert out["text"] == ["z!"]
        assert w.worker_id in coord.workers  # nothing died — only the wire
        wt.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_schedule_computation_and_shutdown_broadcast(tmp_path):
    """The two frame types that had handlers but no sender until
    graftflow's GF401 flagged them: SCHEDULE_COMPUTATION dispatches
    through the same engine path as GENERATE, and shutdown_workers
    broadcasts SHUTDOWN — every worker answers ``{"ok": True}`` and
    stops its loops (graceful fleet retirement), with per-worker
    error strings instead of a failed broadcast when one is gone."""
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w, wt = await start_worker(coord)
        coord.plan_shards(1, store_dir=str(tmp_path))
        await coord.place_shards()
        out = await asyncio.wait_for(
            coord.schedule_computation(
                {"prompts": ["z"], "max_new_tokens": 2}), timeout=15
        )
        assert out["text"] == ["z!"]
        replies = await asyncio.wait_for(coord.shutdown_workers(), timeout=15)
        assert replies == {w.worker_id: {"ok": True}}
        # The worker's run loop really exits (stop() flips its event).
        await asyncio.wait_for(wt, timeout=10)
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_generate_without_placement_errors_then_retries_exhaust(tmp_path):
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w, wt = await start_worker(coord)
        # no PLACE_SHARDS: worker raises, coordinator retries, then fails
        with pytest.raises(RuntimeError, match="failed after"):
            await coord.generate(["x"])
        wt.cancel()
    finally:
        await coord.stop()


async def register_fake(coord, wid, caps):
    """Raw-protocol registration with custom capabilities."""
    reader, writer = await asyncio.open_connection("127.0.0.1", coord.port)
    await protocol.send_message(
        writer, protocol.message("REGISTER", {"worker_id": wid, "capabilities": caps})
    )
    ack = await protocol.receive_message(reader, timeout=5)
    assert ack["type"] == "REGISTER_ACK"
    return reader, writer


@pytest.mark.asyncio
async def test_capacity_aware_plan():
    """Workers advertising more capacity receive proportionally more shards
    (the reference recorded capabilities but never used them, SURVEY §2.2)."""
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        r1, w1 = await register_fake(coord, "big", {"num_devices": 3})
        r2, w2 = await register_fake(coord, "small", {"num_devices": 1})
        plan = coord.plan_shards(4)
        counts = {"big": 0, "small": 0}
        for wid in plan.values():
            counts[wid] += 1
        assert counts == {"big": 3, "small": 1}
        # round_robin parity policy still splits 2/2
        plan_rr = coord.plan_shards(4, policy="round_robin")
        assert sorted(plan_rr.values()) == ["big", "big", "small", "small"]
        w1.close(), w2.close()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_eviction_reassigns_shards(tmp_path):
    """Dynamic reassignment on pool change (plan.md:423-428, never built):
    a dead worker's shards move to the survivor and get re-placed."""
    calls: list[tuple[str, list[int]]] = []

    def factory(store_dir, shards, rt):
        calls.append(("w", shards))
        return FakeEngine()

    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w1, t1 = await start_worker(coord, factory=factory)
        w2, t2 = await start_worker(coord, factory=factory)
        coord.plan_shards(4, store_dir=str(tmp_path))
        await coord.place_shards()
        assert len(calls) == 2

        t1.cancel()  # dies silently -> deadline eviction
        for _ in range(100):
            await asyncio.sleep(0.05)
            if (
                w1.worker_id not in coord.workers
                and set(coord.shard_assignment.values()) == {w2.worker_id}
                and len(calls) >= 3
            ):
                break
        assert set(coord.shard_assignment.values()) == {w2.worker_id}
        assert sorted(coord.shard_assignment) == [0, 1, 2, 3]
        assert sorted(calls[-1][1]) == [0, 1, 2, 3]  # survivor re-placed all
        t2.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_rebalance_after_join(tmp_path):
    """A worker joining after placement takes over shards via rebalance()."""
    calls: list[list[int]] = []

    def factory(store_dir, shards, rt):
        calls.append(shards)
        return FakeEngine()

    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w1, t1 = await start_worker(coord, factory=factory)
        coord.plan_shards(4, store_dir=str(tmp_path))
        await coord.place_shards()
        assert set(coord.shard_assignment.values()) == {w1.worker_id}

        w2, t2 = await start_worker(coord, factory=factory)
        plan = await coord.rebalance()
        assert set(plan.values()) == {w1.worker_id, w2.worker_id}
        per = {}
        for s, wid in plan.items():
            per.setdefault(wid, []).append(s)
        assert sorted(len(v) for v in per.values()) == [2, 2]
        t1.cancel(), t2.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_status_and_metrics_client(tmp_path):
    coord = Coordinator(fast_cfg())
    await coord.start()
    try:
        w, wt = await start_worker(coord)
        async with CoordinatorClient("127.0.0.1", coord.port) as c:
            status = await c.status()
            assert w.worker_id in status["workers"]
            metrics = await c.metrics()
            assert "counters" in metrics
        wt.cancel()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_worker_process_registers():
    """Process-isolated local simulation (the reference's planned
    multiprocessing mode, plan.md:225-233): a separate interpreter running
    host_main registers with the coordinator."""
    import subprocess
    import sys

    coord = Coordinator(fast_cfg())
    await coord.start()
    import pathlib

    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_llms_tpu.cli.host_main",
         "--host", "127.0.0.1", "--port", str(coord.port), "--platform", "cpu"],
        cwd=repo_root,
    )
    try:
        for _ in range(300):  # jax import in the child takes a few seconds
            if coord.workers:
                break
            await asyncio.sleep(0.1)
        assert coord.workers, "worker process never registered"
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        await coord.stop()


@pytest.mark.asyncio
async def test_worker_connect_retry_fails_cleanly():
    w = WorkerHost("127.0.0.1", 1, cfg=fast_cfg())  # port 1: nothing there
    with pytest.raises(ConnectionError, match="could not reach"):
        await w.run()


@pytest.mark.asyncio
async def test_mesh_parallel_serving_end_to_end(tmp_path):
    """The reference's core promise — split one model across devices and
    serve it (src/master/node.py:84-138) — through the PRODUCT path:
    coordinator -> worker -> ParallelModel(dp=2, pp=2, tp=2) -> decoded
    text, exact-matching the single-device engine."""
    import jax

    from distributed_llms_tpu.checkpoint import store as store_lib
    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.models import model as model_lib, presets
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    # vocab 512 >= the byte tokenizer's 259 ids (256 bytes + specials)
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(params, str(tmp_path), num_shards=2, model_config=cfg)

    rt = RuntimeConfig(microbatches=2, max_decode_steps=8)
    mesh_cfg = MeshConfig(data=2, pipe=2, model=2)
    import dataclasses

    # Pipelined generate compiles on CPU need a roomy task deadline, and the
    # compile holds the GIL in bursts that can starve the worker's heartbeat
    # task — so eviction must be lenient too (fast eviction is covered by the
    # dedicated eviction tests above).
    ccfg = dataclasses.replace(
        fast_cfg(), task_timeout_s=180.0, heartbeat_timeout_s=180.0
    )
    coord = Coordinator(ccfg)
    await coord.start()
    try:
        w = WorkerHost("127.0.0.1", coord.port, cfg=ccfg, rt=rt, mesh_cfg=mesh_cfg)
        wt = asyncio.create_task(w.run())
        for _ in range(100):
            if w.worker_id is not None:
                break
            await asyncio.sleep(0.02)
        assert w.worker_id is not None

        coord.plan_shards(2, store_dir=str(tmp_path))
        placed = await coord.place_shards()
        assert "mesh" in placed[w.worker_id]["resident"]
        assert w.engine.parallel is not None and w.engine.parallel.pipelined

        out = await coord.generate(["hello world"], max_new_tokens=8)

        ref = InferenceEngine.from_store(str(tmp_path), rt=rt)
        expect = ref.generate_text(["hello world"], max_new_tokens=8)
        assert out["text"] == expect.text
        wt.cancel()
    finally:
        await coord.stop()
