"""Fleet-global control plane (ISSUE 18): router-resident tenant
ledger, tiered (prefill/decode) autoscaling, and cross-replica KV reuse
via the fleet prefix-digest directory.

Three layers:

1. UNITS (no servers): fleet-ledger arithmetic (charge / refund /
   per-tenant Retry-After walked off the ledger), epoch-keyed directory
   self-invalidation, and the TieredAutoscaler's per-role decision loop
   against a stub fleet (independent streaks/cooldowns, role-scoped
   graceful scale-down, per-tier veto drills, TierPolicy validation).
2. LIVE invariants (tiny model): quota is CONSERVED under elasticity —
   a fleet of 2 admits exactly 1x a tenant's quota, pinned before and
   after a live scale-up; a decode replica on an affinity miss PULLS
   cached pages from the sibling that holds them (``cached_tokens`` > 0
   on the cold sibling, byte-exact), and a mis-steered directory answer
   (``directory.lookup:corrupt``) degrades to local recompute,
   byte-exact, counted.
3. CHAOS ACCEPTANCE: a multi-tenant storm against a disaggregated
   ELASTIC fleet (1 prefill + 2 decode, tiered autoscaler armed) drives
   a prefill-tier scale-up mid-storm and a graceful decode-tier drain in
   the tail, absorbs one ``router.ledger:stall`` and one
   ``directory.lookup:corrupt`` drill, sheds the aggressor with
   fleet-ledger Retry-Afters, self-invalidates directory entries for the
   drained-away replica — and completes every request byte-exact vs an
   unfaulted fixed-fleet reference, pools auditing clean on survivors.
"""

import asyncio
import json

import pytest

import jax

from distributed_llms_tpu.cluster.autoscale import (
    TieredAutoscaler, TierPolicy,
)
from distributed_llms_tpu.cluster.fleet import ReplicaFleet
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.router import ReplicaRouter
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

PAGE = 16


def _run(coro):
    return asyncio.run(coro)


# -- units: the fleet tenant ledger -----------------------------------------


class _LedgerFleet:
    """The minimal fleet surface the router's ledger/directory units
    touch: named handles only."""

    def __init__(self, *handles):
        self.replicas = list(handles)
        self._by_name = {h.name: h for h in self.replicas}


class _Handle:
    def __init__(self, name, role="colocated", epoch=1, committed=0,
                 handoffs=0, inflight=0, state="healthy"):
        self.name = name
        self.role = role
        self.epoch = epoch
        self.committed_tokens = committed
        self.handoffs = handoffs
        self.inflight = set(range(inflight))
        self.state = state
        self.partitioned_until = 0.0

    def routable(self, now):
        return self.state == "healthy" and now >= self.partitioned_until

    def reachable(self, now):
        return self.state != "dead" and now >= self.partitioned_until


def _ledger_router(**kw):
    kw.setdefault("tenant_quota_tps", 5.0)
    kw.setdefault("tenant_rate_window_s", 10.0)
    return ReplicaRouter(_LedgerFleet(), tokenizer=ByteTokenizer(),
                         page_size=PAGE, **kw)


def test_fleet_ledger_charge_refund_and_retry_after():
    r = _ledger_router(tenant_weights={"gold": 4.0})
    # Allowance = weight x quota x window, fleet-wide.
    assert r._tenant_allowance("free") == pytest.approx(50.0)
    assert r._tenant_allowance("gold") == pytest.approx(200.0)
    # Under the window: admits (no hint), then the committed charges
    # fill the window and the NEXT request walks its own Retry-After
    # off the fleet ledger (1..60s, never a load guess).
    assert r._ledger_retry_after("free", 20) is None
    r._ledger_charge("free", 20)
    r._ledger_charge("free", 30)
    hint = r._ledger_retry_after("free", 20)
    assert isinstance(hint, int) and 1 <= hint <= 10
    # A refund reopens the window (a shed must not burn quota)...
    r._ledger_refund("free", 30)
    assert r._ledger_retry_after("free", 20) is None
    # ... and a fully-refunded tenant leaves the (capped) map entirely.
    r._ledger_refund("free", 20)
    assert "free" not in r._tenant_window
    # The exhaust drill forces the over-quota path even under quota.
    assert r._ledger_retry_after("free", 1, forced=True) >= 1
    # Per-tenant isolation: gold's window is untouched by free's.
    assert r._ledger_retry_after("gold", 150) is None


def test_fleet_ledger_oversized_request_has_no_retry_after_path():
    r = _ledger_router()  # anon weight 1.0 -> allowance 50
    # est > the ENTIRE window allowance: the gate's caller answers 400
    # (no Retry-After could come true); the arithmetic here just shows
    # the window can never free enough.
    assert r._tenant_allowance("-") == pytest.approx(50.0)
    hint = r._ledger_retry_after("-", 60)
    assert hint is not None  # capped, structured, finite
    assert 1 <= hint <= 60


def test_directory_epoch_invalidation_after_respawn():
    """An affinity/directory entry recorded against an older epoch (the
    replica drained/respawned since: cold pool) reads as a MISS and is
    dropped + counted — stale directory answers can never steer a pull
    at a cache that no longer holds the pages."""
    h = _Handle("d0", role="decode", epoch=3)
    r = ReplicaRouter(_LedgerFleet(h), tokenizer=ByteTokenizer(),
                      page_size=PAGE)
    d = b"\x01" * 32
    r._affinity[d] = ("d0", 3)
    assert r._affinity_lookup(d) == "d0"
    s0 = METRICS.get_counter("directory.stale_drops")
    h.epoch = 4  # the respawn
    assert r._affinity_lookup(d) is None
    assert d not in r._affinity
    assert METRICS.get_counter("directory.stale_drops") == s0 + 1
    # A handle gone from the fleet entirely (drained away) is the same
    # self-invalidating miss.
    r._affinity[d] = ("gone", 1)
    assert r._affinity_lookup(d) is None
    assert METRICS.get_counter("directory.stale_drops") == s0 + 2


# -- units: the tiered autoscaler -------------------------------------------


class _TierFleet:
    """The surface TieredAutoscaler consumes: role-tagged handles plus
    role-aware add/remove."""

    def __init__(self, *handles):
        self.replicas = list(handles)
        self.added: list[str] = []
        self.removed: list[str] = []

    async def add_replica(self, factory=None, name=None, role=None):
        self.added.append(role)
        h = _Handle(name or f"{role[:1]}{len(self.replicas)}", role=role)
        self.replicas.append(h)
        return h

    async def remove_replica(self, name, drain_timeout_s=30.0):
        self.removed.append(name)
        self.replicas = [h for h in self.replicas if h.name != name]


def _tiered(fleet, **kw):
    kw.setdefault("prefill", TierPolicy(
        min_replicas=1, max_replicas=2, up_load=0.8, down_load=0.2,
        hysteresis=2, cooldown_s=0.0,
    ))
    kw.setdefault("decode", TierPolicy(
        min_replicas=1, max_replicas=3, up_load=0.8, down_load=0.2,
        hysteresis=2, cooldown_s=0.0,
    ))
    kw.setdefault("replica_capacity_tokens", 100)
    return TieredAutoscaler(fleet, **kw)


def test_tier_signals_are_role_scoped():
    async def fn():
        fleet = _TierFleet(
            _Handle("p0", role="prefill", handoffs=3),
            _Handle("d0", role="decode", committed=60, inflight=2),
            _Handle("d1", role="decode", committed=20, inflight=1),
            _Handle("dead", role="decode", state="dead"),
        )
        sc = _tiered(fleet)
        sc._loop = asyncio.get_running_loop()
        pre = sc.signals("prefill")
        # Prefill load = in-flight handoffs per routable prefill replica
        # (handoff charges are transient; the RPC count IS the queue).
        assert pre["replicas"] == 1 and pre["load"] == pytest.approx(3.0)
        dec = sc.signals("decode")
        assert dec["replicas"] == 2          # dead handles don't count
        assert dec["committed_tokens"] == 80
        assert dec["load"] == pytest.approx(80 / 200)
        assert METRICS.get_gauge("autoscale.prefill.load") \
            == pytest.approx(3.0)
        assert METRICS.get_gauge("autoscale.decode.replicas") == 2

    _run(fn())


def test_tiers_scale_independently_with_own_streaks():
    async def fn():
        fleet = _TierFleet(
            _Handle("p0", role="prefill", handoffs=2),   # hot: load 2.0
            _Handle("d0", role="decode", committed=95),  # hot: load 0.95
        )
        sc = _tiered(fleet)
        # Tick 1: both streaks build, nothing acts (hysteresis 2).
        acts = await sc.tick()
        assert acts == {"prefill": None, "decode": None}
        # Tick 2: BOTH tiers scale up, each on its own signal/streak.
        acts = await sc.tick()
        assert acts == {"prefill": "up", "decode": "up"}
        assert fleet.added == ["prefill", "decode"]
        # Prefill at its max (2): hot forever, never past the ceiling —
        # while decode (max 3) may keep growing on ITS signal.
        fleet.replicas[2].handoffs = 2       # keep prefill tier hot
        fleet.replicas[3].committed_tokens = 95
        acts = [await sc.tick() for _ in range(2)]
        assert all(a["prefill"] is None for a in acts)
        assert acts[-1]["decode"] == "up"
        assert fleet.added.count("decode") == 2

    _run(fn())


def test_tier_scale_down_is_role_scoped_and_floored():
    async def fn():
        fleet = _TierFleet(
            _Handle("p0", role="prefill"),               # idle
            _Handle("d0", role="decode", committed=30, inflight=2),
            _Handle("d1", role="decode", committed=1),   # least committed
        )
        sc = _tiered(fleet)
        await sc.tick()
        acts = await sc.tick()
        # Decode drains its LEAST-COMMITTED replica; prefill sits at its
        # floor (min 1) and is never touched by the decode decision.
        assert acts["decode"] == "down" and acts["prefill"] is None
        assert fleet.removed == ["d1"]
        assert [h.name for h in fleet.replicas] == ["p0", "d0"]
        # Both tiers at their floors: cold forever, nothing drains.
        for _ in range(4):
            acts = await sc.tick()
            assert acts == {"prefill": None, "decode": None}
        assert fleet.removed == ["d1"]

    _run(fn())


def test_tier_veto_drills_are_per_role():
    async def fn():
        plane = FaultPlane.parse("fleet.scale_up/prefill:drop@1")
        fleet = _TierFleet(
            _Handle("p0", role="prefill", handoffs=2),
            _Handle("d0", role="decode", committed=95),
        )
        sc = _tiered(fleet, faults=plane)
        f0 = METRICS.get_counter("autoscale.prefill.scale_failures")
        await sc.tick()
        acts = await sc.tick()
        # The tag=prefill drop vetoes ONLY the prefill tier's growth;
        # decode scales on the same tick.
        assert acts == {"prefill": None, "decode": "up"}
        assert fleet.added == ["decode"]
        assert METRICS.get_counter("autoscale.prefill.scale_failures") \
            == f0 + 1
        # The prefill tier retries after its own (zero) cooldown.
        fleet.replicas[0].handoffs = 2
        for _ in range(3):
            acts = await sc.tick()
            if acts["prefill"] == "up":
                break
        assert fleet.added.count("prefill") == 1

    _run(fn())


def test_tier_policy_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        TierPolicy(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        TierPolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="down_load"):
        TierPolicy(up_load=0.5, down_load=0.6)
    with pytest.raises(ValueError, match="hysteresis"):
        TierPolicy(hysteresis=0)


def test_fleet_mints_role_prefixed_names():
    fleet = ReplicaFleet([])
    assert fleet._fresh_name() == "r0"
    assert fleet._fresh_name("p") == "p1"
    assert fleet._fresh_name("d") == "d2"


# -- live fixtures -----------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _replica_batcher(tiny, pages=12):
    cfg, params = tiny
    tok = ByteTokenizer()
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=2, max_len=96, chunk_steps=4,
        paged_pages=pages, page_size=PAGE, prefix_cache=True,
    )


@pytest.fixture(scope="module")
def warmed(tiny):
    """Warm the process-wide jit cache with the replicas' program shapes
    (paged admission, cache-hit admission — the pulled request's path —
    and decode) so fast watchdogs never mistake a compile for a wedge."""
    b = _replica_batcher(tiny)
    for prompt in ("warm short", "a much longer warming prompt xxxx!!",
                   "a much longer warming prompt xxxx!!"):
        b.submit(prompt, max_new_tokens=4)
        b.run()
    return tiny


def _factory(tiny, role="colocated"):
    def make_server():
        return InferenceServer(
            _replica_batcher(tiny), model_name="tiny", host="127.0.0.1",
            port=0, batcher_factory=lambda: _replica_batcher(tiny),
            watchdog_timeout_s=5.0, role=role,
        )

    return make_server


async def _request(host, port, body, tenant=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode()
    hdr = f"X-Tenant: {tenant}\r\n" if tenant else ""
    writer.write(
        f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n{hdr}"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    raw = await reader.read()
    writer.close()
    return status, headers, json.loads(raw) if raw.strip() else {}


def expected_texts(tiny, reqs):
    """Unfaulted FIXED-fleet reference: one roomy batcher serves every
    prompt solo — byte-exactness at temp 0 must be invariant to fleet
    size, elasticity, and where the KV pages came from."""
    cfg, params = tiny
    tok = ByteTokenizer()
    b = ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=4, max_len=96, chunk_steps=4, paged_pages=40,
        page_size=PAGE,
    )
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return {p: tok.decode(res[rid]) for rid, (p, n) in zip(rids, reqs)}


def _audit_all(fleet):
    for h in fleet.replicas:
        if h.server is not None and h.server._engine is not None \
                and h.server._engine.is_alive():
            h.server.batcher.assert_pool_consistent()


LONG = "disaggregate this considerable prompt please! "  # > 2 full pages


# -- live: quota conservation under elasticity -------------------------------


def test_quota_conserved_across_live_scale_up(warmed):
    """THE conservation pin: the router's fleet ledger admits a tenant
    exactly 1x (weight x quota x window) whether the fleet runs 1 or 2
    replicas — a live mid-window scale-up must not reopen the window,
    and the over-quota sheds carry the tenant's own fleet-ledger
    Retry-After."""
    tiny = warmed
    prompts = [f"quota prompt {i:02d} xx" for i in range(5)]
    ids_len = len(ByteTokenizer().encode(prompts[0]))
    est = ids_len + 4  # the router's admission estimate per request
    # Allowance = 2.5x est over a window far longer than the test: the
    # first two admit, the third sheds, and nothing ages out mid-test.
    window = 120.0
    quota = (2.5 * est) / window

    async def driver():
        fleet = ReplicaFleet([_factory(tiny)], probe_interval_s=0.05,
                             probe_timeout_s=2.0)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, tenant_quota_tps=quota,
            tenant_rate_window_s=window,
        )
        await fleet.start()
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)
            c0 = METRICS.get_counter("router.ledger.charges")
            s0 = METRICS.get_counter("router.ledger.sheds")

            async def one(p, tenant="capped"):
                return await _request(
                    host, port, {"prompt": p, "max_tokens": 4},
                    tenant=tenant)

            st1, _, _ = await one(prompts[0])
            st2, _, _ = await one(prompts[1])
            assert (st1, st2) == (200, 200)
            st3, hdr3, body3 = await one(prompts[2])
            assert st3 == 429, body3
            assert body3["error"]["reason"] == "tenant_quota"
            assert int(hdr3["retry-after"]) >= 1
            assert METRICS.get_counter("router.ledger.charges") == c0 + 2
            assert METRICS.get_counter("router.ledger.sheds") >= s0 + 1
            # Live scale-up mid-window: the fleet doubles, the tenant's
            # fleet allowance does NOT.
            h = await fleet.add_replica()
            assert h.state == "healthy" and len(fleet.replicas) == 2
            st4, hdr4, body4 = await one(prompts[3])
            assert st4 == 429, body4
            assert body4["error"]["reason"] == "tenant_quota"
            assert int(hdr4["retry-after"]) >= 1
            # Per-tenant isolation: a different tenant's window is its
            # own — it admits on the grown fleet while "capped" sheds.
            st5, _, body5 = await one(prompts[4], tenant="other")
            assert st5 == 200, body5
            # No silent unmetered admits: every 200 was charged.
            assert METRICS.get_counter("router.ledger.charges") == c0 + 3
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 300))


def test_ledger_exhaust_and_drop_drills(warmed):
    """The fleet-ledger gate's two remaining drills: ``exhaust`` forces
    the over-quota shed (429 + the tenant's own Retry-After, counted,
    nothing charged) even under a generous quota, and ``drop`` bypasses
    the gate AND its charge (a counted unmetered admit — the replica
    backstop is then the only meter)."""
    tiny = warmed
    prompts = [f"ledger drill {i} xx" for i in range(3)]
    wants = expected_texts(tiny, [(p, 4) for p in prompts])
    plane = FaultPlane()
    exhaust = plane.add("router.ledger", "exhaust", when="1")

    async def driver():
        fleet = ReplicaFleet([_factory(tiny)], probe_interval_s=0.05,
                             probe_timeout_s=2.0)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, faults=plane,
            tenant_quota_tps=1000.0, tenant_rate_window_s=10.0,
        )
        await fleet.start()
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)
            c0 = METRICS.get_counter("router.ledger.charges")
            s0 = METRICS.get_counter("router.ledger.sheds")
            b0 = METRICS.get_counter("router.ledger.bypasses")
            # exhaust: forced shed under quota, with a real Retry-After.
            st, hdr, body = await _request(
                host, port, {"prompt": prompts[0], "max_tokens": 4},
                tenant="drilled")
            assert st == 429, body
            assert body["error"]["reason"] == "tenant_quota"
            assert int(hdr["retry-after"]) >= 1
            assert exhaust.fired == 1
            assert METRICS.get_counter("router.ledger.sheds") == s0 + 1
            assert METRICS.get_counter("router.ledger.charges") == c0
            # drop: the gate (and its charge) is skipped — the admit is
            # counted as a bypass, never silently unmetered.
            drop = plane.add("router.ledger", "drop", when="1")
            st, _, body = await _request(
                host, port, {"prompt": prompts[1], "max_tokens": 4},
                tenant="drilled")
            assert st == 200, body
            assert body["choices"][0]["text"] == wants[prompts[1]]
            assert drop.fired == 1
            assert METRICS.get_counter("router.ledger.bypasses") == b0 + 1
            assert METRICS.get_counter("router.ledger.charges") == c0
            # The gate is back to normal metering afterwards.
            st, _, body = await _request(
                host, port, {"prompt": prompts[2], "max_tokens": 4},
                tenant="drilled")
            assert st == 200, body
            assert body["choices"][0]["text"] == wants[prompts[2]]
            assert METRICS.get_counter("router.ledger.charges") == c0 + 1
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 300))


# -- live: cross-replica pull + its degradation ladder -----------------------


def test_directory_pull_serves_sibling_cache_and_falls_back_exact(warmed):
    """A request landing COLD on one replica pulls the prompt's cached
    pages from the sibling that holds them (``cached_tokens`` proves no
    re-prefill; bytes exact), and a mis-steered directory answer
    (``directory.lookup:corrupt`` pointing at a replica that holds
    NOTHING) degrades to local recompute — byte-exact, counted."""
    tiny = warmed
    # Distinct FIRST bytes: chained page digests must share nothing, or
    # the second prompt rides the first's affinity instead of exercising
    # its own cold-placement + pull path.
    p_pull = "pull leg! " + LONG
    p_miss = "steer leg " + LONG
    wants = expected_texts(tiny, [(p_pull, 8), (p_miss, 8)])
    plane = FaultPlane()
    corrupt = plane.add("directory.lookup", "corrupt", when="2")

    async def driver():
        fleet = ReplicaFleet([_factory(tiny)] * 3, probe_interval_s=0.05,
                             probe_timeout_s=2.0)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, faults=plane,
        )
        await fleet.start()
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)
            # Serve p_pull once: sequential + all-idle placement picks
            # r0 (least committed, min name); its pages cache there and
            # the router records the digest run against r0.
            st, _, body = await _request(
                host, port, {"prompt": p_pull, "max_tokens": 8})
            assert st == 200 and body["choices"][0]["text"] == wants[p_pull]
            # r0 stops taking new work (drains) but stays reachable: the
            # re-request must land on a COLD sibling.
            fleet["r0"].state = "draining"
            hits0 = METRICS.get_counter("directory.hits")
            pulls0 = METRICS.get_counter("directory.pulls")
            imp0 = METRICS.get_counter("batcher.kv_pages_imported")
            st, _, body = await _request(
                host, port, {"prompt": p_pull, "max_tokens": 8})
            assert st == 200, body
            assert body["choices"][0]["text"] == wants[p_pull]
            # The cold sibling served the PULLED pages, not a re-prefill.
            cached = body["usage"]["prompt_tokens_details"]["cached_tokens"]
            assert cached >= PAGE, body["usage"]
            assert METRICS.get_counter("directory.hits") > hits0
            assert METRICS.get_counter("directory.pulls") > pulls0
            assert METRICS.get_counter("batcher.kv_pages_imported") > imp0
            fleet["r0"].state = "healthy"
            # The mis-steer drill: p_miss caches on r0 (all idle again),
            # then r0 drains and the fired ``corrupt`` rule steers the
            # pull at r2 — which holds NOTHING for this prompt.  The
            # pull degrades to local recompute on r1: exact bytes, a
            # counted fallback, and no poisoned cache.
            st, _, body = await _request(
                host, port, {"prompt": p_miss, "max_tokens": 8})
            assert st == 200 and body["choices"][0]["text"] == wants[p_miss]
            fleet["r0"].state = "draining"
            fb0 = METRICS.get_counter("directory.pull_fallbacks")
            st, _, body = await _request(
                host, port, {"prompt": p_miss, "max_tokens": 8})
            assert st == 200, body
            assert body["choices"][0]["text"] == wants[p_miss]
            assert corrupt.fired == 1
            assert METRICS.get_counter("directory.pull_fallbacks") > fb0
            fleet["r0"].state = "healthy"
            _audit_all(fleet)
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 300))


def test_pull_degradation_ladder_stale_drop_corrupt_dup(warmed):
    """The pull path's remaining drills, one leg per fault action.  A
    ``directory.lookup:drop`` reads the hit as stale (counted, local
    recompute); an ``xfer.pull:drop`` refuses the export (counted
    rejected fallback); ``:corrupt`` flips bytes post-checksum so the
    puller NACKs every attempt (counted, cache unpoisoned); ``:dup``
    ships the verified frame twice and the receiver absorbs the
    duplicate — the pull still lands.  Every leg byte-exact."""
    tiny = warmed
    legs = {
        "stale": "stale leg! " + LONG,
        "drop": "drop leg!! " + LONG,
        "corrupt": "flip leg!! " + LONG,
        "dup": "dup leg!!! " + LONG,
    }
    wants = expected_texts(tiny, [(p, 8) for p in legs.values()])
    plane = FaultPlane()

    async def driver():
        fleet = ReplicaFleet([_factory(tiny)] * 2, probe_interval_s=0.05,
                             probe_timeout_s=2.0)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, faults=plane,
        )
        await fleet.start()
        # xfer.pull fires on the SOURCE replica's serving loop off the
        # batcher's plane — arm the same plane fleet-wide.
        for h in fleet.replicas:
            h.server.batcher.faults = plane
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=60.0)

            async def serve_then_redo(p):
                """Serve p (all idle: lands r0, caches there), drain r0,
                re-request so the cold sibling consults the directory."""
                st, _, body = await _request(
                    host, port, {"prompt": p, "max_tokens": 8})
                assert st == 200, body
                assert body["choices"][0]["text"] == wants[p]
                fleet["r0"].state = "draining"
                st, _, body = await _request(
                    host, port, {"prompt": p, "max_tokens": 8})
                assert st == 200, body
                assert body["choices"][0]["text"] == wants[p]
                fleet["r0"].state = "healthy"
                return body

            # -- directory.lookup:drop: the stale-answer leg ------------
            rule = plane.add("directory.lookup", "drop", when="1")
            sd0 = METRICS.get_counter("directory.stale_drops")
            fb0 = METRICS.get_counter("directory.pull_fallbacks.stale")
            await serve_then_redo(legs["stale"])
            assert rule.fired == 1
            assert METRICS.get_counter("directory.stale_drops") > sd0
            assert METRICS.get_counter(
                "directory.pull_fallbacks.stale") == fb0 + 1
            # -- xfer.pull:drop: the source refuses the export ----------
            rule = plane.add("xfer.pull", "drop", when="1")
            fb0 = METRICS.get_counter("directory.pull_fallbacks.rejected")
            await serve_then_redo(legs["drop"])
            assert rule.fired == 1
            assert METRICS.get_counter(
                "directory.pull_fallbacks.rejected") == fb0 + 1
            # -- xfer.pull:corrupt: post-checksum flip, every attempt
            # NACKed at the puller, recompute stays exact ---------------
            rule = plane.add("xfer.pull", "corrupt", when="1")
            fb0 = METRICS.get_counter("directory.pull_fallbacks.rejected")
            await serve_then_redo(legs["corrupt"])
            assert rule.fired == 1
            assert METRICS.get_counter(
                "directory.pull_fallbacks.rejected") == fb0 + 1
            # -- xfer.pull:dup: the duplicate is absorbed, the pull lands
            rule = plane.add("xfer.pull", "dup", when="1")
            dd0 = METRICS.get_counter("xfer.dup_deliveries")
            body = await serve_then_redo(legs["dup"])
            assert rule.fired == 1
            cached = body["usage"]["prompt_tokens_details"]["cached_tokens"]
            assert cached >= PAGE, body["usage"]
            assert METRICS.get_counter("xfer.dup_deliveries") == dd0 + 1
            _audit_all(fleet)
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 300))


# -- THE chaos acceptance: disaggregated elastic fleet under storm -----------


def test_elastic_disagg_chaos_storm(warmed):
    """ISSUE 18 acceptance: a two-tenant storm against a 1-prefill +
    2-decode fleet with the TIERED autoscaler armed.  Mid-storm the
    prefill tier scales up on handoff queue depth while one
    ``router.ledger:stall`` drill wedges (only) one admission; the
    aggressor sheds on the FLEET ledger with per-tenant Retry-Afters; a
    ``directory.lookup:corrupt`` drill mis-steers one pull into a
    counted local-recompute fallback; the idle tail drains a decode
    replica away gracefully and its directory entries self-invalidate.
    Every completion is byte-exact vs the unfaulted fixed-fleet
    reference and surviving pools audit clean."""
    tiny = warmed
    gold = [(f"gold storm {i:02d} " + LONG, 8) for i in range(4)]
    agg = [(f"agg flood {i:02d} " + LONG, 8) for i in range(6)]
    wants = expected_texts(tiny, gold + agg)
    est_one = len(ByteTokenizer().encode(agg[0][0])) + 8
    plane = FaultPlane()
    ledger_stall = plane.add("router.ledger", "stall", when="2", arg=0.3)
    corrupt = plane.add("directory.lookup", "corrupt", when="1")

    def role_factory(role):
        return _factory(tiny, role)

    async def driver():
        factories = [role_factory("prefill"),
                     role_factory("decode"), role_factory("decode")]
        fleet = ReplicaFleet(factories, names=["p0", "d0", "d1"],
                             probe_interval_s=0.05, probe_timeout_s=2.0,
                             faults=plane)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, handoff=True, faults=plane,
            tenant_weights={"gold": 2.0},
            # agg's fleet window holds ~3 requests' mass: the 6-deep
            # flood MUST shed on the fleet ledger mid-storm.
            tenant_quota_tps=(3.2 * est_one) / 8.0,
            tenant_rate_window_s=8.0,
        )
        scaler = TieredAutoscaler(
            fleet,
            prefill=TierPolicy(min_replicas=1, max_replicas=2,
                               up_load=0.4, down_load=0.05,
                               hysteresis=2, cooldown_s=0.05),
            decode=TierPolicy(min_replicas=1, max_replicas=2,
                              up_load=5.0,  # decode never scales UP here
                              down_load=0.05, hysteresis=3,
                              cooldown_s=0.05),
            prefill_factory=role_factory("prefill"),
            decode_factory=role_factory("decode"),
            drain_timeout_s=20.0, replica_capacity_tokens=112,
        )
        await fleet.start()
        for h in fleet.replicas:
            h.server.batcher.faults = plane
        host, port = await router.start()
        scaler._loop = asyncio.get_running_loop()
        try:
            assert await fleet.wait_healthy(timeout_s=120.0)
            results: dict[str, tuple[int, dict, dict]] = {}

            async def one(p, n, tenant):
                results[p] = await _request(
                    host, port, {"prompt": p, "max_tokens": n},
                    tenant=tenant)

            tasks = []

            async def storm():
                for (g, n), (a, m) in zip(gold, agg):
                    tasks.append(asyncio.ensure_future(one(a, m, "agg")))
                    await asyncio.sleep(0.03)
                    tasks.append(asyncio.ensure_future(one(g, n, "gold")))
                    await asyncio.sleep(0.03)
                for a, m in agg[len(gold):]:
                    tasks.append(asyncio.ensure_future(one(a, m, "agg")))
                    await asyncio.sleep(0.03)

            storm_task = asyncio.ensure_future(storm())
            # Mid-storm ticks: concurrent handoffs put the single
            # prefill replica's queue depth >= 1 for consecutive ticks
            # -> the PREFILL tier scales up while decode holds.
            # Only the PREFILL tier ticks during the storm: with warm
            # jit caches the staggered storm leaves the decode tier idle
            # gaps long enough to build a down-streak, and draining a
            # decode replica mid-storm would race the drill below (the
            # tail drives full ticks and pins the drain explicitly).
            scaled_up = False
            for _ in range(600):
                await asyncio.sleep(0.01)
                await scaler.tick_tier("prefill")
                if sum(1 for h in fleet.replicas
                       if h.role == "prefill") == 2:
                    scaled_up = True
                    break
            await storm_task
            await asyncio.gather(*tasks)
            assert scaled_up, "the storm never grew the prefill tier"
            assert METRICS.get_counter("autoscale.prefill.scale_ups") >= 1
            assert ledger_stall.fired == 1, "ledger stall never fired"
            # -- storm ledger ------------------------------------------
            completed = sheds = 0
            for p, (status, headers, body) in results.items():
                if status == 200:
                    completed += 1
                    assert body["choices"][0]["text"] == wants[p], p
                else:
                    sheds += 1
                    assert status in (429, 503), (p, status, body)
                    assert "retry-after" in headers, p
                    assert body["error"]["type"] == "overloaded_error"
            assert completed >= len(gold), "storm starved gold"
            agg_sheds = [
                r for r in results.values()
                if r[0] == 429
                and r[2]["error"].get("reason") == "tenant_quota"
            ]
            assert agg_sheds, "the flood was never fleet-ledger-shed"
            assert METRICS.get_counter("router.ledger.sheds") >= 1
            assert METRICS.get_counter("router.ledger.charges") >= completed
            # -- the mis-steer drill -----------------------------------
            # Re-request a completed prompt while its sticky decode
            # replica drains and the prefill tier is partitioned away:
            # the directory HIT fires the armed ``corrupt`` rule, which
            # finds no other reachable sibling to steer at -> counted
            # stale fallback -> local recompute, byte-exact (and the
            # empty prefill tier is a counted handoff fallback, the
            # bottomed-out-tier ladder).
            victim_p = next(p for p, r in results.items() if r[0] == 200)
            digs = router._digests(ByteTokenizer().encode(victim_p))
            src_name = router._affinity[digs[-1]][0]
            now = asyncio.get_running_loop().time()
            fleet[src_name].state = "draining"
            import math as _math

            pre_handles = [h for h in fleet.replicas
                           if h.role == "prefill"]
            for h in pre_handles:
                h.partitioned_until = _math.inf
            fb0 = METRICS.get_counter("directory.pull_fallbacks")
            hf0 = METRICS.get_counter(
                "router.handoff_fallbacks.no_prefill_replica")
            st, _, body = await _request(
                host, port, {"prompt": victim_p, "max_tokens":
                             dict(gold + agg)[victim_p]}, tenant="gold")
            assert st == 200, body
            assert body["choices"][0]["text"] == wants[victim_p]
            assert corrupt.fired == 1, "mis-steer drill never fired"
            assert METRICS.get_counter("directory.pull_fallbacks") > fb0
            assert METRICS.get_counter(
                "router.handoff_fallbacks.no_prefill_replica") > hf0
            fleet[src_name].state = "healthy"
            for h in pre_handles:
                h.partitioned_until = 0.0
            # -- graceful decode drain in the tail ---------------------
            sd0 = METRICS.get_counter("autoscale.decode.scale_downs")
            drained = False
            for _ in range(600):
                await asyncio.sleep(0.02)
                await scaler.tick()
                if sum(1 for h in fleet.replicas
                       if h.role == "decode") == 1:
                    drained = True
                    break
            assert drained, "the idle tail never drained the decode tier"
            assert METRICS.get_counter(
                "autoscale.decode.scale_downs") == sd0 + 1
            # Directory entries for the drained-away replica
            # self-invalidate into counted misses; the survivor serves
            # the same bytes via local recompute or its own cache.
            gone = next(n for n in ("d0", "d1")
                        if n not in fleet._by_name)
            stale_p = next(
                (p for p, r in results.items() if r[0] == 200
                 and router._affinity.get(
                     router._digests(ByteTokenizer().encode(p))[-1],
                     (None,))[0] == gone),
                None)
            if stale_p is not None:
                s0 = METRICS.get_counter("directory.stale_drops")
                st, _, body = await _request(
                    host, port, {"prompt": stale_p, "max_tokens":
                                 dict(gold + agg)[stale_p]},
                    tenant="gold")
                assert st == 200, body
                assert body["choices"][0]["text"] == wants[stale_p]
                assert METRICS.get_counter("directory.stale_drops") > s0
            # -- steady state ------------------------------------------
            for _ in range(400):
                if all(not h.inflight for h in fleet.replicas):
                    break
                await asyncio.sleep(0.02)
            _audit_all(fleet)
        finally:
            await router.stop()
            await fleet.stop()

    asyncio.run(asyncio.wait_for(driver(), 550))
