"""Child process for the multi-host tests (run via subprocess, not
collected by pytest): joins a 2-process jax.distributed runtime on CPU,
runs a cross-process psum over the global mesh, and registers with the
control-plane coordinator as a worker host.

With a 4th argument (a shard-store dir) the child instead enters SERVE
mode: it registers a WorkerHost whose engine spans the GLOBAL 4-device
mesh (data=2 over the two processes x model=2 local) and serves GENERATE
commands until the coordinator sends SHUTDOWN — the multi-host serving
round-trip (BASELINE config 5).

Usage: python multihost_child.py <process_id> <jax_port> <coord_port> [store_dir]
"""

import asyncio
import os
import sys

# 2 local devices per process -> a 4-device global mesh across 2 "hosts",
# the smallest shape that exercises both intra- and inter-process axes.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from distributed_llms_tpu.cluster.distributed import initialize_distributed
from distributed_llms_tpu.cluster.worker import WorkerHost
from distributed_llms_tpu.core.config import ClusterConfig


def serve(cfg: ClusterConfig, coord_port: int) -> None:
    """SERVE mode: worker over the global (cross-process) mesh; the engine's
    collectives span both OS processes, so the coordinator must dispatch
    GENERATE to all workers at once (Coordinator.generate_spmd).  The shard
    store reaches the worker via the coordinator's PLACE_SHARDS payload."""
    from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig

    rt = RuntimeConfig(max_decode_steps=8)
    mesh_cfg = MeshConfig(data=2, model=2)  # data crosses the process boundary

    async def run() -> None:
        w = WorkerHost("127.0.0.1", coord_port, cfg=cfg, rt=rt, mesh_cfg=mesh_cfg)
        await w.run()  # returns after the coordinator's SHUTDOWN

    asyncio.run(run())
    print("CHILD_OK serve", flush=True)
    jax.distributed.shutdown()


def main() -> None:
    process_id, jax_port, coord_port = (int(a) for a in sys.argv[1:4])
    cfg = ClusterConfig(
        distributed_coordinator=f"127.0.0.1:{jax_port}",
        num_processes=2,
        process_id=process_id,
        heartbeat_interval_s=0.2,
        heartbeat_timeout_s=120.0,
    )
    initialize_distributed(cfg)
    if len(sys.argv) > 4:
        serve(cfg, coord_port)
        return
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2

    # Data plane: a psum spanning both processes — the collective the
    # reference's star topology cannot express (every tensor transited the
    # master; SURVEY §2.4).
    mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(2, 2), ("host", "local"))
    f = jax.jit(
        jax.shard_map(
            lambda a: jax.lax.psum(a, ("host", "local")),
            mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(("host", "local")),
            out_specs=jax.sharding.PartitionSpec(),
        )
    )
    arr = jax.make_array_from_process_local_data(
        jax.NamedSharding(mesh, jax.sharding.PartitionSpec(("host", "local"))),
        np.full((2,), float(process_id + 1), np.float32),
    )
    total = float(np.asarray(f(arr))[0])
    assert total == 6.0, total  # proc0 contributes 1+1, proc1 contributes 2+2

    # Control plane: register with the product coordinator like any host.
    async def register_and_report() -> None:
        w = WorkerHost("127.0.0.1", coord_port, cfg=cfg)
        task = asyncio.create_task(w.run())
        for _ in range(200):
            if w.worker_id is not None:
                break
            await asyncio.sleep(0.05)
        assert w.worker_id is not None, "never registered"
        await asyncio.sleep(0.5)  # a few heartbeats
        task.cancel()

    asyncio.run(register_and_report())
    print(f"CHILD_OK process={process_id} psum={total}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
