"""Prometheus exposition endpoint (implementation.md:34-37, :146-157 were
future scope in the reference; here it is scrape-tested over real HTTP)."""

import asyncio
import dataclasses
import json

import pytest

from distributed_llms_tpu.cluster.coordinator import Coordinator
from distributed_llms_tpu.core.config import ClusterConfig
from distributed_llms_tpu.core.observability import METRICS, Metrics


def test_prometheus_text_rendering():
    m = Metrics()
    m.inc("coordinator.tasks_completed", 3)
    m.set_gauge("coordinator.workers", 2)
    for v in (0.1, 0.2, 0.3):
        m.observe("hop.latency_s", v)
    text = m.prometheus_text()
    assert "# TYPE coordinator_tasks_completed counter" in text
    assert "coordinator_tasks_completed 3.0" in text
    assert "# TYPE coordinator_workers gauge" in text
    assert "coordinator_workers 2" in text
    assert "# TYPE hop_latency_s summary" in text
    assert 'hop_latency_s{quantile="0.50"} 0.2' in text
    assert "hop_latency_s_count 3" in text
    assert abs(float(text.split("hop_latency_s_sum ")[1].splitlines()[0]) - 0.6) < 1e-9
    assert text.endswith("\n")


async def _http_get(port: int, path: str) -> tuple[int, dict[str, str], str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, body = raw.decode().partition("\r\n\r\n")
    lines = head.split("\r\n")
    code = int(lines[0].split()[1])
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    return code, headers, body


@pytest.mark.asyncio
async def test_coordinator_metrics_scrape():
    cfg = ClusterConfig(coordinator_host="127.0.0.1", coordinator_port=0,
                        metrics_port=0)
    coord = Coordinator(cfg)
    await coord.start()
    try:
        assert coord.metrics_port is not None
        METRICS.inc("scrape.test_counter")

        code, headers, body = await _http_get(coord.metrics_port, "/metrics")
        assert code == 200
        assert headers["content-type"].startswith("text/plain; version=0.0.4")
        assert "scrape_test_counter" in body

        code, _, body = await _http_get(coord.metrics_port, "/healthz")
        assert (code, body) == (200, "ok\n")

        code, headers, body = await _http_get(coord.metrics_port, "/status")
        assert code == 200
        assert headers["content-type"] == "application/json"
        status = json.loads(body)
        assert status["workers"] == {} and status["queued_tasks"] == 0

        code, _, _ = await _http_get(coord.metrics_port, "/nope")
        assert code == 404
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_stop_not_blocked_by_idle_connection():
    """A client that connects and sends nothing must not hold up shutdown
    (Python 3.12's Server.wait_closed waits for in-flight handlers)."""
    coord = Coordinator(ClusterConfig(coordinator_host="127.0.0.1",
                                      coordinator_port=0, metrics_port=0))
    await coord.start()
    _, writer = await asyncio.open_connection("127.0.0.1", coord.metrics_port)
    try:
        await asyncio.wait_for(coord.stop(), timeout=3.0)
    finally:
        writer.close()


@pytest.mark.asyncio
async def test_oversized_request_line_is_handled():
    """A request line beyond the StreamReader's buffer limit must close the
    connection quietly, not leak an unhandled LimitOverrunError."""
    coord = Coordinator(ClusterConfig(coordinator_host="127.0.0.1",
                                      coordinator_port=0, metrics_port=0))
    await coord.start()
    try:
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", coord.metrics_port
        )
        writer.write(b"GET /" + b"x" * 70_000)
        await writer.drain()
        writer.write(b" HTTP/1.1\r\n\r\n")
        await writer.drain()
        body = await asyncio.wait_for(reader.read(), timeout=5.0)
        # Either an early 414 or a plain close is fine; no hang, no traceback.
        assert body == b"" or b"414" in body
        writer.close()
    finally:
        await coord.stop()


@pytest.mark.asyncio
async def test_metrics_disabled_by_default():
    coord = Coordinator(ClusterConfig(coordinator_host="127.0.0.1",
                                      coordinator_port=0))
    await coord.start()
    try:
        assert coord.metrics_port is None
    finally:
        await coord.stop()
