"""Multi-host smoke test: two real OS processes join one jax.distributed
runtime (CPU + gloo collectives), run a cross-process psum, and register
with the product coordinator — the only feasible single-machine validation
of BASELINE config 5's multi-host path (SURVEY §2.4: the reference's
"multi-node" story was TCP workers in a star; here it is one global SPMD
runtime plus a thin control plane)."""

import asyncio
import os
import socket
import subprocess
import sys

import pytest

from distributed_llms_tpu.cluster.coordinator import Coordinator
from distributed_llms_tpu.core.config import ClusterConfig

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.asyncio
async def test_two_process_distributed_init_and_registration():
    jax_port = _free_port()
    coord = Coordinator(ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=60.0,
    ))
    await coord.start()
    procs: list[subprocess.Popen] = []
    try:
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        for pid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, CHILD, str(pid), str(jax_port), str(coord.port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            ))

        async def drain(p: subprocess.Popen) -> str:
            return await asyncio.to_thread(lambda: p.communicate(timeout=240)[0])

        async def watch_registrations() -> int:
            seen = 0
            while any(p.poll() is None for p in procs):
                seen = max(seen, len(coord.workers))
                await asyncio.sleep(0.05)
            return seen

        watcher = asyncio.create_task(watch_registrations())
        outs = await asyncio.gather(*(drain(p) for p in procs))
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"child rc={p.returncode}:\n{out[-2000:]}"
            assert "CHILD_OK" in out, out[-2000:]
        # Both real processes were registered with the control plane at once.
        assert await watcher == 2
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        await coord.stop()


@pytest.mark.asyncio
async def test_two_process_generate_roundtrip(tmp_path):
    """Multi-host SERVING round-trip (BASELINE config 5's only feasible
    single-machine validation): two OS processes form one 4-device global
    mesh (data=2 across processes x model=2 local), place a tiny model from
    a real shard store, and serve one GENERATE dispatched SPMD to both
    workers — decoded tokens must match the single-process engine."""
    import jax

    from distributed_llms_tpu.checkpoint import store as store_lib
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.models import model as model_lib, presets
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    # float32: XLA's CPU AllReducePromotion pass crashes on bf16 collectives.
    cfg = presets.get_preset("llama-tiny", vocab_size=512, dtype="float32")
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_dir = str(tmp_path / "store")
    store_lib.save_shards(params, store_dir, num_shards=2, model_config=cfg)

    jax_port = _free_port()
    coord = Coordinator(ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=120.0,
        task_timeout_s=240.0,
    ))
    await coord.start()
    procs: list[subprocess.Popen] = []
    try:
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        for pid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, CHILD, str(pid), str(jax_port),
                 str(coord.port), store_dir],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            ))
        for _ in range(600):  # distributed init + registration
            if len(coord.workers) == 2:
                break
            assert all(p.poll() is None for p in procs), "a child died early"
            await asyncio.sleep(0.1)
        assert len(coord.workers) == 2, f"workers: {list(coord.workers)}"

        coord.plan_shards(2, store_dir=store_dir)
        placed = await coord.place_shards(timeout=240.0)
        assert all("mesh" in r.get("resident", "") for r in placed.values()), placed

        out = await coord.generate_spmd(["hello multi host"], max_new_tokens=8)

        ref = InferenceEngine.from_store(
            store_dir, rt=RuntimeConfig(max_decode_steps=8)
        )
        expect = ref.generate_text(["hello multi host"], max_new_tokens=8)
        assert out["text"] == expect.text

        # Mixed-budget leg: the pool serves a requests list through the
        # MULTI-HOST continuous batcher (runtime/batcher.py host-mirrors
        # the scheduling state, so both processes drive identical
        # admit/decode sequences over the cross-process mesh).  Each
        # reply must equal the single-process engine at that request's
        # own budget — per-request budgets survive the mesh.
        mixed = [
            {"prompt": "hello multi host", "max_new_tokens": 3},
            {"prompt": "second request", "max_new_tokens": 8},
        ]
        out2 = await coord.generate_requests(mixed, timeout=240.0)
        for i, req in enumerate(mixed):
            want = ref.generate_text(
                [req["prompt"]], max_new_tokens=req["max_new_tokens"]
            )
            assert out2["text"][i] == want.text[0], (i, out2["text"], want.text)

        # Clean shutdown: workers exit their serve loop and the children
        # print CHILD_OK with rc=0.
        for wid in list(coord.workers):
            await coord.submit("SHUTDOWN", {}, worker_id=wid, timeout=30.0)

        async def drain(p: subprocess.Popen) -> str:
            return await asyncio.to_thread(lambda: p.communicate(timeout=120)[0])

        outs = await asyncio.gather(*(drain(p) for p in procs))
        for p, log_out in zip(procs, outs):
            assert p.returncode == 0, f"child rc={p.returncode}:\n{log_out[-2000:]}"
            assert "CHILD_OK serve" in log_out, log_out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        await coord.stop()
