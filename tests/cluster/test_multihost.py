"""Multi-host smoke test: two real OS processes join one jax.distributed
runtime (CPU + gloo collectives), run a cross-process psum, and register
with the product coordinator — the only feasible single-machine validation
of BASELINE config 5's multi-host path (SURVEY §2.4: the reference's
"multi-node" story was TCP workers in a star; here it is one global SPMD
runtime plus a thin control plane)."""

import asyncio
import os
import socket
import subprocess
import sys

import pytest

from distributed_llms_tpu.cluster.coordinator import Coordinator
from distributed_llms_tpu.core.config import ClusterConfig

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.asyncio
async def test_two_process_distributed_init_and_registration():
    jax_port = _free_port()
    coord = Coordinator(ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=60.0,
    ))
    await coord.start()
    procs: list[subprocess.Popen] = []
    try:
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        for pid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, CHILD, str(pid), str(jax_port), str(coord.port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            ))

        async def drain(p: subprocess.Popen) -> str:
            return await asyncio.to_thread(lambda: p.communicate(timeout=240)[0])

        async def watch_registrations() -> int:
            seen = 0
            while any(p.poll() is None for p in procs):
                seen = max(seen, len(coord.workers))
                await asyncio.sleep(0.05)
            return seen

        watcher = asyncio.create_task(watch_registrations())
        outs = await asyncio.gather(*(drain(p) for p in procs))
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"child rc={p.returncode}:\n{out[-2000:]}"
            assert "CHILD_OK" in out, out[-2000:]
        # Both real processes were registered with the control plane at once.
        assert await watcher == 2
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        await coord.stop()
