"""Mesh-native paged serving (runtime/batcher.py, PR 11): the paged KV
pool — and every feature stacked on it since PR 1 — serves on pure
data/tensor-parallel GSPMD meshes.

The acceptance contract pinned here:

- **Bytes are the contract.**  A tensor-parallel paged batcher serves
  temp-0 token streams BYTE-IDENTICAL to the single-device paged engine
  across the composition matrix: plain paged decode, automatic
  prefix-cache hits, chunked prefill, preemption + host-tier swap
  restore, the int8 QuantKVCache pool, and the dispatch-ahead overlap
  loop on or off.  Sharding changes placement, never results.
- **The pool actually shards.**  Every pool leaf splits its KV-head axis
  over 'model' (parallel.specs.page_pool_specs) — per-chip pool bytes
  divide by tp, which is the capacity claim of ROADMAP item 3.
- **Illegal layouts fail at construction.**  KV heads that do not divide
  over 'model', and the still-unsupported paged x pipelined combination,
  are rejected in milliseconds, not at the first decode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.models.model import QuantKVCache
from distributed_llms_tpu.parallel import api as api_lib
from distributed_llms_tpu.parallel.specs import page_pool_specs
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)  # 2 KV heads
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new):
    out = gen_lib.generate_tokens(
        params, cfg, jnp.asarray([ids], jnp.int32),
        jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
        max_new_tokens=n_new, eos_id=-1, pad_id=0,
    )
    return np.asarray(out)[0].tolist()


def _pm(cfg, devices8, data=1, model=2):
    return api_lib.make_parallel_model(
        cfg, MeshConfig(data=data, model=model),
        devices=devices8[: data * model],
    )


PAGED_KW = dict(batch_slots=2, max_len=64, chunk_steps=4, page_size=16,
                paged_pages=14)


def _ref(cfg, params, **kw):
    return ContinuousBatcher(cfg, params, **{**PAGED_KW, **kw})


def _mesh(cfg, params, devices8, data=1, model=2, **kw):
    pm = _pm(cfg, devices8, data=data, model=model)
    return ContinuousBatcher(
        cfg, pm.shard_params(params), parallel=pm, **{**PAGED_KW, **kw}
    )


def _drive(b, reqs):
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    b.assert_pool_consistent()
    return [res[r] for r in rids]


REQS = [([7, 1, 9], 6), ([4, 4, 4, 4, 4, 4], 12), ([100, 3, 5, 2], 3),
        ([11, 12], 15)]


# -- sharding layout --------------------------------------------------------


def test_pool_shards_kv_heads_over_model(tiny, devices8):
    """The tentpole's capacity claim: every pool leaf splits its KV-head
    axis over 'model' — per-chip pool bytes are 1/tp of the global pool."""
    cfg, params = tiny
    b = _mesh(cfg, params, devices8)
    for leaf in (b.cache.k, b.cache.v):
        assert not leaf.sharding.is_fully_replicated
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[3] == cfg.num_kv_heads // 2  # KV-head axis halves
        assert shard[:3] + shard[4:] == leaf.shape[:3] + leaf.shape[4:]
    # The spec registry matches what the batcher built (graftcheck GC2
    # audits the same function over the fake-mesh ladder).
    specs = page_pool_specs(cfg, b.pm.mesh)
    assert tuple(specs.k) == (None, None, None, "model", None)


def test_int8_pool_shards_scales_with_pages(tiny, devices8):
    cfg, params = tiny
    b = _mesh(cfg, params, devices8, kv_bits=8)
    assert isinstance(b.cache, QuantKVCache)
    for leaf in (b.cache.k, b.cache.v, b.cache.k_scale, b.cache.v_scale):
        assert not leaf.sharding.is_fully_replicated
        assert leaf.sharding.shard_shape(leaf.shape)[3] \
            == cfg.num_kv_heads // 2
    specs = page_pool_specs(cfg, b.pm.mesh, kv_bits=8)
    assert tuple(specs.k_scale) == (None, None, None, "model")


# -- byte-exactness matrix --------------------------------------------------


def test_mesh_paged_matches_single_device(tiny, devices8):
    """Plain paged serving on tp2: mixed budgets, slot reuse — byte-equal
    to the single-device paged engine AND to solo decodes."""
    cfg, params = tiny
    got_ref = _drive(_ref(cfg, params), REQS)
    got = _drive(_mesh(cfg, params, devices8), REQS)
    assert got == got_ref
    for out, (ids, n) in zip(got, REQS):
        assert out == solo(cfg, params, ids, n)


def test_mesh_paged_dp_x_tp(tiny, devices8):
    """data=2 x model=2: the scheduling plane replicates, the pool shards
    heads — results still byte-equal to the single-device paged engine."""
    cfg, params = tiny
    got_ref = _drive(_ref(cfg, params), REQS)
    got = _drive(_mesh(cfg, params, devices8, data=2, model=2), REQS)
    assert got == got_ref


def test_mesh_prefix_cache_hit_byte_exact(tiny, devices8):
    """Automatic prefix caching on the sharded pool: the second request's
    cached head is served from shared (sharded) pages; accounting and
    bytes match the single-device paged engine."""
    cfg, params = tiny
    shared = list(range(40, 58)) + [3, 3]
    reqs = [(shared + [11, 12], 6), (shared + [42], 8), ([4, 4, 4], 4)]

    ref = _ref(cfg, params, prefix_cache=True)
    got_ref = _drive(ref, reqs)
    b = _mesh(cfg, params, devices8, prefix_cache=True)
    got = _drive(b, reqs)
    assert got == got_ref
    assert b.prefix_cache.hit_tokens > 0, "mesh pool never shared pages"
    assert b.prefix_cached_tokens == ref.prefix_cached_tokens


def test_mesh_chunked_prefill_byte_exact(tiny, devices8):
    """Chunked prefill on the mesh (the guard lift): a long prompt chunks
    through prefill_chunk_step(pm=...) and finishes into sharded pool
    pages — bytes equal the single-device chunked run AND the monolithic
    mesh run."""
    cfg, params = tiny
    long = list(range(1, 40))
    reqs = [(long, 8), ([7, 7, 7], 6)]
    got_ref = _drive(_ref(cfg, params, prefill_chunk=8), reqs)
    got = _drive(_mesh(cfg, params, devices8, prefill_chunk=8), reqs)
    assert got == got_ref
    got_mono = _drive(_mesh(cfg, params, devices8), reqs)
    assert got == got_mono


def test_mesh_preempt_swap_byte_exact(tiny, devices8):
    """Overcommitted storm on a tight sharded pool with the host tier
    armed: victims swap raw SHARDED pages out to host RAM and restore
    byte-exact — streams equal the single-device run and solo decodes."""
    cfg, params = tiny
    storm = [([7, 1, 9, 2], 40), ([4, 4, 4, 4], 40), ([9, 8, 7, 3], 40)]
    kw = dict(batch_slots=3, paged_pages=9, host_pages=16)
    out0 = METRICS.get_counter("batcher.kv_swaps.out")
    got_ref = _drive(_ref(cfg, params, **kw), storm)
    b = _mesh(cfg, params, devices8, **kw)
    got = _drive(b, storm)
    assert got == got_ref
    for out, (ids, n) in zip(got, storm):
        assert out == solo(cfg, params, ids, n)
    assert b.preemptions >= 1, "storm never pressured the mesh pool"
    assert METRICS.get_counter("batcher.kv_swaps.out") > out0


def test_mesh_int8_pool_byte_exact_vs_single_device_int8(tiny, devices8):
    """int8 pages on the mesh: quantization is deterministic, so the tp2
    int8 stream is byte-identical to the single-device int8 stream (the
    int8-vs-bf16 parity bound is pinned in test_kv_tiering)."""
    cfg, params = tiny
    got_ref = _drive(_ref(cfg, params, kv_bits=8), REQS)
    got = _drive(_mesh(cfg, params, devices8, kv_bits=8), REQS)
    assert got == got_ref


def test_mesh_overlap_on_off_byte_exact(tiny, devices8):
    """The dispatch-ahead loop is mesh-legal (no more degrade): overlap on
    and off serve identical bytes on tp2, and the on-leg actually
    dispatches ahead."""
    cfg, params = tiny
    reqs = [([7, 1, 9], 24), ([4, 4, 4, 4], 24)]
    b_on = _mesh(cfg, params, devices8, overlap=True)
    got_on = _drive(b_on, reqs)
    assert b_on.overlap, "mesh batcher degraded the overlap loop"
    b_off = _mesh(cfg, params, devices8, overlap=False)
    assert got_on == _drive(b_off, reqs)
    assert b_on.overlap_stats["dispatched_ahead"] >= 1
    assert got_on == _drive(_ref(cfg, params, overlap=True), reqs)


# -- config rejections ------------------------------------------------------


def test_rejects_nondivisible_kv_heads(tiny, devices8):
    """llama-tiny has 2 KV heads: a model=4 mesh cannot shard the pool —
    construction must fail loudly, naming both numbers."""
    cfg, params = tiny
    pm = _pm(cfg, devices8, model=4)
    with pytest.raises(ValueError, match="num_kv_heads 2.*'model' \\(4\\)"):
        ContinuousBatcher(cfg, pm.shard_params(params), parallel=pm,
                          **PAGED_KW)


def test_rejects_paged_on_pipelined_mesh(tiny, devices8):
    cfg, params = tiny
    pm = api_lib.make_parallel_model(cfg, MeshConfig(pipe=2, model=4))
    with pytest.raises(ValueError, match="data/tensor-parallel"):
        ContinuousBatcher(cfg, params, parallel=pm, **PAGED_KW)


def test_engine_policy_explicit_vs_inherited(tiny, devices8, tmp_path):
    """engine.continuous_batcher on a mesh engine now passes paged mode
    through; only a non-divisible head count degrades (config-inherited)
    or errors (explicit)."""
    from distributed_llms_tpu.checkpoint import store as store_lib
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg, params = tiny
    store_lib.save_shards(params, str(tmp_path), num_shards=1,
                          model_config=cfg)
    eng = InferenceEngine.from_store(
        str(tmp_path), rt=RuntimeConfig(max_decode_steps=8),
        mesh_cfg=MeshConfig(data=4, model=2),
    )
    b = eng.continuous_batcher(batch_slots=4, max_len=64, paged_pages=14,
                               page_size=16, prefix_cache=True)
    assert b.paged and b.pm is not None and b.prefix_cache is not None
    rid = b.submit([5, 6, 7], max_new_tokens=5)
    assert b.run()[rid] == solo(cfg, params, [5, 6, 7], 5)

    eng4 = InferenceEngine.from_store(
        str(tmp_path), rt=RuntimeConfig(max_decode_steps=8, paged_pages=14,
                                        page_size=16),
        mesh_cfg=MeshConfig(data=2, model=4),
    )
    # Config-inherited paged_pages on a non-divisible mesh degrades...
    b4 = eng4.continuous_batcher(batch_slots=2, max_len=64)
    assert not b4.paged
    # ...an explicit request errors.
    with pytest.raises(ValueError, match="does not divide"):
        eng4.continuous_batcher(batch_slots=2, max_len=64, paged_pages=14)
