"""Multi-tenant QoS: weighted-fair scheduling, quotas, and the traffic
harness (runtime/scheduler.py TenantScheduler + runtime/workload.py +
the serving gateway's tenant surface).

Three layers, mirroring PR 13's policy/mechanism split:

1. POLICY (no model, no device): the TenantScheduler hooks — virtual-
   token-counter weighted fairness under skewed offered load, admission-
   charge/true-up accounting, the VTC starvation-guard lift, and
   resident-row caps — unit-tested with plain host data.
2. HARNESS (no model): the traffic-replay generator is deterministic,
   actually bursty, stamps shared prefixes, and its goodput/SLO scoring
   does the arithmetic the bench ladder stamps.
3. MECHANISM (tiny model, live HTTP): the tenant id rides the X-Tenant
   header (and the "tenant" body-field fallback) through the gateway
   into the batcher; the per-tenant token-rate gate sheds structured
   429s with the TENANT's own Retry-After (and the tenant.quota drill
   forces one); weighted-fair admission really reorders a skewed
   backlog; ServingClient sends the header and surfaces shed reasons.
"""

import asyncio
import json
import random

import pytest

import jax

from distributed_llms_tpu.cluster.client import ServingClient
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import workload
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.scheduler import (
    HOOKS, MixedScheduler, Scheduler, TenantScheduler, make_scheduler,
    parse_tenant_weights,
)
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


# -- policy: TenantScheduler hooks without a model ---------------------------


class _Req:
    """Queue-entry stand-in: the tenant hooks consume only
    (rid, priority, tenant, ids, max_new_tokens)."""

    _next = [0]

    def __init__(self, tenant=None, priority=0, prompt=10, budget=10):
        _Req._next[0] += 1
        self.rid = _Req._next[0]
        self.priority = priority
        self.tenant = tenant
        self.ids = [0] * prompt
        self.max_new_tokens = budget


def _serve_loop(sched, offered, rounds, emit=None):
    """Drive admission rounds against a standing backlog: each round
    admits one request, charges it, frees it (emitting its full budget
    unless ``emit`` says otherwise), and refills the tenant's backlog —
    the steady-state skewed-offered-load shape.  Returns per-tenant
    service counts."""
    queue = [_Req(t) for t, n in offered.items() for _ in range(n)]
    served = {t: 0 for t in offered}
    for _ in range(rounds):
        req = sched.admission_order(queue)
        assert req is not None
        served[req.tenant] += 1
        sched.note_admitted(req, len(req.ids) + req.max_new_tokens)
        sched.note_freed(req, req.max_new_tokens if emit is None
                         else emit(req))
        queue.remove(req)
        queue.append(_Req(req.tenant))
    return served


def test_tenant_hooks_declared_on_every_policy():
    """The accounting hooks are DECLARED (HOOKS registry) and exist on
    every policy — tenant-blind ones as no-ops, so the batcher's
    delegation never branches on the policy class."""
    assert "note_admitted" in HOOKS and "note_freed" in HOOKS
    for cls in (Scheduler, MixedScheduler, TenantScheduler):
        pol = cls()
        for hook in HOOKS:
            assert callable(getattr(pol, hook)), (cls.__name__, hook)
    # Tenant-blind policies really are no-ops (no state accretes).
    base = MixedScheduler()
    base.note_admitted(_Req("a"), 100)
    base.note_freed(_Req("a"), 0)


def test_weighted_fair_order_under_skewed_load():
    """Both tenants keep a standing backlog; weights 4:1 must split
    service ~4:1 no matter that the aggressor offers 10x the requests —
    offered load buys NOTHING past your weighted share (VTC's claim)."""
    s = make_scheduler("mixed", tenant_weights="gold:4,free:1")
    assert isinstance(s, TenantScheduler)
    served = _serve_loop(s, {"gold": 2, "free": 20}, rounds=100)
    assert served["gold"] == 80 and served["free"] == 20
    # Equal weights, equal split — the aggressor's 20-deep backlog is
    # irrelevant.
    s2 = make_scheduler("mixed", tenant_weights="a:1,b:1")
    served = _serve_loop(s2, {"a": 1, "b": 20}, rounds=50)
    assert served["a"] == 25 and served["b"] == 25


def test_quota_accounting_charge_and_refund():
    """The admission charge is prompt + FULL budget over weight; the
    release true-up refunds what was never emitted, so a short
    completion is not billed like a long one."""
    s = TenantScheduler(tenant_weights={"a": 2.0})
    r = _Req("a", prompt=10, budget=30)
    s.note_admitted(r, 40)
    assert s._vtc["a"] == pytest.approx(20.0)   # 40 / weight 2
    assert s._resident["a"] == 1
    s.note_freed(r, 4)                          # emitted 4 of 30
    assert s._vtc["a"] == pytest.approx(7.0)    # (10+4)/2
    assert s._resident["a"] == 0
    # Unpaired / double frees are inert (preempt + resume re-pairs).
    s.note_freed(r, 4)
    assert s._vtc["a"] == pytest.approx(7.0)
    # The gauges rode along.
    assert METRICS.get_gauge("tenant.vtc.a") == pytest.approx(7.0)


def test_starvation_guard_vtc_lift():
    """A tenant idle through an aggressor's long run is LIFTED to the
    live minimum on return: it gets immediate service (lowest counter
    among backlogged tenants is the aggressor's own floor) but cannot
    monopolize the engine for its whole idle deficit — and the
    continuously-backlogged aggressor is never starved."""
    s = TenantScheduler(tenant_weights={"agg": 1.0, "late": 1.0})
    served = _serve_loop(s, {"agg": 4}, rounds=40)
    assert served == {"agg": 40}
    floor = s._vtc["agg"]
    # The late tenant arrives with an empty history...
    s.admission_order([_Req("late"), _Req("agg")])
    # ...lifted to the aggressor's floor, not credited 40 rounds of idle.
    assert s._vtc["late"] >= floor
    # From here service alternates (equal weights), rather than "late"
    # drawing down a 40-round deficit while "agg" starves.
    served = _serve_loop(s, {"agg": 4, "late": 4}, rounds=20)
    assert served == {"agg": 10, "late": 10}


def test_resident_row_cap_defers_not_shed():
    """A tenant at tenant_max_rows defers (its queue entries wait;
    OTHER tenants admit past it); with every backlogged tenant capped,
    admission back-pressures (None) until a release frees a row."""
    s = TenantScheduler(tenant_weights={"a": 8.0, "b": 1.0},
                        tenant_max_rows=1)
    a1, a2, b1 = _Req("a"), _Req("a"), _Req("b")
    first = s.admission_order([a1, a2, b1])
    assert first is a1  # weight 8 -> "a" first
    s.note_admitted(a1, 20)
    # "a" is at its cap: its second request defers, "b" admits past it.
    second = s.admission_order([a2, b1])
    assert second is b1
    s.note_admitted(b1, 20)
    assert s.admission_order([a2]) is None  # everyone capped: defer
    s.note_freed(a1, 10)
    assert s.admission_order([a2]) is a2


def test_anonymous_and_priority_within_tenant():
    """Requests without a tenant share one anonymous bucket at the
    default weight; within a tenant the base order (priority desc, FIFO
    rid) still applies."""
    s = TenantScheduler(tenant_weights={"*": 2.0, "a": 2.0})
    lo, hi = _Req("a"), _Req("a", priority=5)
    anon = _Req(None)
    assert s.weight(None) == 2.0
    assert s.admission_order([lo, hi, anon]).rid in (hi.rid, anon.rid)
    # Within tenant "a": priority wins over FIFO.
    s2 = TenantScheduler(tenant_weights={"a": 1.0})
    assert s2.admission_order([lo, hi]) is hi


def test_tenant_config_validation():
    assert parse_tenant_weights(None) == {}
    assert parse_tenant_weights("a:4, b:1.5") == {"a": 4.0, "b": 1.5}
    assert parse_tenant_weights({"a": 2}) == {"a": 2.0}
    with pytest.raises(ValueError, match="name:weight"):
        parse_tenant_weights("a=4")
    with pytest.raises(ValueError, match="finite and > 0"):
        parse_tenant_weights("a:0")
    with pytest.raises(ValueError, match="not a number"):
        parse_tenant_weights("a:lots")
    with pytest.raises(ValueError, match="mixed"):
        make_scheduler("alternate", tenant_weights="a:1")
    with pytest.raises(ValueError, match="speculative"):
        make_scheduler("mixed", tenant_weights="a:1", speculative=True)
    with pytest.raises(ValueError, match="tenant_max_rows"):
        TenantScheduler(tenant_max_rows=0)


# -- harness: the traffic generator + scoring (no model) ---------------------


def _specs():
    return [
        workload.TenantSpec("agg", rate_rps=4.0, burst_rate_x=5.0,
                            burst_enter_hz=0.3, burst_exit_hz=0.5,
                            shared_frac=0.5),
        workload.TenantSpec("vic", rate_rps=1.0, prompt_len=(8, 24),
                            output_len=(4, 8)),
    ]


def test_workload_deterministic_and_sorted():
    a = workload.generate(_specs(), 15.0, seed=7,
                          diurnal_period_s=10.0, diurnal_amp=0.4)
    b = workload.generate(_specs(), 15.0, seed=7,
                          diurnal_period_s=10.0, diurnal_amp=0.4)
    assert a == b  # byte-identical offered load across serving legs
    assert a and all(x.t <= y.t for x, y in zip(a, a[1:]))
    assert {x.tenant for x in a} == {"agg", "vic"}
    assert workload.generate(_specs(), 15.0, seed=8) != a  # seed matters


def test_workload_bursts_raise_rate_and_prefixes_share():
    bursty = workload.generate([_specs()[0]], 60.0, seed=1)
    calm = workload.generate([workload.TenantSpec("agg", rate_rps=4.0)],
                             60.0, seed=1)
    # Burst state multiplies the rate 5x for ~38% of the time: the MMPP
    # trace must carry substantially more arrivals than the calm one.
    assert len(bursty) > 1.5 * len(calm)
    pfx = workload.shared_prefix(_specs()[0], 1)
    shared = [a for a in bursty if a.shared]
    assert shared and all(a.prompt.startswith(pfx) for a in shared)
    frac = len(shared) / len(bursty)
    assert 0.35 < frac < 0.65  # spec says 0.5
    # Output budgets respect the per-tenant mix.
    assert all(8 <= a.max_tokens <= 32 for a in bursty)


def test_workload_slo_scoring_arithmetic():
    R = workload.Record
    recs = [
        R(tenant="v", t_arrival=0, status=200, ttft_s=0.1, latency_s=0.5,
          tokens=20, itl_s=[0.01, 0.02]),
        R(tenant="v", t_arrival=1, status=200, ttft_s=3.0, latency_s=4.0,
          tokens=20),                                   # misses TTFT SLO
        R(tenant="v", t_arrival=2, status=429, retry_after=2.0,
          shed_reason="tenant_quota"),
        R(tenant="v", t_arrival=3, status=0),           # transport failure
    ]
    s = workload.summarize(recs, horizon_s=10.0, ttft_slo_s=1.0)["v"]
    assert s["offered"] == 4 and s["completed"] == 2
    assert s["shed"] == 1 and s["shed_with_retry_after"] == 1
    assert s["failed"] == 1
    assert s["slo_attainment"] == 0.5
    assert s["goodput_tok_s"] == pytest.approx(2.0)   # only the SLO-met 20
    assert s["tok_s"] == pytest.approx(4.0)
    assert s["itl_p95_s"] == pytest.approx(0.02)


def test_workload_validation():
    with pytest.raises(ValueError, match="rate_rps"):
        workload.TenantSpec("x", rate_rps=0.0)
    with pytest.raises(ValueError, match="shared_frac"):
        workload.TenantSpec("x", rate_rps=1.0, shared_frac=1.5)
    with pytest.raises(ValueError, match="burst_rate_x"):
        workload.TenantSpec("x", rate_rps=1.0, burst_rate_x=0.5)
    with pytest.raises(ValueError, match="duplicate"):
        workload.generate([workload.TenantSpec("x", rate_rps=1.0)] * 2, 1.0)
    with pytest.raises(ValueError, match="horizon"):
        workload.generate([workload.TenantSpec("x", rate_rps=1.0)], 0.0)


# -- mechanism: the gateway's tenant surface over live HTTP ------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _batcher(tiny, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    return ContinuousBatcher(cfg, params, tokenizer=tok, eos_id=tok.eos_id,
                             pad_id=tok.pad_id, **kw)


async def _post(host, port, body, tenant=None, path="/v1/completions"):
    reader, writer = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode()
    hdr = f"X-Tenant: {tenant}\r\n" if tenant else ""
    writer.write(
        f"POST {path} HTTP/1.1\r\nHost: t\r\n{hdr}"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    raw = await reader.read()
    writer.close()
    return status, headers, json.loads(raw) if raw.strip() else {}


def _serve(tiny, fn, *, batcher_kw=None, **srv_kw):
    async def driver():
        srv = InferenceServer(
            _batcher(tiny, **(batcher_kw or {})), model_name="tiny",
            host="127.0.0.1", port=0,
            batcher_factory=lambda: _batcher(tiny, **(batcher_kw or {})),
            **srv_kw,
        )
        host, port = await srv.start()
        try:
            return await asyncio.wait_for(fn(host, port, srv), 300)
        finally:
            await srv.stop()

    return asyncio.run(driver())


def test_tenant_header_body_field_and_validation(tiny):
    """X-Tenant header and "tenant" body field both bill the request
    (header wins); malformed ids 400 before any admission state."""
    kw = dict(tenant_weights="gold:4,free:1")

    async def fn(host, port, srv):
        s, _, b = await _post(host, port,
                              {"prompt": "hello there", "max_tokens": 4},
                              tenant="gold")
        assert s == 200, b
        s, _, _ = await _post(
            host, port,
            {"prompt": "hi!", "max_tokens": 4, "tenant": "free"})
        assert s == 200
        # Header beats the body field: the charge lands on "gold".
        g0 = METRICS.get_counter("tenant.requests.gold")
        s, _, _ = await _post(
            host, port,
            {"prompt": "hi!", "max_tokens": 4, "tenant": "free"},
            tenant="gold")
        assert s == 200
        assert METRICS.get_counter("tenant.requests.gold") == g0 + 1
        for bad in ("no spaces!", "x" * 65, 7):
            s, _, b = await _post(
                host, port,
                {"prompt": "hi!", "max_tokens": 4, "tenant": bad})
            assert s == 400
            assert "tenant" in b["error"]["message"]
        assert srv._inflight() == 0  # nothing leaked by the 400s
        # The scheduler accounted both tenants (vtc gauges live).
        assert METRICS.get_counter("tenant.requests.free") >= 1

    _serve(tiny, fn, batcher_kw=kw,
           tenant_weights={"gold": 4.0, "free": 1.0})


def test_tenant_rate_quota_sheds_with_per_tenant_retry_after(tiny):
    """A tenant over weight x quota_tps x window admitted-token mass
    sheds a structured 429: overloaded_error + reason "tenant_quota" +
    the TENANT's own Retry-After — while an under-quota tenant on the
    same server keeps serving, and the window aging out re-admits."""

    async def fn(host, port, srv):
        # free allowance: weight 1 x 5 tok/s x 2 s = 10 tokens.
        s, _, _ = await _post(host, port,
                              {"prompt": "four", "max_tokens": 4},
                              tenant="free")  # 9 tokens: fits
        assert s == 200
        # Fits the allowance alone (10 tokens) but not the used window:
        # the retryable shed — 429 + the tenant's OWN Retry-After.
        s, h, b = await _post(host, port,
                              {"prompt": "hello", "max_tokens": 4},
                              tenant="free")
        assert s == 429
        assert b["error"]["type"] == "overloaded_error"
        assert b["error"]["reason"] == "tenant_quota"
        ra = int(h["retry-after"])
        assert 1 <= ra <= 3  # the tenant's OWN window, not fleet load
        # BIGGER than free's entire window allowance: un-retryable — a
        # 400, never a 429 whose Retry-After could not come true.
        s, _, b = await _post(host, port,
                              {"prompt": "hello over quota",
                               "max_tokens": 30}, tenant="free")
        assert s == 400
        assert b["error"]["type"] == "invalid_request_error"
        assert "quota window holds at most" in b["error"]["message"]
        # gold (weight 4: 40-token allowance) is untouched by free's shed.
        s, _, _ = await _post(host, port,
                              {"prompt": "gold still serves",
                               "max_tokens": 8}, tenant="gold")
        assert s == 200
        assert METRICS.get_counter("tenant.shed.free") >= 1
        # The window ages out: free serves again after its Retry-After.
        await asyncio.sleep(ra + 0.2)
        s, _, _ = await _post(host, port,
                              {"prompt": "four", "max_tokens": 4},
                              tenant="free")
        assert s == 200

    _serve(tiny, fn, tenant_weights={"gold": 4.0, "free": 1.0},
           tenant_quota_tps=5.0, tenant_rate_window_s=2.0)


def test_tenant_quota_drill_forces_shed(tiny):
    """The tenant.quota fault site (action exhaust, tag = tenant)
    forces the over-quota path for exactly the tagged tenant — the
    per-tenant-shed drill used by the chaos acceptance storm."""
    plane = FaultPlane.parse("tenant.quota/free:exhaust@1")

    async def fn(host, port, srv):
        s, h, b = await _post(host, port,
                              {"prompt": "tiny", "max_tokens": 2},
                              tenant="free")  # far under quota — forced
        assert s == 429 and b["error"]["reason"] == "tenant_quota"
        assert "retry-after" in h
        s, _, _ = await _post(host, port,
                              {"prompt": "tiny", "max_tokens": 2},
                              tenant="gold")  # untagged tenant unaffected
        assert s == 200
        assert plane.rules[0].fired == 1

    _serve(tiny, fn, batcher_kw=dict(faults=plane),
           tenant_weights={"gold": 1.0, "free": 1.0},
           tenant_quota_tps=1000.0)


def test_weighted_fair_admission_reorders_backlog(tiny):
    """End to end through the engine: with one decode slot and a deep
    aggressor backlog queued FIRST, the victim's single request (higher
    weight, lower counter) admits ahead of most of it — rid order would
    have served it last."""
    b = _batcher(tiny, batch_slots=1,
                 tenant_weights="vic:4,agg:1", tenant_max_rows=1)
    order = []
    agg = [b.submit("aggressor flood " + str(i), max_new_tokens=6,
                    tenant="agg") for i in range(4)]
    vic = b.submit("victim!", max_new_tokens=6, tenant="vic")

    def cb(rid, new, done, lps):
        if done:
            order.append(rid)

    b.run(on_tokens=cb)
    assert set(order) == set(agg) | {vic}
    # The victim outranked at least the tail of the earlier-rid flood.
    assert order.index(vic) < 2, order


def test_serving_client_sends_tenant_and_surfaces_shed_reason():
    """ServingClient(tenant=): the X-Tenant header rides every request;
    a per-tenant 429 is retried on the server's Retry-After and its
    machine-readable reason is surfaced."""

    seen = {"tenants": [], "n": 0}

    async def fn():
        async def handle(reader, writer):
            req = await reader.readuntil(b"\r\n\r\n")
            headers = req.decode("latin-1").lower()
            for line in headers.split("\r\n"):
                if line.startswith("x-tenant:"):
                    seen["tenants"].append(line.split(":", 1)[1].strip())
            clen = 0
            for line in headers.split("\r\n"):
                if line.startswith("content-length:"):
                    clen = int(line.split(":", 1)[1])
            if clen:
                await reader.readexactly(clen)
            seen["n"] += 1
            if seen["n"] == 1:  # first hit: per-tenant shed
                body = json.dumps({"error": {
                    "message": "tenant 'acme' over its token-rate quota",
                    "type": "overloaded_error", "reason": "tenant_quota",
                }}).encode()
                writer.write(
                    b"HTTP/1.1 429 Too Many Requests\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Retry-After: 0\r\n"
                    + f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body)
            else:
                body = b'{"ok": true}'
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    + f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body)
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = ServingClient("127.0.0.1", port, tenant="acme",
                               retry_after_cap_s=0.0, backoff_base_s=0.0,
                               rng=random.Random(0))
        status, out = await client.completions(
            {"prompt": "x", "max_tokens": 1})
        server.close()
        await server.wait_closed()
        assert status == 200 and out == {"ok": True}
        assert seen["tenants"] == ["acme", "acme"]  # header on BOTH tries
        assert client.retries_taken == 1            # honored Retry-After
        assert client.last_shed_reason == "tenant_quota"
        assert client.tenant_sheds == 1

    asyncio.run(fn())


def test_engine_and_cli_plumbing(tiny):
    """RuntimeConfig.tenant_* thread through engine.continuous_batcher
    (explicit args win; ""/0 disable), the CLI declares the flags, and
    respawn rebuilds the tenant policy from the ctor snapshot."""
    import dataclasses

    from distributed_llms_tpu.cli.serve_main import _RUNTIME_FLAGS
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    assert RuntimeConfig().tenant_weights is None
    assert RuntimeConfig().tenant_quota_tps is None
    assert RuntimeConfig().tenant_max_rows is None
    assert _RUNTIME_FLAGS["tenant-weights"] == "tenant_weights"
    assert _RUNTIME_FLAGS["tenant-quota-tps"] == "tenant_quota_tps"
    assert _RUNTIME_FLAGS["tenant-max-rows"] == "tenant_max_rows"
    rt = dataclasses.replace(RuntimeConfig(), max_seq_len=64,
                             tenant_weights="a:2,b:1", tenant_max_rows=1)
    eng = InferenceEngine.from_preset("llama-tiny", rt=rt, vocab_size=512)
    b = eng.continuous_batcher(batch_slots=2, max_len=64)
    assert isinstance(b.sched, TenantScheduler)
    assert b.sched.tenant_weights == {"a": 2.0, "b": 1.0}
    assert b.sched.tenant_max_rows == 1
    # respawn(): fresh counters, same policy.
    assert isinstance(b.respawn().sched, TenantScheduler)
    # Explicit "" disables the config weights.
    b2 = eng.continuous_batcher(batch_slots=2, max_len=64,
                                tenant_weights="", tenant_max_rows=0)
    assert not isinstance(b2.sched, TenantScheduler)
