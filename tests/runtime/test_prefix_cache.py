"""Automatic prefix caching: hash-block KV reuse in the paged pool
(runtime/batcher.py PrefixCache + refcounted page allocator).

Invariants pinned here:
- exact tokens: at temperature 0 every request served with the automatic
  prefix cache ON — hit or miss — equals its solo generate_tokens run
  (extends tests/runtime/test_paged_batcher.py's pinned invariant);
- refcounting: a page shared by live rows is never freed or rewritten
  while any of them reads it; page accounting is conserved;
- LRU: unreferenced cached pages persist (later requests hit them) and
  are evicted oldest-first only under pool pressure;
- accounting: hit/miss/eviction counters (batcher-local and the METRICS
  registry the gateway exports at /metrics) say what actually happened;
- plumbing: per-request opt-out, the engine/config knob, and the named
  register_prefix path coexisting with the automatic cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new):
    out = gen_lib.generate_tokens(
        params, cfg, jnp.asarray([ids], jnp.int32),
        jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
        max_new_tokens=n_new,
    )
    return np.asarray(out)[0].tolist()


def _cached(cfg, params, **kw):
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("paged_pages", 16)
    kw.setdefault("prefix_cache", True)
    return ContinuousBatcher(cfg, params, **kw)


SHARED = list(np.random.RandomState(7).randint(1, 500, size=40))


def test_cache_hits_match_solo_and_count_honestly(tiny):
    """Shared-prefix traffic: later requests hit the first one's full
    prompt pages, prefill only their suffix, and still emit exactly their
    solo tokens; the counters record per-token hits/misses."""
    cfg, params = tiny
    reqs = [
        (SHARED + [7, 1, 9], 6),
        (SHARED + [4, 4], 5),
        (SHARED + [9, 9, 9, 9], 4),
        ([3, 2, 1], 5),  # unrelated: pure miss
    ]
    b = _cached(cfg, params, paged_pages=24)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"req {rid} diverged"
    pc = b.prefix_cache
    # 40-token shared prefix at page 16 -> 2 full pages (32 tokens) are
    # cacheable; requests 2 and 3 hit them.
    assert pc.lookups == 4 and pc.hits == 2
    assert pc.hit_tokens == 64
    assert b.prefix_cached_tokens[rids[1]] == 32
    assert b.prefix_cached_tokens[rids[3]] == 0

    # After the batch drains, the cached pages park in the LRU (not the
    # free list) and a second wave still hits them.
    assert len(pc.lru) > 0 and not b.page_refs
    rid2 = b.submit(SHARED + [5, 5], max_new_tokens=4)
    res2 = b.run()
    assert res2[rid2] == solo(cfg, params, SHARED + [5, 5], 4)
    assert pc.hit_tokens == 96


def test_refcount_never_frees_a_live_page(tiny):
    """Two live rows share cached pages; one finishing must not free them
    (the other still reads them through its page table), and total page
    accounting is conserved at every step."""
    cfg, params = tiny
    b = _cached(cfg, params, paged_pages=24, batch_slots=2)
    n_usable = 23  # pages 1..23; page 0 is scratch

    def accounted():
        lru = len(b.prefix_cache.lru)
        held = len(b.page_refs)
        free = len(b.free_pages)
        assert free + lru + held == n_usable, (free, lru, held)

    r1 = b.submit(SHARED + [7, 1, 9], max_new_tokens=12)
    r2 = b.submit(SHARED + [4, 4], max_new_tokens=2)
    b._admit_pending()  # both admit this round; row 2 hits row 1's pages
    accounted()
    shared_pages = [p for p, r in b.page_refs.items() if r == 2]
    assert len(shared_pages) == 2, "rows do not share the prefix pages"
    assert set(shared_pages) <= set(b.tables[0]) & set(b.tables[1])

    checked = {}

    def cb(rid, new, done, lps):
        # on_tokens fires between device chunks — the documented safe
        # point to inspect batcher state.  When the SHORT row finishes
        # (budget 2 vs 12, so first), the long row still reads the shared
        # pages: they must stay referenced, never on the free list.
        accounted()
        if done and rid == r2:
            for p in shared_pages:
                assert p not in b.free_pages
                assert b.page_refs.get(p) == 1
            checked["r2_done_first"] = True

    res = b.run(on_tokens=cb)
    assert checked.get("r2_done_first"), "short row did not finish first"
    assert res[r1] == solo(cfg, params, SHARED + [7, 1, 9], 12)
    assert res[r2] == solo(cfg, params, SHARED + [4, 4], 2)
    accounted()
    assert not b.page_refs  # everything released; cached pages in the LRU
    # The full allocator audit (partition + refcount-vs-row-holds) agrees.
    b.assert_pool_consistent()


def test_lru_eviction_under_pool_pressure(tiny):
    """A pool too small to keep every cached page resident evicts the
    coldest entries (counted) instead of back-pressuring admission, and
    serving stays exact throughout."""
    cfg, params = tiny
    b = _cached(cfg, params, paged_pages=5, batch_slots=1)
    p1 = list(np.random.RandomState(1).randint(1, 500, size=40))
    p2 = list(np.random.RandomState(2).randint(1, 500, size=40))
    r1 = b.submit(p1, max_new_tokens=4)
    assert b.run()[r1] == solo(cfg, params, p1, 4)
    assert len(b.prefix_cache.lru) == 2 and b.prefix_cache.evictions == 0
    # p2 needs 3 pages; only 2 are free -> the coldest cached page goes.
    r2 = b.submit(p2, max_new_tokens=4)
    assert b.run()[r2] == solo(cfg, params, p2, 4)
    assert b.prefix_cache.evictions >= 1
    # The evicted digest is gone; hash map and LRU stay consistent.
    pc = b.prefix_cache
    assert set(pc.by_hash.values()) == set(pc.page_hash)
    assert set(pc.lru) <= set(pc.page_hash)
    # p1 again: partially evicted prefix still serves exact tokens.
    r3 = b.submit(p1, max_new_tokens=4)
    assert b.run()[r3] == solo(cfg, params, p1, 4)


def test_per_request_optout_and_metrics_export(tiny):
    """prefix_cache=False skips both lookup and publication; the METRICS
    registry (what the gateway's /metrics renders) mirrors the batcher's
    own counters."""
    cfg, params = tiny
    before = METRICS.snapshot()["counters"]
    b = _cached(cfg, params, paged_pages=24)
    ids = SHARED + [1, 2, 3]
    r1 = b.submit(ids, max_new_tokens=4, prefix_cache=False)
    assert b.run()[r1] == solo(cfg, params, ids, 4)
    pc = b.prefix_cache
    assert pc.lookups == 0 and not pc.by_hash  # nothing published either
    assert b.prefix_cached_tokens[r1] == 0
    # Opted-in traffic populates and hits as usual.
    r2 = b.submit(ids, max_new_tokens=4)
    r3 = b.submit(ids, max_new_tokens=4)
    res = b.run()
    assert res[r2] == res[r3] == solo(cfg, params, ids, 4)
    assert pc.lookups == 2 and pc.hits == 1 and pc.hit_tokens == 32
    after = METRICS.snapshot()
    delta = lambda k: after["counters"].get(k, 0) - before.get(k, 0)  # noqa: E731
    assert delta("batcher.prefix_cache.lookups") == 2
    assert delta("batcher.prefix_cache.hits") == 1
    assert delta("batcher.prefix_cache.hit_tokens") == 32
    assert "batcher.prefix_cache.hit_rate" in after["gauges"]
    # The Prometheus rendering the gateway serves includes the family.
    assert "batcher_prefix_cache_hit_tokens" in METRICS.prometheus_text()


def test_named_prefix_and_sampling_compose(tiny):
    """register_prefix requests keep the legacy contiguous-prefix path on
    a cache-enabled batcher, and per-request sampled rows admit through
    the hit path without disturbing greedy neighbors."""
    cfg, params = tiny
    b = _cached(cfg, params, paged_pages=24)
    b.register_prefix("sys", SHARED[:10])
    r_named = b.submit([6, 6, 6], max_new_tokens=5, prefix="sys")
    r_seed = b.submit(SHARED + [8], max_new_tokens=4)
    res = b.run()
    assert res[r_named] == solo(cfg, params, SHARED[:10] + [6, 6, 6], 5)
    assert res[r_seed] == solo(cfg, params, SHARED + [8], 4)
    # A hot-sampled request admits through the cache-hit path; the greedy
    # neighbor submitted alongside stays exact.
    r_hot = b.submit(SHARED + [2, 2], max_new_tokens=5, temperature=1.5,
                     top_p=0.9)
    r_cold = b.submit(SHARED + [3, 3], max_new_tokens=5)
    res = b.run()
    assert len(res[r_hot]) == 5
    assert res[r_cold] == solo(cfg, params, SHARED + [3, 3], 5)
    assert b.prefix_cached_tokens[r_hot] == 32


def test_guards_and_engine_config_plumbing(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(cfg, params, max_len=64, prefix_cache=True)
    with pytest.raises(ValueError, match="prefix_cache"):
        b = _cached(cfg, params)
        b.submit([1, 2], max_new_tokens=2, prefix_cache="yes")

    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    rt = RuntimeConfig(max_seq_len=64, paged_pages=16, page_size=16,
                       prefix_cache=True)
    eng = InferenceEngine(cfg, rt, params)
    b = eng.continuous_batcher(batch_slots=2)
    assert b.prefix_cache is not None
    r1 = b.submit(SHARED + [5], max_new_tokens=3)
    r2 = b.submit(SHARED + [6], max_new_tokens=3)
    res = b.run()
    assert res[r1] == solo(cfg, params, SHARED + [5], 3)
    assert res[r2] == solo(cfg, params, SHARED + [6], 3)
    assert b.prefix_cache.hit_tokens == 32

    # Explicit request without a paged pool errors; a config-inherited
    # flag on a contiguous engine degrades silently (shared configs must
    # not error contiguous workers).
    rt_contig = RuntimeConfig(max_seq_len=64, prefix_cache=True)
    eng2 = InferenceEngine(cfg, rt_contig, params)
    assert eng2.continuous_batcher(batch_slots=2).prefix_cache is None
    with pytest.raises(ValueError, match="paged"):
        eng2.continuous_batcher(batch_slots=2, prefix_cache=True)
