"""Dispatch-ahead engine loop (runtime/batcher.py overlap plane).

The contract pinned here is EXACTNESS: with overlap on (the default),
temp-0 outputs — tokens, logprobs, streaming delivery sequences — are
byte-identical to the fully-synchronous loop (overlap off) across every
composition the engine serves: plain decode, automatic prefix caching,
chunked prefill, pool-pressure preemption with swap restore, int8 KV
pages, and speculative decoding.  Plus: the overlap plane actually
engages (dispatched-ahead chunks counted, device gap ~0 for them), every
sync trigger fires when it must (arrival mid-span, cancel mid-span,
growth under pressure), the batched digest chain equals the old per-page
construction, and a dispatched-ahead chunk still crashes/stalls/recovers
through the serving supervisor exactly.
"""

import asyncio
import hashlib
import json

import numpy as np
import pytest

import jax

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import (
    ContinuousBatcher, PrefixCache,
)
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def mk(tiny, overlap, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        overlap=overlap, **kw,
    )


PAGED = dict(paged_pages=24, page_size=16, prefix_cache=True)


def drive(b, reqs, **submit_kw):
    rids = [b.submit(p, max_new_tokens=n, **submit_kw) for p, n in reqs]
    res = b.run()
    return [res[r] for r in rids], [b.result_logprobs[r] for r in rids]


def both_legs(tiny, reqs, batcher_kw=None, submit_kw=None):
    """Run the same requests with overlap off and on; return
    ((toks_off, lps_off), (toks_on, lps_on), batcher_on)."""
    b_off = mk(tiny, False, **(batcher_kw or {}))
    off = drive(b_off, reqs, **(submit_kw or {}))
    b_on = mk(tiny, True, **(batcher_kw or {}))
    on = drive(b_on, reqs, **(submit_kw or {}))
    return off, on, b_on


# -- exactness across the composition matrix --------------------------------


def test_plain_decode_exact_on_vs_off(tiny):
    """Contiguous mode, staggered budgets (rows finish at different
    chunks): tokens AND logprobs byte-identical, overlap on vs off."""
    reqs = [("hello world", 17), ("abcdef", 9), ("xyz!", 23)]
    off, on, b_on = both_legs(tiny, reqs)
    assert on == off
    assert b_on.overlap_stats["dispatched_ahead"] > 0


def test_prefix_cache_exact_and_hit_accounting(tiny):
    """Paged + automatic prefix caching: shared-prefix traffic hits the
    cache identically (cached-token accounting equal) and bytes match."""
    shared = "the shared system prompt padding " * 2
    reqs = [(shared + "a", 10), (shared + "b", 10), ("solo", 8)]

    def leg(overlap):
        b = mk(tiny, overlap, **PAGED)
        rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
        res = b.run()
        cached = [b.prefix_cached_tokens[r] for r in rids]
        b.assert_pool_consistent()
        return [res[r] for r in rids], cached

    off, cached_off = leg(False)
    on, cached_on = leg(True)
    assert on == off
    assert cached_on == cached_off
    assert max(cached_on) >= 16  # the shared prefix really was served hot


def test_chunked_prefill_exact(tiny):
    """Chunked prefill (paged + prefix cache): a long prompt admitted in
    chunks composes with the overlap plane — under the default mixed
    schedule the bites ride the fused span and only the finishing splice
    syncs; under alternate every prefill round syncs (the scheduler's
    sync_triggers hook, runtime/scheduler.py).  Bytes identical on/off
    either way."""
    long_prompt = "a long prompt that must chunk " * 2
    reqs = [(long_prompt, 12), ("short", 10)]
    kw = dict(prefill_chunk=16, **PAGED)
    off, on, b_on = both_legs(tiny, reqs, batcher_kw=kw)
    assert on == off
    b_on.assert_pool_consistent()


def test_growth_and_preemption_exact_under_pressure(tiny):
    """A pool too small for both rows' full depth: growth escalates to
    preemption (exact recompute) mid-run.  Preemption only ever runs
    against synced mirrors — the span syncs when growth would need
    pressure — and the reunited streams stay byte-identical."""
    reqs = [("a" * 20, 40), ("b" * 25, 40)]
    kw = dict(paged_pages=8, page_size=16, prefix_cache=True,
              batch_slots=2)
    off, on, b_on = both_legs(tiny, reqs, batcher_kw=kw)
    assert on == off
    assert b_on.preemptions > 0  # the pressure leg really ran
    b_on.assert_pool_consistent()


def test_swap_preemption_exact(tiny):
    """Host-tier swap-preemption under the same pressure: victims park
    raw pages and restore byte-exact, overlap on vs off."""
    swaps0 = METRICS.get_counter("batcher.kv_swaps.in")
    reqs = [("a" * 20, 40), ("b" * 25, 40)]
    kw = dict(paged_pages=8, page_size=16, prefix_cache=True,
              batch_slots=2, host_pages=16)
    off, on, b_on = both_legs(tiny, reqs, batcher_kw=kw)
    assert on == off
    assert METRICS.get_counter("batcher.kv_swaps.in") > swaps0
    b_on.assert_pool_consistent()


def test_int8_kv_exact_on_vs_off(tiny):
    """int8 KV pages (deterministic quantized decode): overlap on vs off
    byte-identical at the quantized width too."""
    reqs = [("hello int8", 14), ("quant!", 10)]
    kw = dict(paged_pages=24, page_size=16, prefix_cache=True, kv_bits=8)
    off, on, b_on = both_legs(tiny, reqs, batcher_kw=kw)
    assert on == off
    b_on.assert_pool_consistent()


def test_per_request_sampling_exact(tiny):
    """Per-request sampling (traced per-row path) with a seeded RNG:
    the span plan keeps one compiled program and the RNG stream is
    chunk-aligned, so even sampled outputs match for a single batch."""
    reqs = [("sampled a", 12), ("sampled b", 12)]
    off, on, _ = both_legs(tiny, reqs,
                           submit_kw=dict(temperature=0.8, top_k=7))
    assert on == off


@pytest.mark.fragile_xla_cpu  # spec programs: fresh-process isolation
def test_speculative_exact_on_vs_off(tiny):
    """Speculative rounds chain device-resident exactly like plain
    chunks (draft cache included): greedy spec, overlap on vs off."""
    cfg, params = tiny
    dcfg = presets.get_preset("llama-tiny", vocab_size=512, num_layers=2)
    dparams = model_lib.init_params(jax.random.key(99), dcfg)
    reqs = [([7, 1, 9, 4, 2], 11), ([4, 4, 4], 7), ([11, 12], 13)]
    kw = dict(draft_params=dparams, draft_cfg=dcfg, spec_k=3,
              batch_slots=2, max_len=64)
    off, on, b_on = both_legs(tiny, reqs, batcher_kw=kw)
    assert on == off
    assert b_on.overlap_stats["dispatched_ahead"] > 0


# -- the overlap plane itself ------------------------------------------------


def test_streaming_deliveries_identical(tiny):
    """The full on_tokens sequence — rids, token groups, done flags —
    is identical on vs off (delivery shifts one dispatch later in wall
    time, never in content)."""
    reqs = [("stream me", 10), ("and me", 14)]
    streams = []
    for overlap in (False, True):
        b = mk(tiny, overlap)
        sink = []
        for p, n in reqs:
            b.submit(p, max_new_tokens=n)
        b.run(on_tokens=lambda rid, t, d, l, s=sink:
              s.append((rid, tuple(t), d, tuple(l or []))))
        streams.append(sink)
    assert streams[0] == streams[1]


def test_dispatch_ahead_engages_and_counts(tiny):
    """Steady decode with nothing queued: nearly every chunk dispatches
    ahead (device gap 0 by construction), the span ends in exactly one
    carry sync, chunk count matches the synchronous leg (no ghost
    chunks), and the METRICS mirrors move."""
    ahead0 = METRICS.get_counter("batcher.overlap.dispatched_ahead")
    syncs0 = METRICS.get_counter("batcher.overlap.carry_syncs")
    b_off = mk(tiny, False)
    drive(b_off, [("steady state", 33)])
    b_on = mk(tiny, True)
    drive(b_on, [("steady state", 33)])
    s = b_on.overlap_stats
    assert s["chunks"] == b_off.overlap_stats["chunks"]  # no ghosts
    assert s["dispatched_ahead"] == s["chunks"] - 1  # all but the first
    assert s["carry_syncs"] == 1
    assert s["device_gap_s"] == 0.0  # every gap sample was dispatched-ahead
    assert b_off.overlap_stats["dispatched_ahead"] == 0  # off leg: none
    assert METRICS.get_counter(
        "batcher.overlap.dispatched_ahead") - ahead0 == s["dispatched_ahead"]
    assert METRICS.get_counter(
        "batcher.overlap.carry_syncs") - syncs0 == 1


def test_arrival_mid_span_syncs_and_admits(tiny):
    """A request submitted mid-span (from the streaming callback, i.e.
    during a dispatched-ahead chunk's host window) forces a sync at the
    next boundary and admits — and the late arrival's tokens equal its
    solo run (temp-0 recompute-exactness, unchanged by overlap)."""
    b_solo = mk(tiny, True)
    r = b_solo.submit("late arrival", max_new_tokens=8)
    want_late = b_solo.run()[r]

    b = mk(tiny, True)
    first = b.submit("first request", max_new_tokens=24)
    late = []

    def cb(rid, toks, done, lps):
        if rid == first and not late and len(b.rows[0].emitted or []) >= 9:
            late.append(b.submit("late arrival", max_new_tokens=8))

    res = b.run(on_tokens=cb)
    assert late and res[late[0]] == want_late
    assert b.overlap_stats["carry_syncs"] >= 2  # the arrival split the span


def test_cancel_mid_span_stops_row(tiny):
    """cancel_row from the delivery callback while the carry is device-
    resident: the row stops at the next boundary (no budget-long ghost
    decode), nothing resurrects at the sync, and the pool audits clean."""
    b = mk(tiny, True, **PAGED)
    rid = b.submit("cancel me please", max_new_tokens=64)
    seen = []

    def cb(r, toks, done, lps):
        seen.extend(toks)
        if len(seen) >= 6:
            b.cancel_row(rid)

    res = b.run(on_tokens=cb)
    # Cancelled shortly after the 6th token: chunks already dispatched
    # ahead may land, a fresh budget-worth of decode must not.
    assert 6 <= len(res[rid]) <= 6 + 3 * b.chunk_steps
    assert not b.active.any() and b.rows[0].rid is None
    b.assert_pool_consistent()


def test_rng_stream_aligned_after_eos_ghost(tiny):
    """An all-rows-EOS mid-span dispatches one ghost chunk ahead; its
    RNG split is REFUNDED (a ghost samples nothing), so the engine's
    sampled stream stays aligned with the synchronous loop — a LATER
    sampled request produces identical tokens, overlap on vs off."""
    cfg, params = tiny
    tok = ByteTokenizer()

    def build(overlap, eos_id):
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=eos_id, pad_id=tok.pad_id,
            batch_slots=3, max_len=96, chunk_steps=4, overlap=overlap,
        )

    # Greedy probe: a token the run actually emits mid-span.
    probe = build(False, -1)
    r = probe.submit("ghost drill", max_new_tokens=33)
    eos_tok = probe.run()[r][7]

    def leg(overlap):
        b = build(overlap, eos_tok)
        r1 = b.submit("ghost drill", max_new_tokens=33)
        first = b.run()[r1]
        r2 = b.submit("then sampled", max_new_tokens=12, temperature=0.9)
        return first, b.run()[r2], b

    first_off, second_off, _ = leg(False)
    first_on, second_on, b_on = leg(True)
    assert first_on == first_off
    assert first_on[-1] == eos_tok and len(first_on) < 33  # EOS really hit
    # The ghost was dispatched (chunks exceed the synchronous count by
    # one) yet the sampled follow-up is identical: the split was refunded.
    assert second_on == second_off


def test_digest_chain_matches_per_page_reference(tiny):
    """The batched one-conversion digest chain is byte-identical to the
    old per-page np.asarray construction, at both kv widths."""
    ids = list(np.random.RandomState(3).randint(1, 500, size=77))
    for kv_bits, seed in ((16, b"dlt-prefix-cache-v1"),
                          (8, b"dlt-prefix-cache-v1:kv8")):
        prev, ref = seed, []
        for i in range(4):
            h = hashlib.blake2b(prev, digest_size=16)
            h.update(np.asarray(ids[i * 16: (i + 1) * 16],
                                np.int64).tobytes())
            prev = h.digest()
            ref.append(prev)
        assert PrefixCache.page_digests(ids, 16, 4, kv_bits=kv_bits) == ref


def test_prehash_fills_queued_digests(tiny):
    """The overlapped host window pre-hashes queued prompts: digests are
    memoized on the queued request, and the later admission serves the
    identical cache hit (prehash is a pure move of when the hash runs)."""
    b = mk(tiny, True, **PAGED)
    b.submit("x" * 40, max_new_tokens=4)
    req = b.queue_snapshot()[0]
    assert req.digests is None
    b._prehash_queued()
    want = b._page_digests(req.ids, len(req.ids) // 16)
    assert req.digests == want
    b._prehash_queued()  # idempotent
    assert req.digests == want
    res = b.run()
    assert len(res[req.rid]) == 4
    b.assert_pool_consistent()


def test_engine_config_plumbing(tiny):
    """RuntimeConfig.overlap flows through engine.continuous_batcher
    (explicit argument wins; default is on)."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    assert RuntimeConfig().overlap is True
    eng = InferenceEngine.from_preset("llama-tiny", vocab_size=512)
    assert eng.continuous_batcher(batch_slots=2, max_len=64).overlap is True
    eng.rt = RuntimeConfig(overlap=False)
    assert eng.continuous_batcher(batch_slots=2, max_len=64).overlap is False
    assert eng.continuous_batcher(
        batch_slots=2, max_len=64, overlap=True
    ).overlap is True


# -- fault plane: crash / stall with a dispatched-ahead chunk in flight ------


async def _request(host, port, method, path, body=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    data = await reader.read()
    writer.close()
    return status, data


def run_with_server(batcher, fn, **srv_kw):
    async def driver():
        srv = InferenceServer(batcher, model_name="tiny", host="127.0.0.1",
                              port=0, **srv_kw)
        host, port = await srv.start()
        try:
            return await asyncio.wait_for(fn(host, port, srv), timeout=600)
        finally:
            await srv.stop()

    return asyncio.run(driver())


def _server_batcher(tiny, faults=None):
    # Contiguous mode: a fault-armed PAGED engine deliberately stays on
    # the synchronous growth path (_grow_ahead returns False so drill
    # windows count exactly), which would keep these drills from ever
    # having a dispatched-ahead chunk in flight.
    return mk(tiny, True, batch_slots=2, faults=faults)


def test_supervisor_recovers_crash_at_dispatched_ahead_chunk(tiny):
    """batcher.decode raise@2 with one streaming request: the first
    chunk is in flight when the rule fires at the DISPATCHED-AHEAD
    boundary.  The supervisor respawns; the partially-streamed request
    fails structured; the engine then serves the same prompt byte-exact
    (and /healthz reports exactly one restart)."""
    b_ref = _server_batcher(tiny)
    r = b_ref.submit("crash drill", max_new_tokens=12)
    want = b_ref.tokenizer.decode(b_ref.run()[r])

    plane = FaultPlane.parse("batcher.decode:raise@2")
    restarts0 = METRICS.get_counter("server.engine_restarts")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "crash drill", "max_tokens": 12},
        )
        body = json.loads(raw)
        assert status == 500 and body["error"]["type"] == "engine_error"
        assert plane.rules[0].fired == 1
        # The respawn serves the same prompt byte-exact.
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "crash drill", "max_tokens": 12},
        )
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == want
        assert METRICS.get_counter("server.engine_restarts") - restarts0 == 1
        srv.batcher.assert_pool_consistent()

    run_with_server(_server_batcher(tiny, faults=plane), fn)


def test_supervisor_readmits_zero_streamed_exactly_overlap_on(tiny):
    """The PR-2 acceptance contract with the overlap plane ON: slots
    full, a queued request has streamed nothing when the engine crashes;
    the supervisor re-admits it under its original rid and its temp-0
    text is identical to an unfaulted run."""
    prompts = ["alpha", "bravo!", "charlie?"]
    wants = {}
    for p in prompts:
        b = _server_batcher(tiny)
        r = b.submit(p, max_new_tokens=8)
        wants[p] = b.tokenizer.decode(b.run()[r])

    plane = FaultPlane.parse("batcher.decode:raise@1")
    retried0 = METRICS.get_counter("server.requests_retried")

    async def fn(host, port, srv):
        outs = await asyncio.gather(*[
            _request(host, port, "POST", "/v1/completions",
                     {"prompt": p, "max_tokens": 8})
            for p in prompts
        ])
        completed = 0
        for (status, raw), p in zip(outs, prompts):
            body = json.loads(raw)
            if status == 200:
                assert body["choices"][0]["text"] == wants[p], p
                completed += 1
            else:
                assert body["error"]["type"] == "engine_error"
        # 2 slots admitted (and streamed) before the crash; the queued
        # third re-admits and completes exactly.
        assert completed >= 1
        assert METRICS.get_counter("server.requests_retried") > retried0
        srv.batcher.assert_pool_consistent()

    run_with_server(_server_batcher(tiny, faults=plane), fn)


def test_watchdog_trips_on_wedged_overlapped_chunk(tiny):
    """batcher.decode stall@2 fires at the dispatched-ahead boundary (a
    chunk already in flight): the engine thread wedges with work pending
    and /healthz flips unhealthy until the stall clears."""
    plane = FaultPlane.parse("batcher.decode:stall@2:1.2")

    async def fn(host, port, srv):
        req_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "wedge", "max_tokens": 16},
        ))
        unhealthy_seen = False
        for _ in range(100):
            status, raw = await _request(host, port, "GET", "/healthz")
            if status == 503 and json.loads(raw)["engine_stalled"]:
                unhealthy_seen = True
                break
            await asyncio.sleep(0.05)
        assert unhealthy_seen, "watchdog never flipped /healthz"
        status, _ = await req_task
        assert status == 200
        assert plane.rules[0].fired == 1

    run_with_server(_server_batcher(tiny, faults=plane), fn,
                    watchdog_timeout_s=0.3)
