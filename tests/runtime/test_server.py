"""HTTP serving gateway (runtime/server.py).

Strategy: a real asyncio server on an ephemeral port, driven by a raw
asyncio HTTP/SSE client (no client-library dependency — the same
fake-wire-but-real-sockets idea as the reference's protocol tests,
tests/network/test_protocol.py, upgraded from mocks to a live loopback).
Determinism: greedy sampling makes every response text equal the decode of
a solo batcher run on an identical fresh batcher.
"""

import asyncio
import json

import jax
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def make_batcher(tiny, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id, **kw
    )


def expected_text(tiny, prompt: str, n_new: int) -> str:
    """Greedy reference: a solo run on a fresh identical batcher."""
    b = make_batcher(tiny)
    rid = b.submit(prompt, max_new_tokens=n_new)
    return b.tokenizer.decode(b.run()[rid])


async def _request(host, port, method, path, body=None, read_body=True):
    """Minimal HTTP/1.1 client.  Returns (status, raw_body_bytes)."""
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    data = await reader.read() if read_body else b""
    writer.close()
    return status, data


async def _sse_events(host, port, path, body):
    """POST and parse the SSE stream into a list of data payloads."""
    reader, writer = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode()
    writer.write(
        f"POST {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    events = []
    while True:
        line = await reader.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            events.append("[DONE]")
            break
        events.append(json.loads(data))
    writer.close()
    return status, events


def run_with_server(batcher, fn, **srv_kw):
    """Start an InferenceServer on an ephemeral port, run fn(host, port)."""

    async def driver():
        srv = InferenceServer(batcher, model_name="tiny", host="127.0.0.1",
                              port=0, **srv_kw)
        host, port = await srv.start()
        try:
            return await asyncio.wait_for(fn(host, port, srv), timeout=600)
        finally:
            await srv.stop()

    return asyncio.run(driver())


# -- basics ----------------------------------------------------------------


def test_health_models_metrics(tiny):
    async def fn(host, port, srv):
        # /healthz is a real readiness report now: JSON body, 200 only
        # while the engine thread is alive, unstalled, and not draining.
        status, body = await _request(host, port, "GET", "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["engine_alive"] is True
        assert health["draining"] is False
        assert health["engine_restarts"] == 0
        assert "seconds_since_last_chunk" in health
        status, body = await _request(host, port, "GET", "/v1/models")
        assert status == 200
        models = json.loads(body)
        assert models["data"][0]["id"] == "tiny"
        status, body = await _request(host, port, "GET", "/metrics")
        assert status == 200
        status, _ = await _request(host, port, "GET", "/nope")
        assert status == 404
        # Latency histograms appear after serving a request: TTFT (first
        # mailbox delivery) and end-to-end request duration.
        status, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hi", "max_tokens": 3},
        )
        assert status == 200
        _, body = await _request(host, port, "GET", "/metrics")
        assert b"server_ttft_seconds" in body
        assert b"server_request_seconds" in body

    run_with_server(make_batcher(tiny), fn)


def test_completion_matches_solo_run(tiny):
    want = expected_text(tiny, "hello", 8)

    async def fn(host, port, srv):
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hello", "max_tokens": 8},
        )
        assert status == 200
        out = json.loads(body)
        assert out["object"] == "text_completion"
        choice = out["choices"][0]
        assert choice["text"] == want
        assert choice["finish_reason"] in ("length", "stop")
        assert out["usage"]["prompt_tokens"] == len(
            ByteTokenizer().encode("hello")
        )
        assert out["usage"]["completion_tokens"] == 8

    run_with_server(make_batcher(tiny), fn)


def test_concurrent_requests_each_match_solo(tiny):
    prompts = ["alpha", "bravo bravo", "charlie!", "d"]
    wants = [expected_text(tiny, p, 6) for p in prompts]

    async def fn(host, port, srv):
        outs = await asyncio.gather(*[
            _request(host, port, "POST", "/v1/completions",
                     {"prompt": p, "max_tokens": 6})
            for p in prompts
        ])
        for (status, body), want in zip(outs, wants):
            assert status == 200
            assert json.loads(body)["choices"][0]["text"] == want

    run_with_server(make_batcher(tiny), fn)


def test_streaming_concatenates_to_blocking_text(tiny):
    want = expected_text(tiny, "stream me", 10)

    async def fn(host, port, srv):
        status, events = await _sse_events(
            host, port, "/v1/completions",
            {"prompt": "stream me", "max_tokens": 10, "stream": True},
        )
        assert status == 200
        assert events[-1] == "[DONE]"
        text = "".join(e["choices"][0]["text"] for e in events[:-1])
        assert text == want
        finals = [e for e in events[:-1]
                  if e["choices"][0]["finish_reason"] is not None]
        assert len(finals) == 1

    run_with_server(make_batcher(tiny), fn)


def test_chat_completion_and_stream(tiny):
    tok = ByteTokenizer()
    messages = [{"role": "user", "content": "hi"}]
    want = expected_text(tiny, tok.apply_chat_template(messages), 6)

    async def fn(host, port, srv):
        status, body = await _request(
            host, port, "POST", "/v1/chat/completions",
            {"messages": messages, "max_tokens": 6},
        )
        assert status == 200
        out = json.loads(body)
        assert out["object"] == "chat.completion"
        assert out["choices"][0]["message"] == {
            "role": "assistant", "content": want,
        }
        status, events = await _sse_events(
            host, port, "/v1/chat/completions",
            {"messages": messages, "max_tokens": 6, "stream": True},
        )
        assert status == 200
        assert events[0]["choices"][0]["delta"] == {"role": "assistant"}
        text = "".join(
            e["choices"][0]["delta"].get("content", "")
            for e in events[1:-1]
        )
        assert text == want

    run_with_server(make_batcher(tiny), fn)


# -- stop sequences and cancellation ---------------------------------------


def test_stop_sequence_truncates_and_frees_row(tiny):
    full = expected_text(tiny, "stopper", 24)
    # Random byte-level output decodes to few chars (ids >= 256 are dropped,
    # invalid UTF-8 collapses to U+FFFD) — use a mid-text single char as the
    # stop string and compute the expected cut the same way the server does.
    assert len(full) >= 2
    stop = full[len(full) // 2]
    want = full[: full.find(stop)]

    async def fn(host, port, srv):
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "stopper", "max_tokens": 12, "stop": stop},
        )
        assert status == 200
        out = json.loads(body)
        assert out["choices"][0]["text"] == want
        assert out["choices"][0]["finish_reason"] == "stop"
        # The cancelled row must actually free: all slots empty soon after.
        for _ in range(100):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        assert all(r.rid is None for r in srv.batcher.rows)
        assert not srv._cancelled

    run_with_server(make_batcher(tiny), fn)


def test_client_disconnect_cancels_row(tiny):
    async def fn(host, port, srv):
        reader, writer = await asyncio.open_connection(host, port)
        body = json.dumps({
            "prompt": "bye now", "max_tokens": 100, "stream": True,
        }).encode()
        writer.write(
            f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body
        )
        await writer.drain()
        await reader.readline()  # status line — generation is live
        # Read a couple of SSE lines so at least one delivery happened.
        for _ in range(6):
            await reader.readline()
        writer.close()
        await writer.wait_closed()
        # The server must notice the dead socket at the next delta write
        # and cancel the row; the long token budget means this only ends
        # quickly IF cancellation works.
        for _ in range(200):
            if all(r.rid is None for r in srv.batcher.rows) and not srv._requests:
                break
            await asyncio.sleep(0.05)
        assert all(r.rid is None for r in srv.batcher.rows)
        assert not srv._requests

    run_with_server(make_batcher(tiny, max_len=128), fn)


# -- request validation ----------------------------------------------------


def test_bad_requests_rejected(tiny):
    async def fn(host, port, srv):
        cases = [
            ({}, 400),                                      # no prompt
            ({"prompt": ""}, 400),
            ({"prompt": "x", "max_tokens": 0}, 400),
            ({"prompt": "x", "max_tokens": True}, 400),     # bool is not int
            ({"prompt": "x", "n": 9}, 400),              # n capped at 8
            ({"prompt": "x", "n": 0}, 400),
            ({"prompt": "x", "temperature": -0.1}, 400),
            ({"prompt": "x", "top_p": 0.0}, 400),
            ({"prompt": "x", "top_k": -1}, 400),
            ({"prompt": "x", "top_k": 1.5}, 400),
            ({"prompt": "x", "top_k": True}, 400),
            ({"prompt": "x", "top_k": 2**40}, 400),  # > int32: 400, not crash
            ({"prompt": "x", "prefix_cache": "yes"}, 400),
            ({"prompt": "x", "stop": ["a", "b", "c", "d", "e"]}, 400),
            ({"prompt": "x" * 500, "max_tokens": 8}, 400),  # exceeds max_len
            ({"prompt": "x", "prefix": "nope"}, 400),       # unknown prefix
        ]
        for body, want_status in cases:
            status, _ = await _request(host, port, "POST", "/v1/completions", body)
            assert status == want_status, body
        # Malformed JSON body.
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            b"Content-Length: 5\r\n\r\n{oops"
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        assert status == 400
        writer.close()
        # Per-request sampling rides the batcher's per-row path — top_k
        # included (no longer rejected as engine-wide).
        status, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "ok", "max_tokens": 2, "temperature": 0.9,
             "top_p": 0.95, "top_k": 7},
        )
        assert status == 200

    run_with_server(make_batcher(tiny), fn)


def test_prefix_cache_usage_and_metrics(tiny):
    """Through a paged prefix-cache-enabled gateway: a repeated prompt's
    second request reports its cached prompt tokens in
    usage.prompt_tokens_details, text stays the deterministic greedy
    decode, the opt-out knob works, and the cache counters show on
    /metrics."""
    shared = "shared system prompt " * 3  # > one 16-token page of bytes
    prompt = shared + "tail"
    want = expected_text(tiny, prompt, 6)

    async def fn(host, port, srv):
        outs = []
        for body in (
            {"prompt": prompt, "max_tokens": 6},
            {"prompt": prompt, "max_tokens": 6},
            {"prompt": prompt, "max_tokens": 6, "prefix_cache": False},
        ):
            status, raw = await _request(
                host, port, "POST", "/v1/completions", body
            )
            assert status == 200
            outs.append(json.loads(raw))
        for out in outs:
            assert out["choices"][0]["text"] == want
        first, second, opted_out = outs
        assert first["usage"]["prompt_tokens_details"]["cached_tokens"] == 0
        assert second["usage"]["prompt_tokens_details"]["cached_tokens"] > 0
        assert opted_out["usage"]["prompt_tokens_details"]["cached_tokens"] == 0
        _, body = await _request(host, port, "GET", "/metrics")
        assert b"batcher_prefix_cache_hit_tokens" in body
        assert b"batcher_prefix_cache_lookups" in body

    run_with_server(
        make_batcher(tiny, max_len=96, paged_pages=19, page_size=16,
                     prefix_cache=True),
        fn,
    )


def test_chunked_body_rejected(tiny):
    async def fn(host, port, srv):
        # Only Content-Length bodies are read; chunked must fail loudly
        # (501), not as a misleading "'prompt' missing" 400.
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        assert status == 501
        writer.close()

    run_with_server(make_batcher(tiny), fn)


def test_graceful_drain_finishes_in_flight(tiny):
    """stop(drain_timeout>0): new requests get 500 immediately, in-flight
    ones run to completion (full token budget, finish_reason length) —
    the SIGTERM semantics of dlt-serve --drain-timeout."""
    async def fn(host, port, srv):
        # 64 tokens of budget (~16 scheduling chunks) so the request is
        # reliably still in flight when the drain starts — 24 used to
        # complete inside one poll interval on a warm jit cache and flake
        # the srv._requests check below.
        req_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hello", "max_tokens": 64},
        ))
        for _ in range(500):  # wait until the request is registered
            if srv._requests:
                break
            await asyncio.sleep(0.01)
        assert srv._requests
        stop_task = asyncio.create_task(srv.stop(drain_timeout=60.0))
        await asyncio.sleep(0)  # let stop() flip _draining
        status_new, body_new = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "x", "max_tokens": 2},
        )
        # 503, not 500: load balancers treat it as retry-elsewhere.
        assert status_new == 503 and b"draining" in body_new
        status, body = await req_task
        assert status == 200
        out = json.loads(body)
        assert out["usage"]["completion_tokens"] == 64  # NOT cancelled
        await stop_task

    run_with_server(make_batcher(tiny), fn)


def test_force_stop_cuts_graceful_drain_short(tiny):
    """Second-SIGTERM semantics: force_stop() mid-drain cancels in-flight
    rows at their next chunk instead of letting them run to completion —
    the drain returns promptly and the client gets a PARTIAL response.
    A stall fault paces every chunk so the request is deterministically
    still in flight when the force-stop lands (a warm jit cache can
    otherwise finish 64 tokens inside the test's reaction time)."""
    from distributed_llms_tpu.runtime.faults import FaultPlane

    plane = FaultPlane.parse("batcher.decode:stall@1+:0.05")

    async def fn(host, port, srv):
        req_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "linger", "max_tokens": 64},
        ))
        for _ in range(500):  # wait until the request is in flight
            if srv._requests:
                break
            await asyncio.sleep(0.01)
        assert srv._requests
        t0 = asyncio.get_running_loop().time()
        stop_task = asyncio.create_task(srv.stop(drain_timeout=60.0))
        await asyncio.sleep(0.05)  # the drain is now waiting on the request
        assert not stop_task.done()
        srv.force_stop()  # second SIGTERM: cut the drain short
        status, body = await req_task
        await asyncio.wait_for(stop_task, timeout=30)
        # Nowhere near the 60 s drain deadline.
        assert asyncio.get_running_loop().time() - t0 < 30
        assert status == 200
        out = json.loads(body)
        # Cancelled at a chunk boundary: fewer tokens than requested.
        assert 0 < out["usage"]["completion_tokens"] < 64

    run_with_server(make_batcher(tiny, max_len=128, faults=plane), fn)


def test_force_stop_with_just_queued_request(tiny):
    """Shutdown racing a just-queued request: the request lands in the
    batcher queue as force_stop() flips _stopping — the engine's stopping
    drain must still answer its mailbox (a structured shutdown error), not
    strand the handler forever."""
    from distributed_llms_tpu.runtime.server import _Mailbox

    async def fn(host, port, srv):
        rid = srv.batcher.next_rid
        mbox = _Mailbox()
        srv._requests[rid] = mbox
        assert srv.batcher.submit("raced", max_new_tokens=8) == rid
        srv.force_stop()  # immediate: skips the drain entirely
        srv._work.set()
        toks, done, err, _lps = await asyncio.wait_for(mbox.queue.get(), 10)
        assert done and err == "server is shutting down"
        srv._requests.pop(rid, None)

    run_with_server(make_batcher(tiny), fn)


def test_shutdown_drains_pending_request(tiny):
    from distributed_llms_tpu.runtime.server import _Mailbox

    async def fn(host, port, srv):
        # Emulate the shutdown race: a request lands in the batcher queue
        # just as stop() flips _stopping (so the stop()-time cancel sweep
        # missed it).  The engine's stopping path must fail it — without
        # the drain its mailbox would never be notified and the handler
        # would hang forever.
        rid = srv.batcher.next_rid
        mbox = _Mailbox()
        srv._requests[rid] = mbox
        assert srv.batcher.submit("hi", max_new_tokens=4) == rid
        srv._stopping = True
        srv._work.set()
        toks, done, err, _lps = await asyncio.wait_for(mbox.queue.get(), 10)
        assert done and err == "server is shutting down"
        srv._requests.pop(rid, None)

    run_with_server(make_batcher(tiny), fn)


def test_max_pending_backpressure(tiny):
    async def fn(host, port, srv):
        # Fill the in-flight table beyond the cap; the extras get 429.
        results = await asyncio.gather(*[
            _request(host, port, "POST", "/v1/completions",
                     {"prompt": f"req {i}", "max_tokens": 4})
            for i in range(6)
        ])
        statuses = sorted(s for s, _ in results)
        assert statuses.count(200) >= 2
        assert all(s in (200, 429) for s in statuses)

    run_with_server(make_batcher(tiny, batch_slots=2), fn, max_pending=2)


def test_token_id_prompt_and_prefix(tiny):
    b = make_batcher(tiny)
    b.register_prefix("sys", "system says: ")

    want_b = make_batcher(tiny)
    want_b.register_prefix("sys", "system says: ")
    rid = want_b.submit("query", max_new_tokens=5, prefix="sys")
    want = want_b.tokenizer.decode(want_b.run()[rid])

    async def fn(host, port, srv):
        # Raw token-id prompt.
        ids = ByteTokenizer().encode("raw ids")
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": ids, "max_tokens": 3},
        )
        assert status == 200
        assert json.loads(body)["usage"]["prompt_tokens"] == len(ids)
        # Registered-prefix extension reuses the cached system-prompt KV.
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "query", "max_tokens": 5, "prefix": "sys"},
        )
        assert status == 200
        assert json.loads(body)["choices"][0]["text"] == want

    run_with_server(b, fn)


def test_logprobs_blocking_and_stream(tiny):
    async def fn(host, port, srv):
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "lp please", "max_tokens": 6, "logprobs": True},
        )
        assert status == 200
        out = json.loads(body)
        lp = out["choices"][0]["logprobs"]
        assert len(lp["tokens"]) == len(lp["token_logprobs"]) == 6
        assert all(v <= 1e-6 for v in lp["token_logprobs"])
        # Streaming: per-chunk logprob slices reassemble the same list.
        status, events = await _sse_events(
            host, port, "/v1/completions",
            {"prompt": "lp please", "max_tokens": 6, "logprobs": 0,
             "stream": True},
        )
        assert status == 200
        got = []
        for e in events[:-1]:
            f = e["choices"][0].get("logprobs")
            if f:
                got.extend(f["token_logprobs"])
        assert got == lp["token_logprobs"]
        # Chat shape.
        status, body = await _request(
            host, port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "x"}],
             "max_tokens": 3, "logprobs": True},
        )
        assert status == 200
        content = json.loads(body)["choices"][0]["logprobs"]["content"]
        assert len(content) == 3 and all("logprob" in c for c in content)
        # Top-alternative counts are not supported.
        status, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "x", "logprobs": 3},
        )
        assert status == 400

    run_with_server(make_batcher(tiny), fn)


def test_n_choices_blocking_and_stream(tiny):
    want = expected_text(tiny, "multi", 5)

    async def fn(host, port, srv):
        # Greedy n=3: all choices identical to the solo run, indices 0..2.
        status, body = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "multi", "max_tokens": 5, "n": 3},
        )
        assert status == 200
        out = json.loads(body)
        assert [c["index"] for c in out["choices"]] == [0, 1, 2]
        assert all(c["text"] == want for c in out["choices"])
        assert out["usage"]["completion_tokens"] == 15
        # Streaming n=2: chunks carry per-choice indices; each choice's
        # concatenation equals the solo text, one finish per choice.
        status, events = await _sse_events(
            host, port, "/v1/completions",
            {"prompt": "multi", "max_tokens": 5, "n": 2, "stream": True},
        )
        assert status == 200
        texts = {0: "", 1: ""}
        finals = {0: 0, 1: 0}
        for e in events[:-1]:
            c = e["choices"][0]
            texts[c["index"]] += c["text"]
            finals[c["index"]] += c["finish_reason"] is not None
        assert texts == {0: want, 1: want}
        assert finals == {0: 1, 1: 1}
        # Validation.
        status, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "x", "n": 9},
        )
        assert status == 400
        status, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "x", "n": 0},
        )
        assert status == 400

    run_with_server(make_batcher(tiny), fn)
