"""Replica-fleet serving (runtime/router.py + cluster/fleet.py).

The acceptance contract pinned here, one level up from PR 2's in-process
supervisor: a fleet of N independent server/batcher replicas behind the
health-aware router survives replica CRASH (abrupt, unflushed), engine
STALL past the watchdog, network PARTITION, and rolling DRAIN/RESPAWN —
and through all of it every request that completes is temp-0 byte-exact
(zero-streamed requests re-admit VERBATIM on a healthy replica) and every
request that fails carries a structured, retryable error: 429/503 +
Retry-After before any bytes streamed, an in-stream ``engine_error`` event
after (deltas cannot be retracted).  Surviving replicas' page pools audit
clean afterward.

Also here: placement policy (least committed-token load, prefix-cache
session affinity with a load-spill guard, the ``router.place`` veto site)
and ``ServingClient``'s client-side multi-endpoint failover.
"""

import asyncio
import json

import pytest

import jax

from distributed_llms_tpu.cluster.client import ServingClient
from distributed_llms_tpu.cluster.fleet import ReplicaFleet
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.router import ReplicaRouter
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

PAGE = 16


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _replica_batcher(tiny):
    cfg, params = tiny
    tok = ByteTokenizer()
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=2, max_len=96, chunk_steps=4,
        paged_pages=8, page_size=PAGE, prefix_cache=True,
    )


@pytest.fixture(scope="module")
def warmed(tiny):
    """Warm the process-wide jit cache with the replicas' exact program
    shapes (paged admission across the prompt buckets, cache-hit
    admission, decode): replicas then serve first requests in
    milliseconds, so the fast watchdogs these tests run never mistake a
    cold compile for a wedged engine."""
    b = _replica_batcher(tiny)
    for prompt in ("warm short", "a much longer warming prompt xxxx",
                   "warm short"):  # repeat: cache-hit admission path
        b.submit(prompt, max_new_tokens=4)
        b.run()
    return tiny


def server_factory(tiny, **srv_kw):
    """() -> a fresh, unstarted replica: full server/batcher stack with
    its own supervisor, small paged pool (7 usable pages = 112 tokens),
    and a fast watchdog so stall drills resolve quickly."""
    srv_kw.setdefault("watchdog_timeout_s", 0.4)

    def make_server():
        return InferenceServer(
            _replica_batcher(tiny), model_name="tiny", host="127.0.0.1",
            port=0, batcher_factory=lambda: _replica_batcher(tiny), **srv_kw,
        )

    return make_server


def run_with_fleet(tiny, n, fn, faults=None, srv_kw=None, router_kw=None):
    """Boot an n-replica fleet + router, wait until every replica probes
    healthy, run ``fn(host, port, fleet, router)``, tear down."""

    async def driver():
        fleet = ReplicaFleet(
            [server_factory(tiny, **(srv_kw or {}))] * n,
            probe_interval_s=0.05, probe_timeout_s=2.0, faults=faults,
        )
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, faults=faults, **(router_kw or {}),
        )
        await fleet.start()
        host, port = await router.start()
        try:
            for _ in range(200):
                if all(h.state == "healthy" for h in fleet.replicas):
                    break
                await asyncio.sleep(0.02)
            assert all(h.state == "healthy" for h in fleet.replicas)
            return await asyncio.wait_for(
                fn(host, port, fleet, router), timeout=600
            )
        finally:
            await router.stop()
            await fleet.stop()

    return asyncio.run(driver())


async def _request(host, port, method, path, body=None):
    """Raw request; returns (status, headers dict, body bytes)."""
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    data = await reader.read()
    writer.close()
    return status, headers, data


def expected_texts(tiny, reqs):
    """Reference texts from one roomy, un-faulted batcher (exactness is
    batching- and replica-invariant at temperature 0)."""
    cfg, params = tiny
    tok = ByteTokenizer()
    b = ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=4, max_len=96, chunk_steps=4, paged_pages=40,
        page_size=PAGE,
    )
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return {p: tok.decode(res[rid]) for rid, (p, n) in zip(rids, reqs)}


async def _wait_inflight(fleet):
    """The replica currently holding >= 1 in-flight router request."""
    for _ in range(1000):
        for h in fleet.replicas:
            if h.inflight and h.state == "healthy":
                return h
        await asyncio.sleep(0.005)
    raise AssertionError("no request ever went in flight")


# -- placement --------------------------------------------------------------


def test_placement_prefix_affinity_and_least_load(warmed):
    tiny = warmed
    """Same-prefix traffic sticks to the replica that already holds the
    pages (affinity hit counter moves); disjoint traffic balances to the
    least-committed replica."""
    shared = "shared system prompt! " * 2  # > 1 full 16-token page
    reqs = [(shared + "tail one", 4), (shared + "tail two", 4),
            ("completely different", 4)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        hits0 = METRICS.get_counter("router.affinity_hits")
        for p, n in reqs:
            status, _, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": p, "max_tokens": n},
            )
            body = json.loads(raw)
            assert status == 200, body
            assert body["choices"][0]["text"] == wants[p], p
        # Request 2 shared request 1's full first page: affinity hit.
        assert METRICS.get_counter("router.affinity_hits") > hits0
        assert router._affinity  # digests recorded for future placement

    run_with_fleet(tiny, 2, fn)


def test_affinity_invalidated_after_respawn(warmed):
    tiny = warmed
    """Affinity hygiene: a drained/respawned replica comes back with a
    COLD pool and prefix cache — affinity entries recorded against its
    previous life (epoch) must read as misses, so stale stickiness can
    never beat least-loaded placement at a cache that no longer holds
    the pages."""
    shared = "sticky system prompt!! " * 2  # > 1 full 16-token page
    reqs = [(shared + "aaa", 4), (shared + "bbb", 4)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": 4},
        )
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == wants[reqs[0][0]]
        digests = router._digests(ByteTokenizer().encode(reqs[1][0]))
        sticky = {router._affinity_lookup(d) for d in digests} - {None}
        assert sticky, "placement never recorded affinity"
        (name,) = sticky
        # Drain + respawn the sticky replica: fresh pool, bumped epoch.
        await fleet.drain(name, drain_timeout_s=15.0)
        assert fleet[name].restarts == 1
        # Every entry pointing at the old life now reads as a miss (and
        # is dropped), rather than steering traffic at a cold cache.
        assert all(router._affinity_lookup(d) is None for d in digests)
        hits0 = METRICS.get_counter("router.affinity_hits")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[1][0], "max_tokens": 4},
        )
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == wants[reqs[1][0]]
        # The shared-prefix request placed WITHOUT a (stale) affinity hit.
        assert METRICS.get_counter("router.affinity_hits") == hits0

    run_with_fleet(tiny, 2, fn)


def test_router_place_drop_vetoes_choice(warmed):
    tiny = warmed
    """A ``router.place ... drop`` rule vetoes the chosen replica: the
    request spills to the next-best candidate and still completes."""
    plane = FaultPlane()
    rule = plane.add("router.place", "drop", when="1")
    wants = expected_texts(tiny, [("veto me", 4)])

    async def fn(host, port, fleet, router):
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "veto me", "max_tokens": 4},
        )
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == wants["veto me"]
        assert rule.fired == 1

    run_with_fleet(tiny, 2, fn, faults=plane)


# -- exact failover ---------------------------------------------------------


def test_crash_failover_zero_streamed_exact(warmed):
    tiny = warmed
    """A replica killed abruptly mid-request: the zero-streamed (buffered)
    request is re-sent verbatim to the surviving replica and completes
    with byte-exact temp-0 text; the failover is counted and timed."""
    reqs = [("failover target request", 32)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        f0 = METRICS.get_counter("router.failovers")
        task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        ))
        victim = await _wait_inflight(fleet)
        await fleet.kill(victim.name)
        status, headers, raw = await task
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert METRICS.get_counter("router.failovers") > f0
        rec = METRICS.snapshot()["histograms"].get("router.failover_seconds")
        assert rec and rec["count"] >= 1
        # The survivor's pool audits clean.
        for h in fleet.replicas:
            if h.state != "dead":
                h.server.batcher.assert_pool_consistent()

    run_with_fleet(tiny, 2, fn)


def test_chaos_crash_close_drill_fails_over_exact(warmed):
    tiny = warmed
    """The fleet's own chaos site: a ``replica.crash ... close`` rule
    kills the in-flight replica at the next probe tick (no direct
    fleet.kill from the test) — the zero-streamed request re-sends
    verbatim to the survivor and completes byte-exact."""
    plane = FaultPlane()
    reqs = [("chaos crash request", 32)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        f0 = METRICS.get_counter("router.failovers")
        task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        ))
        victim = await _wait_inflight(fleet)
        rule = plane.add("replica.crash", "close", when="1",
                         tag=victim.name)
        for _ in range(400):  # the kill lands at the next probe tick
            if rule.fired:
                break
            await asyncio.sleep(0.01)
        assert rule.fired == 1
        status, _, raw = await task
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert METRICS.get_counter("router.failovers") > f0
        assert victim.state == "dead"
        for h in fleet.replicas:
            if h.state != "dead":
                h.server.batcher.assert_pool_consistent()

    run_with_fleet(tiny, 2, fn, faults=plane)


def test_stall_past_watchdog_fails_over(warmed):
    tiny = warmed
    """A replica whose engine wedges past the watchdog flips its own
    /healthz unhealthy; the fleet probe aborts the in-flight proxy and the
    zero-streamed request completes exactly on the other replica."""
    plane = FaultPlane()
    reqs = [("stalled engine request", 32)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        f0 = METRICS.get_counter("router.failovers")
        # Both replicas idle -> the first placement deterministically goes
        # least-loaded by name: r0.  Wedge r0's engine 2s (watchdog 0.4s)
        # BEFORE sending, so its FIRST decode chunk stalls: /healthz flips
        # stalled, the probe marks it unhealthy, the proxy aborts.
        victim = fleet["r0"]
        rule = plane.add("replica.stall", "delay", when="1", arg=2.0,
                         tag="r0")
        for _ in range(200):  # the wedge arms at the next probe tick
            if rule.fired:
                break
            await asyncio.sleep(0.01)
        assert rule.fired == 1
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert METRICS.get_counter("router.failovers") > f0
        # The stalled replica heals once the wedge passes.
        for _ in range(400):
            if victim.state == "healthy":
                break
            await asyncio.sleep(0.02)
        assert victim.state == "healthy"
        victim.server.batcher.assert_pool_consistent()

    run_with_fleet(tiny, 2, fn, faults=plane)


def test_partition_fails_over_and_heals(warmed):
    tiny = warmed
    """A partitioned replica (unreachable from the router, engine alive):
    its in-flight request migrates, placement avoids it, and it returns to
    rotation when the partition heals."""
    plane = FaultPlane()
    reqs = [("partitioned request", 32)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        f0 = METRICS.get_counter("router.failovers")
        # Slow r0's decode (50ms per chunk) so the request reliably spans
        # several probe ticks — the partition then lands MID-FLIGHT.
        fleet["r0"].server.batcher.faults = FaultPlane.parse(
            "batcher.decode:stall@1+:0.05"
        )
        task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        ))
        victim = await _wait_inflight(fleet)
        assert victim.name == "r0"  # deterministic least-loaded tiebreak
        plane.add("replica.partition", "drop", when="1", arg=0.8,
                  tag=victim.name)
        status, _, raw = await task
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == wants[reqs[0][0]]
        assert METRICS.get_counter("router.failovers") > f0
        now = asyncio.get_running_loop().time()
        assert not victim.routable(now), "partitioned replica stayed routable"
        for _ in range(400):
            now = asyncio.get_running_loop().time()
            if victim.routable(now):
                break
            await asyncio.sleep(0.02)
        assert victim.routable(now), "partition never healed"

    run_with_fleet(tiny, 2, fn, faults=plane)


def test_streamed_failure_is_structured_engine_error(warmed):
    tiny = warmed
    """A replica dying after SSE deltas reached the client cannot fail
    over (deltas are irretractable): the stream ends with a structured
    engine_error event — the PR-2 mailbox contract one level up."""

    async def fn(host, port, fleet, router):
        # Slow r0's decode so the kill reliably lands mid-stream.
        fleet["r0"].server.batcher.faults = FaultPlane.parse(
            "batcher.decode:stall@1+:0.05"
        )
        reader, writer = await asyncio.open_connection(host, port)
        payload = json.dumps({
            "prompt": "stream then die", "max_tokens": 64, "stream": True,
        }).encode()
        writer.write(
            f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
        )
        await writer.drain()
        victim = await _wait_inflight(fleet)
        assert victim.name == "r0"
        # Wait for the first SSE data bytes (the router held headers until
        # real payload, so anything readable means deltas flowed).
        first = await reader.read(512)
        assert b"data:" in first
        await fleet.kill(victim.name)
        rest = await reader.read()
        writer.close()
        text = (first + rest).decode()
        assert "engine_error" in text, text
        # The structured error TERMINATES the stream: no completion
        # sentinel may follow it (a [DONE] after the error would tell
        # clients the truncated output completed normally).
        assert "[DONE]" not in text.split("engine_error", 1)[-1], text
        assert METRICS.get_counter("router.failed_streamed") >= 1

    run_with_fleet(tiny, 2, fn)


# -- rolling drain/respawn --------------------------------------------------


def test_rolling_restart_zero_downtime(warmed):
    tiny = warmed
    """rolling_restart drains + respawns every replica one at a time
    while a steady trickle of requests keeps completing exactly — the
    zero-downtime fleet restart."""
    reqs = [(f"rolling req {i}", 6) for i in range(10)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        outs = []

        async def trickle():
            for p, n in reqs:
                outs.append((p, await _request(
                    host, port, "POST", "/v1/completions",
                    {"prompt": p, "max_tokens": n},
                )))
                await asyncio.sleep(0.05)

        t = asyncio.create_task(trickle())
        await fleet.rolling_restart(drain_timeout_s=15.0)
        await t
        for p, (status, _h, raw) in outs:
            body = json.loads(raw)
            assert status == 200, (p, body)
            assert body["choices"][0]["text"] == wants[p], p
        assert all(h.restarts == 1 for h in fleet.replicas)
        assert all(h.state == "healthy" for h in fleet.replicas)
        for h in fleet.replicas:
            h.server.batcher.assert_pool_consistent()

    run_with_fleet(tiny, 2, fn)


# -- router front door ------------------------------------------------------


def test_router_healthz_metrics_and_no_replica_shed(warmed):
    tiny = warmed
    async def fn(host, port, fleet, router):
        status, _, raw = await _request(host, port, "GET", "/healthz")
        report = json.loads(raw)
        assert status == 200 and report["healthy"] == 2
        assert set(report["replicas"]) == {"r0", "r1"}
        # Kill the whole fleet: /healthz flips 503 and a completion sheds
        # structured + Retry-After instead of hanging.
        for h in list(fleet.replicas):
            await fleet.kill(h.name)
        status, headers, raw = await _request(host, port, "GET", "/healthz")
        assert status == 503
        assert int(headers["retry-after"]) >= 1
        status, headers, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "nobody home", "max_tokens": 4},
        )
        body = json.loads(raw)
        assert status == 503
        assert body["error"]["type"] == "overloaded_error"
        assert int(headers["retry-after"]) >= 1
        status, _, raw = await _request(host, port, "GET", "/metrics")
        text = raw.decode()
        for fam in ("router_placements", "router_replicas_healthy",
                    "router_replica_kills"):
            assert fam in text, fam

    run_with_fleet(tiny, 2, fn)


# -- client-side failover (ServingClient endpoints) -------------------------


def test_serving_client_endpoint_failover(warmed):
    tiny = warmed
    """ServingClient with an endpoints list fails over client-side: a
    dead endpoint rotates to the live one immediately (no backoff sleep
    against a severed socket)."""

    async def driver():
        s1 = server_factory(tiny)()
        s2 = server_factory(tiny)()
        h1, p1 = await s1.start()
        h2, p2 = await s2.start()
        try:
            await s1.kill()  # endpoint 1 is a dead socket
            client = ServingClient(
                endpoints=[(h1, p1), (h2, p2)], max_retries=4,
                backoff_base_s=0.05, backoff_cap_s=0.2,
            )
            status, body = await client.completions(
                {"prompt": "fail over to me", "max_tokens": 4}
            )
            assert status == 200, body
            assert client.failovers >= 1
            assert client.retries_taken == 0, "slept at a dead endpoint"
        finally:
            await s2.stop()

    asyncio.run(driver())


# -- THE chaos acceptance test ----------------------------------------------


def test_chaos_fleet_crash_stall_drain_storm(warmed):
    tiny = warmed
    """ISSUE 6 acceptance: a 3-replica fleet under >= 1.5x offered load
    survives one abrupt replica CRASH, one engine STALL past the watchdog,
    and one rolling DRAIN/RESPAWN — every completed request is temp-0
    byte-exact, every unstreamed failure is structured 429/503 with
    Retry-After, every streamed failure a structured engine_error event,
    and the page pool audits clean on every surviving replica."""
    n_req, n_new = 14, 24
    reqs = [(f"chaos storm request {i:02d}", n_new) for i in range(n_req)]
    wants = expected_texts(tiny, reqs)
    # Offered: 14 x (~22 prompt + 24 new) ~ 644 tokens vs 3 x 112 = 336
    # pool capacity ~ 1.9x.
    plane = FaultPlane()

    async def one(host, port, i, p, n):
        if i % 5 == 4:  # a streamed minority rides along
            reader, writer = await asyncio.open_connection(host, port)
            payload = json.dumps(
                {"prompt": p, "max_tokens": n, "stream": True}
            ).encode()
            writer.write(
                f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
            )
            await writer.drain()
            raw = await reader.read()
            writer.close()
            return ("sse", raw)
        return ("http", await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": n},
        ))

    async def fn(host, port, fleet, router):
        kills0 = METRICS.get_counter("router.replica_kills")

        async def staggered(i, p, n):
            await asyncio.sleep(i * 0.06)
            return await one(host, port, i, p, n)

        tasks = [asyncio.create_task(staggered(i, p, n))
                 for i, (p, n) in enumerate(reqs)]
        # Phase 1 — CRASH r0 once real work is in flight on it.
        for _ in range(1000):
            if fleet["r0"].inflight:
                break
            await asyncio.sleep(0.005)
        await fleet.kill("r0")
        # Phase 2 — STALL r1's engine past the watchdog (heals in 1.2s).
        await asyncio.sleep(0.1)
        plane.add("replica.stall", "delay", when="1", arg=1.2, tag="r1")
        for _ in range(600):  # wait for the stall to be observed + healed
            if fleet["r1"].state == "healthy" and plane.rules[-1].fired:
                break
            await asyncio.sleep(0.02)
        # Phase 3 — rolling DRAIN/RESPAWN of r2 while traffic continues.
        await fleet.drain("r2", drain_timeout_s=20.0)
        outs = await asyncio.gather(*tasks)

        completed = shed = stream_failed = 0
        for (kind, out), (p, n) in zip(outs, reqs):
            if kind == "http":
                status, headers, raw = out
                body = json.loads(raw)
                if status == 200:
                    assert body["choices"][0]["finish_reason"] == "length", body
                    assert body["choices"][0]["text"] == wants[p], p
                    completed += 1
                else:
                    assert status in (429, 503), (status, body)
                    assert body["error"]["type"] in (
                        "overloaded_error", "engine_error",
                    ), body
                    assert int(headers["retry-after"]) >= 1
                    shed += 1
            else:
                head, _, text = out.decode().partition("\r\n\r\n")
                status_line = head.split("\r\n", 1)[0]
                if "200" not in status_line:
                    # Shed before any stream began: plain structured
                    # 429/503 with Retry-After, same as the HTTP legs.
                    assert any(c in status_line for c in ("429", "503")), head
                    assert ("overloaded_error" in text
                            or "engine_error" in text), text
                    assert "retry-after" in head.lower(), head
                    shed += 1
                elif "engine_error" in text:
                    stream_failed += 1  # structured mid-stream failure
                else:
                    assert "[DONE]" in text, text
                    got = "".join(
                        json.loads(line[len("data: "):])["choices"][0]["text"]
                        for line in text.split("\n\n")
                        if line.startswith("data: ")
                        and not line.startswith("data: [DONE]")
                    )
                    assert got == wants[p], p
                    completed += 1
        assert completed + shed + stream_failed == n_req
        assert completed >= 3, (completed, shed, stream_failed)
        assert METRICS.get_counter("router.replica_kills") - kills0 == 1
        assert plane.rules[-1].fired >= 1, "stall never fired"
        assert fleet["r2"].restarts == 1
        # The failover plane actually exercised.  (Recovery LATENCY is
        # stamped by the deterministic replica-failover bench row — in a
        # full storm a failed-over request may legitimately end shed when
        # the rest of the fleet is stalled/draining at that instant, so
        # the histogram sample is not guaranteed here.)
        assert METRICS.get_counter("router.failovers") >= 1
        # Fleet steady state: the two surviving replicas are healthy and
        # their pools audit clean once traffic drains.
        for _ in range(400):
            if all(not h.inflight for h in fleet.replicas):
                break
            await asyncio.sleep(0.02)
        survivors = [h for h in fleet.replicas if h.state != "dead"]
        assert {h.name for h in survivors} == {"r1", "r2"}
        for _ in range(400):  # probes flip survivors healthy as they drain
            if all(h.state == "healthy" for h in survivors):
                break
            await asyncio.sleep(0.02)
        for h in survivors:
            assert h.state == "healthy", (h.name, h.state)
            for _ in range(200):
                if all(r.rid is None for r in h.server.batcher.rows):
                    break
                await asyncio.sleep(0.05)
            h.server.batcher.assert_pool_consistent()

    run_with_fleet(tiny, 3, fn, faults=plane)
