"""Speculative continuous batching (runtime/batcher.py spec_chunk).

Invariant: with ANY draft, the speculative batcher's greedy results are
bit-identical to the plain batcher's (which are pinned against solo
decodes by test_batcher.py) — acceptance only changes how many tokens land
per scheduling round.  Exercises mixed budgets, EOS mid-round, slot reuse,
prefix caching (draft prefills the full prompt), and the draft backfill
after fully accepted rounds (self-draft).
"""

import jax
import numpy as np
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

# Whole-family fresh-process isolation — shared marker, tests/conftest.py.
pytestmark = pytest.mark.fragile_xla_cpu


@pytest.fixture(scope="module")
def models():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    dcfg = presets.get_preset("llama-tiny", vocab_size=512, num_layers=2)
    dparams = model_lib.init_params(jax.random.key(99), dcfg)  # unrelated
    return cfg, params, dcfg, dparams


def _run(cfg, params, reqs, eos_id=-1, spec=None, spec_k=3):
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=4, eos_id=eos_id,
        **(dict(draft_params=spec[1], draft_cfg=spec[0], spec_k=spec_k)
           if spec else {}),
    )
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    return b, rids, b.run()


def test_spec_batcher_matches_plain(models):
    cfg, params, dcfg, dparams = models
    reqs = [([7, 1, 9, 4, 2], 9), ([4, 4, 4], 5), ([11, 12], 12), ([42], 7),
            ([3, 1], 1)]
    _, rp, plain = _run(cfg, params, reqs)
    _, rs, spec = _run(cfg, params, reqs, spec=(dcfg, dparams))
    for a, b in zip(rp, rs):
        assert plain[a] == spec[b], (a, plain[a], spec[b])


def test_spec_batcher_self_draft_matches_plain(models):
    """Self-draft: every round fully accepts, hammering the draft-backfill
    slot math round after round."""
    cfg, params, _, _ = models
    reqs = [([7, 1, 9], 13), ([5, 5], 11)]
    _, rp, plain = _run(cfg, params, reqs)
    _, rs, spec = _run(cfg, params, reqs, spec=(cfg, params), spec_k=4)
    for a, b in zip(rp, rs):
        assert plain[a] == spec[b]


def test_spec_batcher_eos_and_slot_reuse(models):
    cfg, params, dcfg, dparams = models
    # Find an EOS id that actually occurs: run once free, grab a token.
    probe_b, probe_r, probe = _run(cfg, params, [([7, 1, 9], 8)])
    eos_id = probe[probe_r[0]][3]
    reqs = [([7, 1, 9], 8), ([4, 4, 4], 6), ([11, 12], 9), ([2, 8], 7)]
    _, rp, plain = _run(cfg, params, reqs, eos_id=eos_id)
    _, rs, spec = _run(cfg, params, reqs, eos_id=eos_id,
                       spec=(dcfg, dparams))
    for a, b in zip(rp, rs):
        assert plain[a] == spec[b]


def test_spec_batcher_prefix_caching(models):
    """Prefix-cached requests: the draft prefills prefix+suffix itself
    (register_prefix stores target KV only); results must still match the
    plain batcher's prefix path exactly."""
    cfg, params, dcfg, dparams = models

    def run(spec):
        b = ContinuousBatcher(
            cfg, params, batch_slots=2, max_len=64, chunk_steps=4,
            **(dict(draft_params=dparams, draft_cfg=dcfg, spec_k=3)
               if spec else {}),
        )
        b.register_prefix("sys", [9, 8, 7, 6, 5])
        rids = [b.submit([1, 2], max_new_tokens=7, prefix="sys"),
                b.submit([3], max_new_tokens=5, prefix="sys"),
                b.submit([4, 4, 4], max_new_tokens=6)]
        return rids, b.run()

    rp, plain = run(False)
    rs, spec = run(True)
    for a, b2 in zip(rp, rs):
        assert plain[a] == spec[b2]


def test_spec_preemption_recompute_exact(models):
    """ROADMAP item 5 corner (spec-decode x preemption): preempt a row
    BETWEEN speculative rounds, mid-generation — the resume request
    re-prefills prompt + emitted prefix into BOTH the target and draft
    caches (admit_row + admit_row_kv) and the reunited stream is temp-0
    bit-identical to the unpreempted plain run, with nothing re-delivered
    and done fired exactly once across both residencies."""
    cfg, params, dcfg, dparams = models
    from distributed_llms_tpu.core.observability import METRICS

    reqs = [([7, 1, 9, 4, 2], 12), ([4, 4, 4], 10)]
    _, rp, plain = _run(cfg, params, reqs)
    preempt0 = METRICS.get_counter("batcher.preemptions_total")
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=4,
        draft_params=dparams, draft_cfg=dcfg, spec_k=3,
    )
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    streamed = {r: [] for r in rids}
    dones = {r: 0 for r in rids}
    state = {"preempted": False}

    def cb(rid, toks, done, lps):
        streamed[rid].extend(toks)
        if done:
            dones[rid] += 1
        # Preempt rid[0]'s row once a few tokens streamed but well before
        # its budget: the callback runs between device chunks — the
        # documented safe point (the same contract cancel_row uses).
        if (not state["preempted"] and rid == rids[0] and not done
                and len(streamed[rids[0]]) >= 4):
            slot = next(
                i for i, r in enumerate(b.rows) if r.rid == rids[0]
            )
            if b.active[slot]:
                b._preempt_row(slot, "spec-preemption drill")
                state["preempted"] = True

    res = b.run(on_tokens=cb)
    assert state["preempted"], "preemption never fired"
    assert METRICS.get_counter("batcher.preemptions_total") > preempt0
    for a, c in zip(rp, rids):
        assert plain[a] == res[c], (a, plain[a], res[c])
        assert streamed[c] == res[c], "stream diverged across residencies"
        assert dones[c] == 1


def test_spec_windowed_target_exact(models):
    """ROADMAP item-5's last untested corner (spec-decode x windowed
    attention): a sliding-window TARGET in the speculative batcher stays
    temp-0 bit-identical to the plain windowed batcher ACROSS the window
    boundary.  Budgets are sized so every row's generation slides the
    readable window well past its prompt — the verify forward's masks,
    the committed-slot bookkeeping, and the draft backfill must all
    stay consistent with the target's sliding reads round after round
    (contiguous layout: slot == position, so the slot-space band equals
    the position-space window exactly).  Runs with an unwindowed draft
    (caches deliberately shaped differently) AND as windowed self-draft
    (every round fully accepts, hammering the backfill at the
    boundary)."""
    _, _, dcfg, dparams = models
    tcfg = presets.get_preset("llama-tiny", vocab_size=512, sliding_window=8)
    tparams = model_lib.init_params(jax.random.key(0), tcfg)
    # 7 + 16 and 3 + 14 both cross the window=8 boundary mid-generation;
    # the third row finishes before the boundary (mixed-regime batch).
    reqs = [([7, 1, 9, 4, 2, 8, 3], 16), ([4, 4, 4], 14), ([11, 12], 4)]
    _, rp, plain = _run(tcfg, tparams, reqs)
    _, rs, spec = _run(tcfg, tparams, reqs, spec=(dcfg, dparams))
    for a, b in zip(rp, rs):
        assert plain[a] == spec[b], (a, plain[a], spec[b])
    _, rs2, spec2 = _run(tcfg, tparams, reqs, spec=(tcfg, tparams),
                         spec_k=4)
    for a, b in zip(rp, rs2):
        assert plain[a] == spec2[b], (a, plain[a], spec2[b])


def test_spec_batcher_near_capacity(models):
    """REGRESSION (r4 review): a request filling its slot exactly
    (prompt + max_new_tokens == max_len) makes the last verify write k+1
    slots past the frontier — without headroom, dynamic_update_slice CLAMPS
    the start and silently corrupts the last committed slot's KV.  The
    padded cache must keep tokens bit-identical to the plain batcher."""
    cfg, params, dcfg, dparams = models
    max_len = 32
    prompt = [7, 1, 9, 4, 2, 8, 3, 5]          # 8 tokens
    reqs = [(prompt, max_len - len(prompt))]   # fills the slot exactly

    def run(spec):
        b = ContinuousBatcher(
            cfg, params, batch_slots=1, max_len=max_len, chunk_steps=4,
            **(dict(draft_params=dparams, draft_cfg=dcfg, spec_k=4)
               if spec else {}),
        )
        rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
        return rids, b.run()

    rp, plain = run(False)
    rs, spec = run(True)
    assert len(plain[rp[0]]) == max_len - len(prompt)
    assert plain[rp[0]] == spec[rs[0]]


def test_spec_batcher_guards(models):
    cfg, params, dcfg, dparams = models
    with pytest.raises(ValueError, match="draft_cfg"):
        ContinuousBatcher(cfg, params, max_len=64, draft_params=dparams)
    # spec x paged composes since round 17 (the draft/verify window rides
    # the page pool); only chunked prefill still rejects with a clear
    # error (the draft admission prefills the full prompt monolithically).
    ContinuousBatcher(cfg, params, draft_params=dparams, draft_cfg=dcfg,
                      paged_pages=8, page_size=16, max_len=64)
    with pytest.raises(ValueError, match="chunked prefill"):
        ContinuousBatcher(cfg, params, draft_params=dparams, draft_cfg=dcfg,
                          prefill_chunk=8, max_len=64)
    with pytest.raises(ValueError, match="vocab"):
        bad = presets.get_preset("llama-tiny", vocab_size=97)
        ContinuousBatcher(cfg, params, max_len=64,
                          draft_params=model_lib.init_params(
                              jax.random.key(1), bad), draft_cfg=bad)
    # Engine-wide sampling composes with speculation; PER-REQUEST overrides
    # don't (the rejection test warps p and q with one static config).
    sb = ContinuousBatcher(cfg, params, max_len=64, draft_params=dparams,
                          draft_cfg=dcfg, temperature=0.5)
    with pytest.raises(ValueError, match="engine-wide"):
        sb.submit([1, 2], max_new_tokens=4, temperature=0.9)
    with pytest.raises(ValueError, match="engine-wide"):
        sb.submit([1, 2], max_new_tokens=4, top_p=0.5)
    # Values MATCHING the engine config are accepted (they are no-ops).
    assert sb.submit([1, 2], max_new_tokens=4, temperature=0.5) >= 0


def test_sampled_spec_batcher_distribution():
    """Sampled speculative batching is distribution-preserving: over many
    seeds, the joint empirical distribution of the first two tokens from a
    temperature>0 spec batcher (unrelated draft, so rejection/residual
    carries real weight) must match the plain sampled batcher's — measured
    with the same self-calibrated total-variation test as the standalone
    loop (tests/runtime/test_speculative.py).  Also pins per-seed
    determinism."""
    n_seeds = 800
    cfg = presets.get_preset("llama-tiny", vocab_size=16, num_layers=1,
                             num_heads=2, num_kv_heads=2, hidden_size=16,
                             intermediate_size=44)
    params = model_lib.init_params(jax.random.key(0), cfg)
    dparams = model_lib.init_params(jax.random.key(77), cfg)  # unrelated
    prompt = [7, 1, 9]

    def run_one(seed, spec):
        b = ContinuousBatcher(
            cfg, params, batch_slots=1, max_len=16, chunk_steps=2,
            temperature=0.9, seed=seed,
            **(dict(draft_params=dparams, draft_cfg=cfg, spec_k=2)
               if spec else {}),
        )
        rid = b.submit(prompt, max_new_tokens=2)
        out = b.run()[rid]
        assert len(out) == 2
        return tuple(out)

    spec = [run_one(s, True) for s in range(n_seeds)]
    plain_a = [run_one(s + 10_000, False) for s in range(n_seeds)]
    plain_b = [run_one(s + 20_000, False) for s in range(n_seeds)]
    assert run_one(5, True) == spec[5]  # per-seed determinism

    def joint_hist(arr):
        h = np.zeros((16, 16))
        for a_, b_ in arr:
            h[a_, b_] += 1
        return h / len(arr)

    hs, hp_a, hp_b = joint_hist(spec), joint_hist(plain_a), joint_hist(plain_b)
    null_tv = 0.5 * np.abs(hp_a - hp_b).sum()
    test_tv = 0.5 * np.abs(hs - hp_a).sum()
    assert test_tv < 1.5 * null_tv + 0.04, (
        f"TV {test_tv:.3f} vs same-distribution null {null_tv:.3f} — "
        "sampled speculative batching diverges from the target distribution"
    )


def test_engine_spec_batcher_wiring():
    """RuntimeConfig(spec_decode=True): continuous_batcher() defaults to
    speculative mode with the engine's attached self-draft, and its results
    match the plain batcher."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    rt = RuntimeConfig(max_decode_steps=8, max_seq_len=64, spec_decode=True,
                       spec_k=3)
    eng = InferenceEngine.from_preset("llama-tiny", rt, vocab_size=300,
                                      max_seq_len=64)
    b = eng.continuous_batcher(batch_slots=2, max_len=48)
    assert b.speculative
    rids = [b.submit("hello", max_new_tokens=6),
            b.submit("cat", max_new_tokens=4)]
    res = b.run()
    plain = eng.continuous_batcher(batch_slots=2, max_len=48,
                                   speculative=False)
    assert not plain.speculative
    rp = [plain.submit("hello", max_new_tokens=6),
          plain.submit("cat", max_new_tokens=4)]
    resp = plain.run()
    for a, c in zip(rids, rp):
        assert res[a] == resp[c]


def test_sampled_spec_server_roundtrip(models):
    """The HTTP gateway serves a SAMPLED speculative engine: requests with
    temperature matching the engine config get 200 + logprobs; overrides
    differing from it get a clean 400 (submit's engine-wide policy)."""
    import asyncio
    import json

    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    cfg, params, dcfg, dparams = models
    tok = ByteTokenizer()
    b = ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=2, max_len=96, chunk_steps=4, temperature=0.8,
        draft_params=dparams, draft_cfg=dcfg, spec_k=2,
    )

    async def post(host, port, body):
        reader, writer = await asyncio.open_connection(host, port)
        payload = json.dumps(body).encode()
        writer.write(
            f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        while (await reader.readline()) not in (b"\r\n", b"\n", b""):
            pass
        data = await reader.read()
        writer.close()
        return status, data

    async def fn():
        srv = InferenceServer(b, model_name="t", host="127.0.0.1", port=0)
        host, port = await srv.start()
        try:
            st, data = await post(host, port, {
                "prompt": "hi", "max_tokens": 6, "temperature": 0.8,
                "logprobs": True,
            })
            assert st == 200, (st, data)
            out = json.loads(data)
            lp = out["choices"][0]["logprobs"]
            assert len(lp["token_logprobs"]) == len(lp["tokens"]) > 0
            assert all(v <= 1e-6 for v in lp["token_logprobs"])
            st2, data2 = await post(host, port, {
                "prompt": "hi", "max_tokens": 4, "temperature": 0.1,
            })
            assert st2 == 400 and b"engine-wide" in data2, (st2, data2)
        finally:
            await srv.stop()

    asyncio.run(fn())


def test_spec_streaming_matches_plain_stream(models):
    """Speculative streaming: chunk boundaries differ (k+1-token rounds),
    but the reassembled streams are bit-identical to the plain batcher's
    results and done fires exactly once per request."""
    cfg, params, dcfg, dparams = models
    reqs = [([7, 1, 9], 8), ([4, 4], 5), ([11, 12, 13], 10)]
    _, rp, plain = _run(cfg, params, reqs)
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          chunk_steps=4, draft_params=dparams,
                          draft_cfg=dcfg, spec_k=3)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    streamed = {r: [] for r in rids}
    dones = {r: 0 for r in rids}

    def cb(rid, new, done, lps):
        streamed[rid].extend(new)
        dones[rid] += bool(done)

    res = b.run(on_tokens=cb)
    for a, r in zip(rp, rids):
        assert streamed[r] == res[r] == plain[a]
        assert dones[r] == 1
