"""Per-request sampling in the continuous batcher (sampling.sample_rows).

Core invariants:
- isolation: a greedy request's tokens are EXACTLY its solo-greedy run even
  while sharing decode chunks with sampled rows (the per-row path's
  ``where(t > 0, drawn, greedy)`` must leave greedy rows untouched);
- equivalence: submitting with explicit knobs equals building the batcher
  with those knobs as its config (per-row path == static path under the
  same rng stream);
- determinism: same seed -> same sampled tokens.
"""

import jax
import numpy as np
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def make(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("batch_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    return ContinuousBatcher(cfg, params, **kw)


def test_greedy_rows_isolated_from_sampled_rows(tiny):
    """Greedy requests sharing the batch with hot-sampled ones must still
    match their solo-greedy runs token for token."""
    greedy_reqs = [([7, 1, 9], 8), ([4, 4, 4, 4, 4], 11)]
    solos = {}
    for ids, n in greedy_reqs:
        b = make(tiny)
        rid = b.submit(ids, max_new_tokens=n)
        solos[tuple(ids)] = b.run()[rid]

    b = make(tiny)
    rids = {}
    for k, (ids, n) in enumerate(greedy_reqs):
        rids[tuple(ids)] = b.submit(ids, max_new_tokens=n)
        # Interleave a hot-sampled request after each greedy one.
        b.submit([30 + k, 2, 5], max_new_tokens=9, temperature=1.7,
                 top_p=0.95)
    res = b.run()
    for ids, _ in greedy_reqs:
        assert res[rids[tuple(ids)]] == solos[tuple(ids)]


def test_per_request_equals_batcher_config(tiny):
    """submit(temperature=t, top_p=p) on a greedy-configured batcher must
    draw the same tokens as a batcher CONFIGURED with (t, p) — the traced
    per-row path and the static path are the same math on the same rng
    stream."""
    ids = [3, 14, 15, 9, 2]
    a = make(tiny, temperature=0.8, top_p=0.9, seed=11)
    ra = a.submit(ids, max_new_tokens=12)
    out_a = a.run()[ra]

    b = make(tiny, seed=11)  # greedy config
    rb = b.submit(ids, max_new_tokens=12, temperature=0.8, top_p=0.9)
    out_b = b.run()[rb]
    assert out_a == out_b


def test_per_request_top_k_equals_batcher_config(tiny):
    """submit(top_k=k) on a top_k=0 batcher draws the same tokens as a
    batcher CONFIGURED with top_k=k: the traced per-row top-k mask keeps
    exactly the static mask's token set (ties included), so the
    categorical draw matches on the same rng stream — admission and
    decode chunks both."""
    ids = [5, 6, 7, 8]
    a = make(tiny, temperature=0.8, top_k=5, seed=3)
    ra = a.submit(ids, max_new_tokens=10)
    out_a = a.run()[ra]

    b = make(tiny, temperature=0.8, seed=3)  # top_k=0 config
    rb = b.submit(ids, max_new_tokens=10, top_k=5)
    out_b = b.run()[rb]
    assert out_a == out_b

    # top_k=1 at temperature>0 collapses to the greedy argmax chain.
    c = make(tiny, temperature=0.8, seed=3)
    rc = c.submit(ids, max_new_tokens=10, top_k=1)
    g = make(tiny)
    rg = g.submit(ids, max_new_tokens=10)
    assert c.run()[rc] == g.run()[rg]


def test_top_k_row_isolated_from_neighbors(tiny):
    """A top_k-overriding row must not disturb a greedy neighbor (the
    per-row path leaves temperature-0 rows on the argmax)."""
    ids, n = [7, 1, 9], 8
    solo_b = make(tiny)
    srid = solo_b.submit(ids, max_new_tokens=n)
    want = solo_b.run()[srid]
    b = make(tiny)
    rid = b.submit(ids, max_new_tokens=n)
    b.submit([2, 3, 4], max_new_tokens=6, temperature=1.4, top_k=3)
    assert b.run()[rid] == want


def test_sampled_deterministic_and_not_greedy(tiny):
    ids = [5, 6, 7, 8]
    runs = []
    for _ in range(2):
        b = make(tiny, seed=3)
        rid = b.submit(ids, max_new_tokens=16, temperature=2.0)
        runs.append(b.run()[rid])
    assert runs[0] == runs[1]  # same seed -> same draws

    g = make(tiny, seed=3)
    rg = g.submit(ids, max_new_tokens=16)
    greedy = g.run()[rg]
    assert runs[0] != greedy  # 16 hot draws all matching argmax: ~impossible


def test_mixed_sampling_in_paged_mode(tiny):
    """The paged admission path threads per-request knobs too."""
    ids, n = [9, 8, 7], 7
    solo_b = make(tiny)
    solo_rid = solo_b.submit(ids, max_new_tokens=n)
    solo = solo_b.run()[solo_rid]

    b = make(tiny, paged_pages=13, page_size=32, max_len=96)
    rid_g = b.submit(ids, max_new_tokens=n)
    b.submit([2, 2, 2], max_new_tokens=6, temperature=1.5, top_p=0.8)
    assert b.run()[rid_g] == solo


def test_submit_validation(tiny):
    b = make(tiny)
    with pytest.raises(ValueError, match="temperature"):
        b.submit([1, 2], max_new_tokens=4, temperature=-0.5)
    with pytest.raises(ValueError, match="top_p"):
        b.submit([1, 2], max_new_tokens=4, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        b.submit([1, 2], max_new_tokens=4, top_p=1.5)
    with pytest.raises(ValueError, match="top_k"):
        b.submit([1, 2], max_new_tokens=4, top_k=-1)
    with pytest.raises(ValueError, match="top_k"):
        b.submit([1, 2], max_new_tokens=4, top_k=2.5)
    with pytest.raises(ValueError, match="top_k"):
        b.submit([1, 2], max_new_tokens=4, top_k=True)
    with pytest.raises(ValueError, match="top_k"):
        # int32 bound: an unbounded int would overflow the traced scalar
        # at admission — crash the engine thread instead of a 400.
        b.submit([1, 2], max_new_tokens=4, top_k=2**40)


def test_speculative_rejects_per_request_sampling(tiny):
    cfg, params = tiny
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=4,
        draft_params=params, draft_cfg=cfg, spec_k=2,
    )
    # Engine-wide sampling composes with speculation (temperature set at
    # construction); per-request overrides differing from the engine's
    # config do not.
    with pytest.raises(ValueError, match="engine-wide"):
        b.submit([1, 2, 3], max_new_tokens=4, temperature=0.7)
    with pytest.raises(ValueError, match="engine-wide"):
        b.submit([1, 2, 3], max_new_tokens=4, top_k=5)
    # Explicit values matching this engine's config are accepted.
    rid = b.submit([1, 2, 3], max_new_tokens=4, temperature=0.0, top_k=0)
    assert rid >= 0


def test_logprobs_aligned_deterministic_and_streamed(tiny):
    """result_logprobs aligns 1:1 with results, is <= 0 (raw-distribution
    log-probabilities), matches across identical runs, and the streamed
    deliveries reassemble it exactly."""
    reqs = [([7, 1, 9], 6), ([4, 4], 9, 1.3), ([11], 4)]

    def drive():
        b = make(tiny, seed=5)
        rids = []
        for r in reqs:
            ids, n = r[0], r[1]
            t = r[2] if len(r) > 2 else None
            rids.append(b.submit(ids, max_new_tokens=n, temperature=t))
        streamed_lps = {r: [] for r in rids}

        def cb(rid, new, done, lps):
            assert lps is not None and len(lps) == len(new)
            streamed_lps[rid].extend(lps)

        res = b.run(on_tokens=cb)
        return rids, res, dict(b.result_logprobs), streamed_lps

    rids, res, result_lps, streamed = drive()
    for r in rids:
        assert len(result_lps[r]) == len(res[r])
        assert all(v <= 1e-6 for v in result_lps[r])
        assert streamed[r] == result_lps[r]
    # Logprobs are real numbers, not a constant placeholder.
    flat = [v for r in rids for v in result_lps[r]]
    assert len(set(flat)) > 1
    # Determinism: a fresh identical batcher reproduces them bit-for-bit.
    _, _, result_lps2, _ = drive()
    assert result_lps == {k: result_lps2[k] for k in result_lps}


# The two speculative tests below compile spec_chunk programs (plain and
# penalized) — fresh-process via tests/runtime/test_isolated.py (shared
# marker, tests/conftest.py).
@pytest.mark.fragile_xla_cpu
def test_speculative_logprobs_match_plain(tiny):
    """Speculative mode gathers chosen-token logprobs from the verify
    pass's logits; at temperature 0 they must match the plain batcher's
    (same model, same tokens, same raw distribution — the verify forward
    and the plain decode forward see identical committed context)."""
    cfg, params = tiny
    reqs = [([1, 2, 3], 8), ([7, 1], 5)]
    plain = make(tiny)
    plain_rids = [plain.submit(ids, max_new_tokens=n) for ids, n in reqs]
    plain_res = plain.run()

    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=96, chunk_steps=4,
        draft_params=params, draft_cfg=cfg, spec_k=2,
    )
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    streamed_lps = {r: [] for r in rids}

    def cb(rid, new, done, lps):
        assert lps is not None and len(lps) == len(new)
        streamed_lps[rid].extend(lps)

    res = b.run(on_tokens=cb)
    for pr, r in zip(plain_rids, rids):
        assert res[r] == plain_res[pr]  # spec is greedy-exact
        assert len(b.result_logprobs[r]) == len(res[r])
        assert streamed_lps[r] == b.result_logprobs[r]
        for a, c in zip(plain.result_logprobs[pr], b.result_logprobs[r]):
            assert abs(a - c) < 5e-4, (a, c)


def test_penalties_break_repetition_and_preserve_neighbors(tiny):
    """A frequency/presence-penalized greedy row must diverge from the
    unpenalized greedy run once repetition appears, while an unpenalized
    greedy neighbor in the same batch stays bit-exact with its solo run."""
    ids, n = [7, 1, 9], 20
    plain_b = make(tiny)
    plain_rid = plain_b.submit(ids, max_new_tokens=n)
    plain = plain_b.run()[plain_rid]
    # Random tiny models loop hard; the premise of the test is repetition.
    assert len(set(plain)) < len(plain)

    other_ids, other_n = [4, 4, 4, 4], 9
    solo_b = make(tiny)
    solo_rid = solo_b.submit(other_ids, max_new_tokens=other_n)
    solo = solo_b.run()[solo_rid]

    b = make(tiny)
    rid_pen = b.submit(ids, max_new_tokens=n, presence_penalty=1.5,
                       frequency_penalty=1.5)
    rid_other = b.submit(other_ids, max_new_tokens=other_n)
    res = b.run()
    assert res[rid_pen] != plain          # penalties changed the argmax path
    assert res[rid_other] == solo         # neighbor untouched
    # Explicit zero penalties are the identity.
    z = make(tiny)
    rid_z = z.submit(ids, max_new_tokens=n, presence_penalty=0.0,
                     frequency_penalty=0.0)
    assert z.run()[rid_z] == plain


def test_penalty_validation(tiny):
    b = make(tiny)
    with pytest.raises(ValueError, match="presence_penalty"):
        b.submit([1, 2], max_new_tokens=4, presence_penalty=2.5)
    with pytest.raises(ValueError, match="frequency_penalty"):
        b.submit([1, 2], max_new_tokens=4, frequency_penalty=float("nan"))


@pytest.mark.fragile_xla_cpu
def test_speculative_penalties_match_plain(tiny):
    """Penalized speculative batching is bit-exact vs the penalized plain
    batcher: verify position j's penalty histogram (base + drafts 1..j)
    equals the sequential decode's committed-context histogram within the
    accepted lead — so the adjusted argmax chain is identical.  An
    unpenalized neighbor in the same spec batch stays exact too."""
    cfg, params = tiny
    ids, n = [7, 1, 9], 20
    other_ids, other_n = [4, 4, 4, 4], 9

    plain = make(tiny)
    p_pen = plain.submit(ids, max_new_tokens=n, presence_penalty=1.5,
                         frequency_penalty=1.5)
    p_other = plain.submit(other_ids, max_new_tokens=other_n)
    p_res = plain.run()
    # Premise: penalties actually changed the path (vs unpenalized run).
    un = make(tiny)
    u_rid = un.submit(ids, max_new_tokens=n)
    assert p_res[p_pen] != un.run()[u_rid]

    spec = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=96, chunk_steps=4,
        draft_params=params, draft_cfg=cfg, spec_k=2,
    )
    s_pen = spec.submit(ids, max_new_tokens=n, presence_penalty=1.5,
                        frequency_penalty=1.5)
    s_other = spec.submit(other_ids, max_new_tokens=other_n)
    s_res = spec.run()
    assert s_res[s_pen] == p_res[p_pen]
    assert s_res[s_other] == p_res[p_other]
