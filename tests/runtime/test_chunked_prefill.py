"""Chunked prefill (runtime/batcher.py prefill_chunk).

Invariant: admission that consumes a prompt ``prefill_chunk`` tokens per
scheduling round — interleaved with other rows' decode chunks — produces
TOKEN-IDENTICAL results vs monolithic admission: the chunk steps are the
prefix-continuation math against the row's own partial prompt (the same
machinery as prefix-cached admission, pinned equivalent by
tests/runtime/test_session.py), and the final first-token sample runs the
same _finish_admission.  Logprob values agree to float drift (the same
attention reduces in different shapes).  What changes is scheduling
latency, never tokens.
"""

import jax
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

# Fresh-process isolation (compile-heavy; shared marker, tests/conftest.py).
pytestmark = pytest.mark.fragile_xla_cpu


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _run(cfg, params, reqs, chunk=None, prefixes=(), **kw):
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=96, chunk_steps=4,
        prefill_chunk=chunk, **kw,
    )
    for name, ids in prefixes:
        b.register_prefix(name, ids)
    rids = [b.submit(ids, max_new_tokens=n, **req_kw)
            for ids, n, req_kw in reqs]
    return b, rids, b.run()


@pytest.mark.parametrize("chunk", [1, 3, 16])
def test_chunked_matches_monolithic(tiny, chunk):
    """Mixed long/short prompts, more requests than slots: every request's
    tokens AND logprobs match the monolithic batcher exactly, for chunk
    sizes splitting prompts at 1, mid, and barely."""
    cfg, params = tiny
    reqs = [
        (list(range(7, 27)), 6, {}),        # 20-token prompt: chunks
        ([4, 4, 4], 5, {}),                 # short: admits monolithically
        (list(range(40, 75)), 8, {}),       # 35 tokens, slot reuse
        ([11, 12], 9, {}),
    ]
    plain_b, rp, plain = _run(cfg, params, reqs)
    pb, rc, chunked = _run(cfg, params, reqs, chunk=chunk)
    for a, c in zip(rp, rc):
        assert plain[a] == chunked[c], (a, plain[a], chunked[c])
        assert len(plain_b.result_logprobs[a]) == len(pb.result_logprobs[c])
        # Tokens are bit-identical (argmax is drift-stable); logprob VALUES
        # carry float-level drift (~1e-5) because chunked forwards reduce
        # the same attention in different shapes.
        for x, y in zip(plain_b.result_logprobs[a], pb.result_logprobs[c]):
            assert abs(x - y) < 1e-3, (x, y)


def test_chunked_prefix_cached_matches(tiny):
    """Prefix-cached requests: the registered prefix KV seeds the transient
    row (never mutated — no donation), the suffix chunks, results equal the
    monolithic prefix path."""
    cfg, params = tiny
    prefixes = [("sys", [9, 8, 7, 6, 5])]
    reqs = [
        (list(range(20, 36)), 7, {"prefix": "sys"}),
        ([1, 2], 5, {"prefix": "sys"}),
        ([4, 4, 4], 6, {}),
    ]
    _, rp, plain = _run(cfg, params, reqs, prefixes=prefixes)
    pb, rc, chunked = _run(cfg, params, reqs, chunk=4, prefixes=prefixes)
    for a, c in zip(rp, rc):
        assert plain[a] == chunked[c]
    # The prefix is reusable afterwards (its buffers were not donated).
    rid = pb.submit([3], max_new_tokens=4, prefix="sys")
    assert len(pb.run()[rid]) == 4


def test_chunked_paged_auto_prefix_cache_hit_matches(tiny):
    """Chunked prefill now CONSULTS the automatic prefix cache (the PR-3
    TODO): a chunked admission whose prompt's leading pages are cached
    seeds its transient row from the shared pages and chunks only the
    un-cached suffix — tokens stay temp-0 identical to the monolithic
    contiguous run, the hit is accounted, and the retained pages release
    cleanly on completion AND on a mid-prefill cancel."""
    from distributed_llms_tpu.core.observability import METRICS

    cfg, params = tiny
    shared = [((i * 37) % 450) + 1 for i in range(36)]
    reqs = [
        (shared + [7, 1, 9], 6, {}),      # publishes the shared pages
        (shared + [4, 4, 2, 8], 5, {}),   # 40 tokens: 2 full cached pages
    ]
    _, rp, plain = _run(cfg, params, reqs)
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=96, chunk_steps=4,
        prefill_chunk=6, paged_pages=16, page_size=16, prefix_cache=True,
    )
    r1 = b.submit(reqs[0][0], max_new_tokens=6)
    assert b.run()[r1] == plain[rp[0]]
    assert b.prefix_cache.hit_tokens == 0  # first writer: all miss
    chunks0 = METRICS.get_counter("batcher.prefill_chunks")
    r2 = b.submit(reqs[1][0], max_new_tokens=5)
    res = b.run()
    assert res[r2] == plain[rp[1]]
    # The cached run (2 full pages = 32 tokens) seeded the row; only the
    # 8-token suffix chunked through the model (2 bites at chunk=6).
    assert b.prefix_cache.hit_tokens == 32
    assert b.prefix_cached_tokens[r2] == 32
    assert METRICS.get_counter("batcher.prefill_chunks") - chunks0 == 2
    b.assert_pool_consistent()
    # Mid-prefill cancel: the reserving row holds the retained cached
    # pages; cancel releases them and the allocator audits clean.
    r3 = b.submit(shared + [9, 9, 9], max_new_tokens=4)
    b._admit_pending()  # one 6-token bite of the 7-token suffix: pending
    assert b.rows[0].prefilling and len(b.rows[0].pages) == 2
    assert b.cancel_row(r3)
    assert not b._prefills
    b.assert_pool_consistent()


def test_chunked_streaming_and_sampling(tiny):
    """Streaming reassembles exactly (first token streams at admission
    completion) and greedy rows stay bit-exact vs monolithic even while a
    sampled row shares the batch.  The SAMPLED row itself draws from the
    same distribution but a different RNG stream (the split order follows
    the scheduling rounds, which chunking changes) — pinned per-seed
    deterministic instead of bit-equal."""
    cfg, params = tiny
    reqs = [
        (list(range(7, 25)), 6, {"temperature": 1.1}),
        ([4, 4], 5, {}),
    ]
    _, rp, plain = _run(cfg, params, reqs, seed=3)

    def chunked_run():
        b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=96,
                              chunk_steps=4, prefill_chunk=5, seed=3)
        rids = [b.submit(ids, max_new_tokens=n, **kw)
                for ids, n, kw in reqs]
        streamed = {r: [] for r in rids}
        dones = {r: 0 for r in rids}

        def cb(rid, new, done, lps):
            streamed[rid].extend(new)
            dones[rid] += bool(done)

        res = b.run(on_tokens=cb)
        for r in rids:
            assert streamed[r] == res[r]
            assert dones[r] == 1
        return [res[r] for r in rids]

    first = chunked_run()
    assert len(first[0]) == 6
    assert first[1] == plain[rp[1]]     # greedy neighbor: bit-exact
    assert first == chunked_run()       # sampled row: per-seed deterministic


def test_interleaved_long_prompts_prefill_concurrently(tiny):
    """Two long prompts chunk their prefills CONCURRENTLY (the old
    one-in-flight head-of-line limit is lifted): both are pending at once
    mid-admission, and every request still matches the monolithic batcher
    token for token."""
    cfg, params = tiny
    reqs = [
        (list(range(7, 27)), 6, {}),     # 20 tokens: chunks
        (list(range(40, 62)), 5, {}),    # 22 tokens: chunks alongside
        ([4, 4, 4], 7, {}),
    ]
    _, rp, plain = _run(cfg, params, reqs)
    b = ContinuousBatcher(cfg, params, batch_slots=3, max_len=96,
                          chunk_steps=4, prefill_chunk=3)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n, _kw in reqs]
    # One scheduling round admits both long prompts into prefill slots.
    b._admit_pending()
    assert len(b._prefills) == 2, "long prompts did not interleave"
    assert sum(r.prefilling for r in b.rows) == 2
    chunked = b.run()
    for a, c in zip(rp, rids):
        assert plain[a] == chunked[c], (a, plain[a], chunked[c])

    # The cap still binds: a third long prompt waits (FIFO) while two are
    # in flight, and a 1-slot concurrency behaves like the old limit.
    b2 = ContinuousBatcher(cfg, params, batch_slots=3, max_len=96,
                           chunk_steps=4, prefill_chunk=3,
                           prefill_concurrency=1)
    for ids, n, _kw in reqs[:2]:
        b2.submit(ids, max_new_tokens=n)
    b2._admit_pending()
    assert len(b2._prefills) == 1
    res2 = b2.run()
    assert list(res2.values()) == [plain[rp[0]], plain[rp[1]]]


def test_chunked_cancel_mid_prefill(tiny):
    """Cancelling a request whose prompt is still chunking frees the slot
    (nothing was spliced into the shared cache) and later requests reuse
    it with exact results."""
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=1, max_len=96,
                          chunk_steps=4, prefill_chunk=3)
    long_rid = b.submit(list(range(7, 27)), max_new_tokens=6)

    # Drive ONE scheduling round manually: the prefill starts but cannot
    # finish (20 tokens / 3-token chunks).
    b._admit_pending()
    assert b._prefills and b.rows[0].prefilling
    assert b.cancel_row(long_rid)
    assert not b._prefills and b.rows[0].rid is None
    assert b.results[long_rid] == []

    follow = b.submit([4, 4, 4], max_new_tokens=5)
    res = b.run()
    solo = ContinuousBatcher(cfg, params, batch_slots=1, max_len=96,
                             chunk_steps=4)
    srid = solo.submit([4, 4, 4], max_new_tokens=5)
    assert res[follow] == solo.run()[srid]


def test_chunked_guards(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatcher(cfg, params, max_len=64, prefill_chunk=0)
    # Chunked prefill composes with paged AND dp/tp meshes now; only the
    # speculative draft's monolithic admission remains incompatible.
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(cfg, params, max_len=64, prefill_chunk=4,
                          draft_params=params, draft_cfg=cfg)
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    eng = InferenceEngine.from_preset(
        "llama-tiny", RuntimeConfig(max_decode_steps=6, max_seq_len=96),
        vocab_size=300,
    )
    cb = eng.continuous_batcher(batch_slots=2, max_len=64, prefill_chunk=4)
    assert cb.prefill_chunk == 4
    rid = cb.submit("hello world, a long-ish prompt", max_new_tokens=5)
    plain = eng.continuous_batcher(batch_slots=2, max_len=64)
    prid = plain.submit("hello world, a long-ish prompt", max_new_tokens=5)
    assert cb.run()[rid] == plain.run()[prid]
