"""Weight-only quantized serving (SURVEY §7 hard part 6): block weights stay
int8/int4 in device memory and the blockwise dequant fuses into each layer's
matmuls at use — vs round 1 where the store could quantize but serving always
rehydrated to full dtype at load."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint import quantize as quant_lib
from distributed_llms_tpu.checkpoint import store as store_lib
from distributed_llms_tpu.core.config import RuntimeConfig
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.engine import InferenceEngine


@pytest.mark.parametrize("name", ["llama-tiny", "gpt2-tiny"])
def test_quantized_blocks_forward_matches_dequantized(name):
    """Dequant-at-use == dequant-at-load, bit for bit (same q*scale op)."""
    cfg = presets.get_preset(name)
    params = model_lib.init_params(jax.random.key(0), cfg)
    qblocks = quant_lib.quantize_tree(params["blocks"], bits=8, block=32)
    deq = {**params, "blocks": quant_lib.dequantize_tree(qblocks, jnp.dtype(cfg.dtype))}
    live = {**params, "blocks": qblocks}
    toks = jax.random.randint(jax.random.key(1), (2, 7), 0, cfg.vocab_size, dtype=jnp.int32)
    ref, _ = model_lib.forward(deq, cfg, toks)
    out, _ = model_lib.forward(live, cfg, toks)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


@pytest.mark.parametrize("quantization", ["int8", "int4"])
def test_engine_serves_quantized_store(tmp_path, quantization):
    """serve_quantized=True keeps block weights quantized in memory and
    generates the same tokens as serving the dequantized store."""
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(
        params, str(tmp_path), num_shards=2, model_config=cfg,
        quantization=quantization, quant_block=32,
    )
    rt = RuntimeConfig(max_decode_steps=6)
    ref = InferenceEngine.from_store(str(tmp_path), rt=rt)
    eng = InferenceEngine.from_store(
        str(tmp_path), rt=RuntimeConfig(max_decode_steps=6, serve_quantized=True)
    )
    # Block weights really are resident quantized.
    qleaves = [
        x for x in jax.tree.leaves(
            eng.params["blocks"],
            is_leaf=lambda x: isinstance(x, quant_lib.QuantizedTensor),
        )
        if isinstance(x, quant_lib.QuantizedTensor)
    ]
    assert qleaves, "no QuantizedTensor leaves survived into the engine"
    assert quant_lib.tree_bytes(eng.params["blocks"]) < quant_lib.tree_bytes(
        ref.params["blocks"]
    )
    out_ref = ref.generate_text(["hello world", "hi"])
    out = eng.generate_text(["hello world", "hi"])
    assert out.text == out_ref.text


@pytest.mark.parametrize("quantization", ["int8", "int4"])
def test_sessions_over_quantized_weights(tmp_path, quantization):
    """Multi-turn sessions with quantized-resident block weights: both the
    first turn and a continuation must match the engine serving the same
    store dequantized (identical q*scale math, dequant-at-use vs at-load)."""
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(
        params, str(tmp_path), num_shards=1, model_config=cfg,
        quantization=quantization, quant_block=32,
    )
    rt_q = RuntimeConfig(max_decode_steps=5, serve_quantized=True, max_seq_len=64)
    rt_d = RuntimeConfig(max_decode_steps=5, max_seq_len=64)
    eng_q = InferenceEngine.from_store(str(tmp_path), rt=rt_q)
    eng_d = InferenceEngine.from_store(str(tmp_path), rt=rt_d)
    sid_q, first_q = eng_q.start_session(["hello world"])
    sid_d, first_d = eng_d.start_session(["hello world"])
    assert first_q.tokens.tolist() == first_d.tokens.tolist()
    more_q = eng_q.continue_session(sid_q, [" again"])
    more_d = eng_d.continue_session(sid_d, [" again"])
    assert more_q.tokens.tolist() == more_d.tokens.tolist()


def test_serve_quantized_requires_quantized_store(tmp_path):
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_lib.save_shards(params, str(tmp_path), num_shards=1, model_config=cfg)
    with pytest.raises(ValueError, match="serve_quantized"):
        InferenceEngine.from_store(
            str(tmp_path), rt=RuntimeConfig(serve_quantized=True)
        )


def test_dequantize_is_slice_safe():
    """dequantize must work on a per-layer slice of a stacked QuantizedTensor
    (what lax.scan hands the block body), not just the full [L, ...] tree."""
    x = jax.random.normal(jax.random.key(0), (4, 8, 16), jnp.float32)
    qt = quant_lib.quantize(x, bits=8, block=8)
    sliced = quant_lib.QuantizedTensor(
        data=qt.data[1], scale=qt.scale[1], bits=qt.bits, orig_shape=qt.orig_shape
    )
    full = quant_lib.dequantize(qt)
    np.testing.assert_array_equal(np.asarray(full[1]), np.asarray(quant_lib.dequantize(sliced)))
