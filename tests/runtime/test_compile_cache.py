"""RuntimeConfig.compilation_cache_dir: a restarted serving process reuses
compiled programs (VERDICT r3 weak #8's compile-bound pain, turned into a
product knob — on TPU the first 7B decode compile is ~20-40 s)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from distributed_llms_tpu.core.config import RuntimeConfig
from distributed_llms_tpu.runtime.engine import InferenceEngine

eng = InferenceEngine.from_preset(
    "llama-tiny", vocab_size=512,
    rt=RuntimeConfig(max_decode_steps=4, compilation_cache_dir={cache!r}),
)
t0 = time.perf_counter()
eng.generate_text(["cache me"], max_new_tokens=4)
print(f"GEN_WALL {{time.perf_counter() - t0:.3f}}")
"""


def test_restarted_process_hits_cache(tmp_path):
    cache = str(tmp_path / "cc")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    walls = []
    for _ in range(3):  # 1 cold + 2 warm (best-of-2 absorbs CI jitter)
        r = subprocess.run(
            [sys.executable, "-c", CHILD.format(repo=REPO, cache=cache)],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        walls.append(float(r.stdout.split("GEN_WALL")[1].strip()))
    assert os.listdir(cache), "no cache entries were written"
    # A restarted process must be materially faster than the cold one
    # (measured ~5x; the generous margin keeps loaded-CI noise out).
    assert min(walls[1:]) < walls[0] * 0.75, walls
