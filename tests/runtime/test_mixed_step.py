"""Stall-free mixed batching (runtime/scheduler.py + batcher.mixed_step).

Two invariants:

1. POLICY is extracted: every scheduling decision — admission order,
   chunk sizing against the token budget, victim selection, the pressure
   ladder, the overlap sync-trigger list — is a declared hook on the
   scheduler object, unit-testable with plain host data (no model, no
   device, no batcher).

2. MECHANISM is exact: ``--schedule mixed`` (the fused token-budget step
   — decode legs + the head pending prefill's bite in ONE compiled
   program) produces temp-0 token streams BYTE-IDENTICAL to
   ``--schedule alternate`` (the classic serialized prefill rounds)
   across the composition matrix: prefix cache, chunked prefill,
   preempt+swap, int8 KV pages, overlap on/off.  Chunk splits and
   program fusion change scheduling, never math.

Also pins the overlap x disaggregation corner ROADMAP called only
partially pinned: a decode-role engine adopts a KV handoff arriving
MID-SPAN (the import is a sync trigger) byte-exact with overlap on vs
off.
"""

import jax
import pytest

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import scheduler as scheduler_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane, InjectedFault
from distributed_llms_tpu.runtime.scheduler import (
    HOOKS, PRESSURE_LADDER, MixedScheduler, Scheduler, SyncView,
    make_scheduler,
)
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


# -- policy hooks: unit tests without a model --------------------------------


class _Req:
    """Queue-entry stand-in: the hooks consume only (priority, rid)."""

    def __init__(self, rid, priority=0):
        self.rid, self.priority = rid, priority


def _view(**kw):
    base = dict(any_active=True, cancel_dirty=False, queued=False,
                kv_imports=False, prefills=0, head_prefill_left=0,
                live_budgets=(100,), chunks_ahead=1,
                grow_blocked=lambda: False)
    base.update(kw)
    return SyncView(**base)


def test_every_declared_hook_exists_on_every_policy():
    for cls in (Scheduler, MixedScheduler):
        pol = cls()
        for hook in HOOKS:
            assert callable(getattr(pol, hook)), (cls.__name__, hook)
    assert set(scheduler_lib.POLICIES) == {"alternate", "mixed"}


def test_admission_order_priority_then_fifo():
    pol = Scheduler()
    assert pol.admission_order([]) is None
    q = [_Req(3), _Req(1, priority=1), _Req(2, priority=1), _Req(0)]
    # Highest priority wins; FIFO (lowest rid) within the class — a
    # preempted resume (old rid) re-admits ahead of later arrivals.
    assert pol.admission_order(q).rid == 1
    assert MixedScheduler().admission_order(q).rid == 1
    assert pol.admission_order([_Req(5), _Req(4)]).rid == 4


def test_select_victim_lowest_priority_most_recent():
    pol = MixedScheduler()
    cands = [(0, 1, 10), (1, 0, 5), (2, 0, 7), (3, 2, 1)]
    assert pol.select_victim(cands) == 2          # prio 0, newest admit
    assert pol.select_victim(cands, below_priority=2) == 2
    # Strictly-lower restriction: nothing below priority 0.
    assert pol.select_victim(cands, below_priority=0) is None
    assert pol.select_victim([]) is None


def test_prefill_bite_budget_split():
    # Mixed with a budget: decode legs claim n_active first.
    m = MixedScheduler(token_budget=16, prefill_chunk=8)
    assert m.prefill_bite(remaining=100, n_active=3) == 13
    assert m.prefill_bite(remaining=5, n_active=3) == 5   # capped
    assert m.prefill_bite(remaining=100, n_active=40) == 1  # floor: progress
    # No budget: fusion keeps prefill_chunk-sized bites.
    assert MixedScheduler(prefill_chunk=8).prefill_bite(100, 3) == 8
    # Alternate spends the full chunk regardless of live decode rows.
    a = Scheduler(prefill_chunk=8, token_budget=16)
    assert a.prefill_bite(100, 3) == 8


def test_chunk_threshold_and_auto_chunk():
    assert Scheduler(prefill_chunk=8).chunk_threshold() == 8
    assert Scheduler(token_budget=32).chunk_threshold() is None
    assert MixedScheduler(prefill_chunk=8, token_budget=32) \
        .chunk_threshold() == 8
    # Budget set, no prefill_chunk: prompts past the budget auto-chunk.
    assert MixedScheduler(token_budget=32).chunk_threshold() == 32
    assert MixedScheduler(token_budget=32,
                          speculative=True).chunk_threshold() is None
    assert MixedScheduler().chunk_threshold() is None


def test_pressure_ladder_declared():
    for pol in (Scheduler(), MixedScheduler()):
        assert pol.pressure_rungs() == PRESSURE_LADDER
    assert PRESSURE_LADDER == (
        "evict_spill", "swap_preempt", "recompute_preempt", "back_pressure",
    )


def test_sync_triggers_alternate_vs_mixed():
    alt, mix = Scheduler(chunk_steps=8), MixedScheduler(chunk_steps=8)
    assert alt.sync_triggers(_view()) == []
    assert "all_idle" in alt.sync_triggers(_view(any_active=False))
    assert "cancel" in alt.sync_triggers(_view(cancel_dirty=True))
    assert "queued" in alt.sync_triggers(_view(queued=True))
    assert "kv_import" in alt.sync_triggers(_view(kv_imports=True))
    # THE divergence: a pending prefill parks the alternate span; the
    # mixed span keeps dispatching (the bite rides the fused step) and
    # syncs only for the finishing splice.
    v = _view(prefills=1, head_prefill_left=10)
    assert alt.sync_triggers(v) == ["prefill"]
    assert mix.sync_triggers(v) == []
    done = _view(prefills=1, head_prefill_left=0)
    assert mix.sync_triggers(done) == ["prefill_finish"]
    assert alt.sync_triggers(done) == ["prefill"]


def test_sync_triggers_budget_certainty_and_growth():
    pol = MixedScheduler(chunk_steps=8)
    certain = _view(live_budgets=(8, 3), chunks_ahead=1)
    assert pol.sync_triggers(certain) == ["budget_certain"]
    assert pol.sync_triggers(_view(live_budgets=(9,), chunks_ahead=1)) == []
    # Speculative rounds commit at least ONE token, not chunk_steps.
    spec = MixedScheduler(chunk_steps=8, speculative=True)
    assert spec.sync_triggers(_view(live_budgets=(2,), chunks_ahead=1)) == []
    # Growth is probed LAST (it allocates from spare capacity): a cheaper
    # trigger short-circuits the thunk entirely.
    probed = []
    blocked = _view(grow_blocked=lambda: probed.append(1) or True)
    assert pol.sync_triggers(blocked) == ["page_pressure"]
    assert probed == [1]
    probed.clear()
    assert pol.sync_triggers(_view(
        queued=True, grow_blocked=lambda: probed.append(1) or True,
    )) == ["queued"]
    assert probed == []  # never evaluated


def test_make_scheduler_validation():
    assert make_scheduler("mixed").name == "mixed"
    assert make_scheduler("alternate").name == "alternate"
    with pytest.raises(ValueError, match="unknown schedule"):
        make_scheduler("sarathi")
    with pytest.raises(ValueError, match="token_budget"):
        make_scheduler("mixed", token_budget=0)


def test_batcher_rejects_bad_schedule():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = jax.eval_shape(
        lambda: model_lib.init_params(jax.random.key(0), cfg)
    )  # ctor validation fires before any device work needs real params
    with pytest.raises(ValueError, match="unknown schedule"):
        ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          schedule="sarathi")


# -- mechanism: byte-equality across the composition matrix ------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def mk(tiny, schedule, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        schedule=schedule, **kw,
    )


def drive(b, reqs):
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return [res[r] for r in rids]


def legs(tiny, reqs, **kw):
    """The same requests under alternate then mixed; returns
    (alt_tokens, mixed_tokens, mixed_batcher)."""
    alt = drive(mk(tiny, "alternate", **kw), reqs)
    bm = mk(tiny, "mixed", **kw)
    mixed = drive(bm, reqs)
    return alt, mixed, bm


LONG = "the quick brown fox jumped over the lazy dog " * 2  # 90 bytes
REQS = [(LONG[:70], 10), ("hi!", 8), (LONG[:55], 12)]


@pytest.mark.fragile_xla_cpu
def test_mixed_matches_alternate_chunked_and_monolithic(tiny):
    """Contiguous mode: chunked prefill fused vs serialized, plus the
    monolithic reference — token-identical, and the mixed leg really
    fused (budget metrics moved, zero stall rounds)."""
    mono = drive(mk(tiny, "alternate"), REQS)
    s0 = METRICS.get_counter("batcher.sched.stall_rounds")
    b0 = METRICS.get_counter("batcher.sched.budget_tokens")
    alt, mixed, _ = legs(tiny, REQS, prefill_chunk=8)
    stalls = METRICS.get_counter("batcher.sched.stall_rounds") - s0
    assert alt == mono and mixed == mono
    assert METRICS.get_counter("batcher.sched.budget_tokens") > b0, \
        "the fused mixed step never dispatched"
    assert stalls > 0  # the alternate leg's serialized bites counted
    # Token budget resizes bites; bytes must not move.
    _, budgeted, _ = legs(tiny, REQS, prefill_chunk=8, token_budget=12)
    assert budgeted == mono
    # Auto-chunk: budget set, prefill_chunk never configured.
    auto = drive(mk(tiny, "mixed", token_budget=16), REQS)
    assert auto == mono


@pytest.mark.fragile_xla_cpu
def test_mixed_stall_free_while_prefill_rides(tiny):
    """While a long prompt prefills next to live decode rows the mixed
    schedule runs ZERO serialized prefill bites (every bite fused) and
    the dispatch-ahead span keeps running (alternate parks it: a pending
    prefill is a sync trigger there)."""
    s0 = METRICS.get_counter("batcher.sched.stall_rounds")
    bm = mk(tiny, "mixed", prefill_chunk=6, token_budget=12, batch_slots=3)
    res = drive(bm, [("decode row busy", 24), (LONG[:80], 6), ("x", 20)])
    assert all(res)
    assert METRICS.get_counter("batcher.sched.stall_rounds") - s0 == 0
    assert bm.overlap_stats["dispatched_ahead"] > 0
    util = METRICS.get_gauge("batcher.sched.budget_utilization")
    assert 0.0 < util <= 1.0


@pytest.mark.fragile_xla_cpu
def test_mixed_matches_alternate_paged_prefix_cache(tiny):
    """Paged pool + automatic prefix cache: the fused finish publishes
    the same digests (cache hits identical across schedules)."""
    shared = LONG[:48]  # 3 full 16-token pages
    kw = dict(prefill_chunk=8, paged_pages=24, page_size=16,
              prefix_cache=True)

    def leg(schedule):
        b = mk(tiny, schedule, **kw)
        r1 = b.submit(shared + " tail one", max_new_tokens=8)
        first = b.run()[r1]  # publishes the shared pages at its finish
        r2 = b.submit(shared + " two!", max_new_tokens=8)
        r3 = b.submit("short", max_new_tokens=6)
        res = b.run()
        return [first, res[r2], res[r3]], b

    alt, _ = leg("alternate")
    mixed, bm = leg("mixed")
    assert alt == mixed
    assert bm.prefix_cache.hit_tokens >= 48  # the chunked start hit
    bm.assert_pool_consistent()


@pytest.mark.fragile_xla_cpu
def test_mixed_matches_alternate_preempt_and_swap(tiny):
    """A pool too small for every row's full depth: growth escalates to
    preemption (and host-tier swap restore) mid-run under BOTH
    schedules; the reunited streams stay byte-identical."""
    reqs = [("a" * 20, 40), ("b" * 25, 40)]
    kw = dict(paged_pages=8, page_size=16, prefix_cache=True,
              prefill_chunk=8, host_pages=16)
    swaps0 = METRICS.get_counter("batcher.kv_swaps.in")
    alt, mixed, bm = legs(tiny, reqs, **kw)
    assert alt == mixed
    assert bm.preemptions > 0  # the pressure ladder really ran
    assert METRICS.get_counter("batcher.kv_swaps.in") > swaps0
    bm.assert_pool_consistent()


@pytest.mark.fragile_xla_cpu
def test_mixed_matches_alternate_int8_and_overlap_off(tiny):
    """int8 KV pages (deterministic quantized decode) and the fully-
    synchronous loop: fusion composes with both — overlap is about WHEN
    the host syncs, the fused step is about WHAT one dispatch runs."""
    kw = dict(prefill_chunk=8, paged_pages=24, page_size=16,
              prefix_cache=True, kv_bits=8)
    alt, mixed, bm = legs(tiny, REQS, **kw)
    assert alt == mixed
    bm.assert_pool_consistent()
    off_alt, off_mixed, _ = legs(tiny, REQS, overlap=False, **kw)
    assert off_alt == alt and off_mixed == alt


@pytest.mark.fragile_xla_cpu
def test_mixed_step_fault_site_drill(tiny):
    """The batcher.mixed_step site fires per fused dispatch (tag
    'prefill'): a raise drill crashes the first fused step — the
    supervisor-restart class for the stall-free path — and the rule
    counts exactly one firing."""
    plane = FaultPlane.parse("batcher.mixed_step/prefill:raise@1")
    b = mk(tiny, "mixed", prefill_chunk=6, faults=plane)
    b.submit("seed an active decode row", max_new_tokens=16)
    b.submit(LONG[:60], max_new_tokens=4)
    with pytest.raises(InjectedFault):
        b.run()
    assert plane.rules[0].fired == 1


@pytest.mark.fragile_xla_cpu
def test_mixed_step_stall_drill_delays_but_stays_exact(tiny):
    """batcher.mixed_step stall drill: a fused dispatch held at the step
    boundary delays the run measurably but moves no tokens — the
    slow-step analog of the raise drill above."""
    import time

    ref = mk(tiny, "mixed", prefill_chunk=6)
    r0 = ref.submit("seed an active decode row", max_new_tokens=16)
    want = ref.run()[r0]
    plane = FaultPlane.parse("batcher.mixed_step:stall@1:0.05")
    b = mk(tiny, "mixed", prefill_chunk=6, faults=plane)
    rid = b.submit("seed an active decode row", max_new_tokens=16)
    t0 = time.perf_counter()
    res = b.run()
    assert time.perf_counter() - t0 >= 0.05
    assert res[rid] == want
    assert plane.rules[0].fired == 1
    b.assert_pool_consistent()


@pytest.mark.fragile_xla_cpu
def test_kv_handoff_adopted_mid_span_exact_overlap_on_vs_off(tiny):
    """Overlap x disaggregation corner (ROADMAP: only partially pinned):
    a decode-role engine adopts a verified KV handoff arriving while a
    span is dispatching ahead — the import is a sync trigger, the
    adopted pages serve the forwarded prompt's prefix — byte-exact with
    overlap on vs off, and the handoff request's bytes match a fully
    colocated run."""
    cfg, params = tiny
    blk = 16
    handoff_prompt = LONG[:40]  # 40 bytes -> 2 full 16-token pages
    # Prefill-role engine: serve the prompt once (pages publish content-
    # addressed), then export the cached run for handoff.
    bp = mk(tiny, "mixed", paged_pages=24, page_size=blk,
            prefix_cache=True)
    ids = bp.tokenizer.encode(handoff_prompt)
    bp.submit(handoff_prompt, max_new_tokens=1)
    bp.run()
    export = bp.export_prefix_pages(ids)
    assert export is not None
    digests, k_pages, v_pages = export
    assert len(digests) == (len(ids) - 1) // blk
    # Colocated reference: the same two requests, no handoff anywhere.
    ref = drive(mk(tiny, "mixed", paged_pages=24, page_size=blk,
                   prefix_cache=True), [("resident row", 24),
                                        (handoff_prompt, 8)])

    def leg(overlap):
        b = mk(tiny, "mixed", paged_pages=24, page_size=blk,
               prefix_cache=True, overlap=overlap)
        r0 = b.submit("resident row", max_new_tokens=24)
        state = {"sent": False, "rid": None, "acks": []}

        def cb(rid, new, done, lps):
            # Deterministic mid-run arrival: once the resident row has
            # streamed 8+ tokens (mid-span on the overlap leg), the
            # verified transfer lands and the forwarded request follows.
            if not state["sent"] and rid == r0 and not done \
                    and len(b.rows[0].emitted) >= 8:
                state["sent"] = True
                b.submit_kv_import(
                    digests, k_pages, v_pages,
                    on_done=lambda ok, reason: state["acks"].append(
                        (ok, reason)),
                )
                state["rid"] = b.submit(handoff_prompt, max_new_tokens=8)
        res = b.run(on_tokens=cb)
        assert state["acks"] == [(True, "imported")]
        # The adopted pages served the forwarded prompt's full-page run.
        assert b.prefix_cached_tokens[state["rid"]] == len(digests) * blk
        b.assert_pool_consistent()
        return [res[r0], res[state["rid"]]]

    off, on = leg(False), leg(True)
    assert on == off
    assert on[1] == ref[1]  # handoff vs colocated: same bytes


# -- config plumbing ---------------------------------------------------------


def test_engine_and_config_plumbing(tiny):
    """RuntimeConfig.schedule/token_budget thread through
    engine.continuous_batcher (explicit args win; 0 budget = off), and
    the batcher snapshot rebuilds the policy on respawn."""
    import dataclasses

    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    assert RuntimeConfig().schedule == "mixed"
    assert RuntimeConfig().token_budget is None
    rt = dataclasses.replace(
        RuntimeConfig(), max_seq_len=64, schedule="alternate",
        token_budget=24,
    )
    eng = InferenceEngine.from_preset("llama-tiny", rt=rt,
                                      vocab_size=512)
    b = eng.continuous_batcher(batch_slots=2, max_len=64)
    assert b.sched.name == "alternate" and b.sched.token_budget == 24
    b2 = eng.continuous_batcher(batch_slots=2, max_len=64,
                                schedule="mixed", token_budget=0)
    assert b2.sched.name == "mixed" and b2.sched.token_budget is None
    # respawn() rebuilds an identical policy from the ctor snapshot.
    assert b2.respawn().sched.name == "mixed"
    # The CLI declares the knobs (graftlint GL303 pins the table; this
    # pins the intent).
    from distributed_llms_tpu.cli.serve_main import _RUNTIME_FLAGS

    assert _RUNTIME_FLAGS["schedule"] == "schedule"
    assert _RUNTIME_FLAGS["token-budget"] == "token_budget"
