"""KV memory tiering (runtime/batcher.py, PR 9): int8 quantized KV pages
plus the async host-RAM offload tier behind the paged pool.

The acceptance contract pinned here:

- **Offload is exact.**  Every bf16 host-tier path — swap-preemption
  (raw pages parked at preempt, scattered back at restore) and
  prefix-cache spill/restore (cold pages captured ahead of LRU eviction,
  restored on a later hit) — produces temp-0 streams BYTE-EXACT against
  the untier'd reference.  Verification failures (corrupt drills) degrade
  to exact recompute / cold prefill, never to wrong tokens.
- **Quantization is parity-bounded.**  int8 pages (``kv_bits=8``) are
  deterministic and hit pinned greedy token-agreement thresholds vs the
  bf16 reference; offload paths under int8 are byte-exact against the
  *int8* unpreempted run (raw quantized bytes round-trip verbatim).
- **The audit spans tiers.**  ``assert_pool_consistent()`` extends to the
  host tier: every swap parcel must be owned by exactly one queued resume
  request, budget accounting must balance — run after every workload
  here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint.quantize import (kv_dequantize,
                                                      kv_quantize)
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.models.model import QuantKVCache
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import (ContinuousBatcher,
                                                  HostTier, PrefixCache,
                                                  pool_page_bytes)
from distributed_llms_tpu.runtime.faults import FaultPlane


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def tiny_gpt2():
    cfg = presets.get_preset("gpt2-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(1), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new):
    out = gen_lib.generate_tokens(
        params, cfg, jnp.asarray([ids], jnp.int32),
        jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
        max_new_tokens=n_new, eos_id=-1, pad_id=0,
    )
    return np.asarray(out)[0].tolist()


def _paged(cfg, params, **kw):
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("paged_pages", 9)
    return ContinuousBatcher(cfg, params, **kw)


def _counter(name):
    return METRICS.get_counter(name)


STORM = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]


def _run_storm(b, reqs=STORM):
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    b.assert_pool_consistent()
    return rids, res


# -- configuration contract -------------------------------------------------


def test_int8_requires_paged_pool(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, kv_bits=8)
    with pytest.raises(ValueError, match="kv_bits"):
        _paged(cfg, params, kv_bits=4)


def test_host_tier_requires_paged_pool(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          host_pages=8)
    with pytest.raises(ValueError, match="host_pages"):
        _paged(cfg, params, host_pages=-1)


def test_int8_pool_storage_and_capacity(tiny):
    """Pool storage is int8 + f32 scales; logical token capacity is
    unchanged (same page count), so capacity per POOL BYTE grows by the
    byte ratio — >= 1.8x at head_dim 64 (the acceptance floor)."""
    cfg, params = tiny
    b = _paged(cfg, params, kv_bits=8)
    assert isinstance(b.cache, QuantKVCache)
    assert b.cache.k.dtype == jnp.int8 and b.cache.v.dtype == jnp.int8
    assert b.cache.k_scale.dtype == jnp.float32
    b16 = _paged(cfg, params)
    assert b.capacity_tokens() == b16.capacity_tokens()
    ratio = (pool_page_bytes(cfg, 16, 16) / pool_page_bytes(cfg, 16, 8))
    assert ratio >= 1.8, f"int8 pages only {ratio:.2f}x denser"
    b.assert_pool_consistent()
    b16.assert_pool_consistent()


def test_kv_quantize_round_trip_is_stable():
    """Re-quantizing a dequantized parcel reproduces identical int8 data
    and scales — the property that keeps a kv-bits-8 handoff byte-stable
    (export dequantizes, import re-quantizes)."""
    x = jax.random.normal(jax.random.key(3), (4, 8, 2, 16), jnp.bfloat16)
    data, scale = kv_quantize(x)
    full = kv_dequantize(data, scale, jnp.bfloat16)
    data2, scale2 = kv_quantize(full)
    np.testing.assert_array_equal(np.asarray(data), np.asarray(data2))
    np.testing.assert_array_equal(np.asarray(scale), np.asarray(scale2))


def test_digests_salted_by_kv_bits(tiny):
    """Digest chains fold in the KV width: int8 pages can never alias
    bf16 pages — while sharing WITHIN a width stays content-addressed."""
    ids = list(range(48))
    d16 = PrefixCache.page_digests(ids, 16, 3)
    d8 = PrefixCache.page_digests(ids, 16, 3, kv_bits=8)
    assert d16 != d8 and all(a != b for a, b in zip(d16, d8))
    # Default-width digests are unchanged by the new parameter.
    assert d16 == PrefixCache.page_digests(ids, 16, 3, kv_bits=16)
    cfg, params = tiny
    b = _paged(cfg, params, paged_pages=17, prefix_cache=True, kv_bits=8)
    shared = list(range(40, 60)) + [3] * 5
    r1 = b.submit(shared + [9], max_new_tokens=8)
    b.run()
    r2 = b.submit(shared + [11], max_new_tokens=8)
    b.run()
    assert b.prefix_cached_tokens[r2] > 0, "int8 pages did not share"
    b.assert_pool_consistent()


# -- int8 serving quality ---------------------------------------------------


def _agreement(cfg, params, prompts, n_new=24):
    b16 = _paged(cfg, params, batch_slots=4, paged_pages=17)
    b8 = _paged(cfg, params, batch_slots=4, paged_pages=17, kv_bits=8)
    r16 = [b16.submit(p, max_new_tokens=n_new) for p in prompts]
    o16 = b16.run()
    r8 = [b8.submit(p, max_new_tokens=n_new) for p in prompts]
    o8 = b8.run()
    b16.assert_pool_consistent()
    b8.assert_pool_consistent()
    tot = sum(len(o16[r]) for r in r16)
    agree = sum(
        sum(i == j for i, j in zip(o16[a], o8[b]))
        for a, b in zip(r16, r8)
    )
    return agree / tot, [o8[r] for r in r8]


PROMPTS = [[7, 1, 9, 2], [4, 4, 4, 4], [9, 8, 7, 3], [11, 5],
           [100, 200, 50, 60, 70, 80, 90, 10], [3] * 12]


def test_int8_greedy_token_agreement_gpt2(tiny_gpt2):
    """gpt2 short runs: int8 pages agree with the bf16 reference at the
    pinned threshold (measured 1.0 at pinning time; floor 0.9)."""
    cfg, params = tiny_gpt2
    frac, _ = _agreement(cfg, params, PROMPTS)
    assert frac >= 0.9, f"gpt2 int8 agreement {frac:.3f} < 0.9"


def test_int8_greedy_token_agreement_and_determinism(tiny):
    """llama-tiny: agreement floor 0.7 (greedy divergence cascades after
    a first flipped token — measured 0.88 at pinning time), and two int8
    runs are byte-identical (quantization is deterministic)."""
    cfg, params = tiny
    frac, outs = _agreement(cfg, params, PROMPTS)
    assert frac >= 0.7, f"int8 agreement {frac:.3f} < 0.7"
    _, outs2 = _agreement(cfg, params, PROMPTS)
    assert outs == outs2, "int8 serving is not deterministic"


# -- swap-preemption (host tier) -------------------------------------------


def test_swap_preempt_byte_exact_vs_solo(tiny):
    """Overcommitted storm with the host tier armed: victims SWAP out
    instead of recomputing, and every stream equals its solo run."""
    cfg, params = tiny
    b = _paged(cfg, params, host_pages=16)
    out0 = _counter("batcher.kv_swaps.out")
    in0 = _counter("batcher.kv_swaps.in")
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n), f"rid {rid} diverged"
    assert _counter("batcher.kv_swaps.out") - out0 >= 1
    assert _counter("batcher.kv_swaps.in") - in0 >= 1
    assert b.preemptions >= 1
    assert sorted(b.free_pages) == list(range(1, 9))


def test_swap_restore_equals_recompute_and_streams_once(tiny):
    """The same storm with and without the host tier produces identical
    results (bf16 offload is lossless); streamed deliveries across a
    swap restore never re-deliver and fire done exactly once."""
    cfg, params = tiny
    swaps0 = _counter("batcher.kv_swaps.out")
    b_re = _paged(cfg, params)
    _, res_re = _run_storm(b_re)
    assert _counter("batcher.kv_swaps.out") == swaps0  # no tier, no swaps
    assert b_re.preemptions >= 1

    b_sw = _paged(cfg, params, host_pages=16)
    deliveries: dict[int, list[int]] = {}
    dones: dict[int, int] = {}

    def on_tokens(rid, toks, done, lps):
        deliveries.setdefault(rid, []).extend(toks)
        if done:
            dones[rid] = dones.get(rid, 0) + 1

    rids = [b_sw.submit(ids, max_new_tokens=n) for ids, n in STORM]
    res_sw = b_sw.run(on_tokens=on_tokens)
    b_sw.assert_pool_consistent()
    assert _counter("batcher.kv_swaps.out") > swaps0
    assert {r: res_sw[r] for r in rids} == {r: res_re[r] for r in rids}
    for rid in rids:
        assert deliveries[rid] == res_sw[rid], "stream diverged from result"
        assert dones[rid] == 1


def test_swap_falls_back_when_host_budget_dry(tiny):
    """A 1-page host tier cannot hold any victim: every preemption falls
    back to exact recompute and the fallback counter says so."""
    cfg, params = tiny
    fb0 = _counter("batcher.kv_swaps.fallback")
    in0 = _counter("batcher.kv_swaps.in")
    b = _paged(cfg, params, host_pages=1)
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n)
    assert b.preemptions >= 1
    assert _counter("batcher.kv_swaps.fallback") - fb0 >= 1
    assert _counter("batcher.kv_swaps.in") == in0


def test_int8_swap_preempt_byte_exact_vs_unpreempted_int8(tiny):
    """Under int8 pages the swap parcel carries the RAW quantized bytes:
    a preempted-and-restored stream is byte-identical to the int8 run
    that was never under pressure (stronger than recompute could be)."""
    cfg, params = tiny
    ref = _paged(cfg, params, batch_slots=3, paged_pages=17, kv_bits=8)
    rids_ref = [ref.submit(ids, max_new_tokens=n) for ids, n in STORM]
    res_ref = ref.run()

    b = _paged(cfg, params, kv_bits=8, host_pages=16)
    out0 = _counter("batcher.kv_swaps.out")
    rids, res = _run_storm(b)
    assert _counter("batcher.kv_swaps.out") - out0 >= 1
    for r, rr in zip(rids, rids_ref):
        assert res[r] == res_ref[rr], "int8 swap restore moved tokens"


def test_swapped_request_cancel_and_audit(tiny):
    """A swap parcel whose request is cancelled while queued is freed
    (the audit would otherwise catch the stranded handle); mid-flight the
    audit accounts the queued parcel."""
    cfg, params = tiny
    b = _paged(cfg, params, host_pages=16)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in STORM]
    # Admit everything, then preempt a resident row by hand.
    b._admit_pending()
    victim = next(i for i in range(b.b) if b.rows[i].rid is not None
                  and b.rows[i].pages)
    vrid = b.rows[victim].rid
    b._preempt_row(victim, "test")
    queued = [r for r in b.queue_snapshot() if r.rid == vrid]
    assert queued and queued[0].swap_handle is not None
    b.assert_pool_consistent()  # parcel owned by the queued request: clean
    assert b.cancel_row(vrid)
    assert b.host_tier.stats()["swap_parcels"] == 0
    b.assert_pool_consistent()
    res = b.run()
    for rid, (ids, n) in zip(rids, STORM):
        if rid != vrid:
            assert res[rid] == solo(cfg, params, ids, n)


def test_host_tier_audit_catches_stranded_handle(tiny):
    """The cross-tier audit fails on a parcel no queued request owns —
    the host-RAM analogue of a dangling refcount."""
    cfg, params = tiny
    b = _paged(cfg, params, host_pages=16)
    h = b.host_tier.park_swap((np.zeros((2, 2)),), 2)
    assert h is not None
    with pytest.raises(AssertionError, match="swap handles"):
        b.assert_pool_consistent()
    b.host_tier.drop_swap(h)
    b.assert_pool_consistent()


# -- prefix-cache spill tier ------------------------------------------------


SHARED = list(range(40, 60)) + [3] * 5  # 25 tokens -> 3 full pages of 8


def _spill_batcher(cfg, params, **kw):
    return _paged(cfg, params, batch_slots=2, page_size=8, paged_pages=17,
                  prefix_cache=True, **kw)


def _evict_cache(b, n=3):
    """Push unrelated long prompts through until the shared pages fall
    off the device LRU."""
    for i in range(n):
        b.submit([200 + i] * 30 + [i], max_new_tokens=20)
    b.run()


def test_host_spill_restore_byte_exact_vs_device_hit(tiny):
    """Warm cache -> eviction pressure -> re-hit: with the host tier the
    evicted run restores (counted) and the hit's stream is byte-exact vs
    a plain device hit; cached-token accounting matches too."""
    cfg, params = tiny
    # Reference: plain device hit, no eviction in between.
    ref = _spill_batcher(cfg, params)
    ref.submit(SHARED + [9, 9], max_new_tokens=12)
    ref.run()
    r_hit = ref.submit(SHARED + [9, 9], max_new_tokens=12)
    hit_tokens = ref.run()[r_hit]
    hit_cached = ref.prefix_cached_tokens[r_hit]
    assert hit_cached == 24  # 3 full pages of 8

    b = _spill_batcher(cfg, params, host_pages=32)
    b.submit(SHARED + [9, 9], max_new_tokens=12)
    b.run()
    sp0 = _counter("batcher.host_tier.spilled_pages")
    rs0 = _counter("batcher.host_tier.restored_pages")
    _evict_cache(b)
    assert _counter("batcher.host_tier.spilled_pages") - sp0 >= 1
    r2 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    out = b.run()[r2]
    assert _counter("batcher.host_tier.restored_pages") - rs0 >= 1
    assert out == hit_tokens, "spill-restored hit moved tokens"
    assert b.prefix_cached_tokens[r2] == hit_cached, (
        "restore did not recover the full cached run"
    )
    b.assert_pool_consistent()


def test_spill_restore_bridges_evicted_head(tiny):
    """LRU evicts a run's HEAD pages first: the tiered match restores the
    host-parked head and still reaches the device-resident tail — a
    device-only match would miss the whole run."""
    cfg, params = tiny
    b = _spill_batcher(cfg, params, host_pages=32)
    b.submit(SHARED + [9, 9], max_new_tokens=12)
    b.run()
    # One small alloc evicts exactly the oldest (head) cached page.
    _evict_cache(b, n=1)
    r2 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    b.run()
    assert b.prefix_cached_tokens[r2] == 24, (
        f"tiered match only found {b.prefix_cached_tokens[r2]} tokens"
    )
    b.assert_pool_consistent()


def test_spill_restore_composes_with_chunked_prefill(tiny):
    """A chunked (long-prompt) admission consults the host tier too: the
    restored run seeds the transient row and only the suffix chunks."""
    cfg, params = tiny
    ref = _spill_batcher(cfg, params, prefill_chunk=8)
    ref.submit(SHARED + [9, 9], max_new_tokens=12)
    ref.run()
    r_hit = ref.submit(SHARED + [9, 9], max_new_tokens=12)
    hit_tokens = ref.run()[r_hit]

    b = _spill_batcher(cfg, params, prefill_chunk=8, host_pages=32)
    b.submit(SHARED + [9, 9], max_new_tokens=12)
    b.run()
    _evict_cache(b)
    r2 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    out = b.run()[r2]
    assert out == hit_tokens
    assert b.prefix_cached_tokens[r2] == 24
    b.assert_pool_consistent()


# -- int8 x chunked prefill x preemption composition ------------------------


def test_int8_chunked_prefill_with_preemption_matches_monolithic(tiny):
    """The full composition: int8 pages + chunked prefill + host-tier
    swap under pool pressure — streams equal the int8 monolithic
    unpressured run (chunked prefill accumulates the same KV, the splice
    quantizes the same bytes, and swap restores them verbatim)."""
    cfg, params = tiny
    reqs = [(list(range(30)), 30), ([4, 4, 4, 4], 40), ([9, 8, 7, 3], 40)]
    ref = _paged(cfg, params, batch_slots=3, paged_pages=33, kv_bits=8)
    rr = [ref.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res_ref = ref.run()

    b = _paged(cfg, params, kv_bits=8, host_pages=24, prefill_chunk=8,
               paged_pages=9)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    b.assert_pool_consistent()
    assert b.preemptions >= 1
    for a, c in zip(rids, rr):
        assert res[a] == res_ref[c], "int8 x chunked x swap moved tokens"


# -- fault drills (one per new site) ----------------------------------------


def test_drill_swap_out_drop_falls_back_exact(tiny):
    cfg, params = tiny
    faults = FaultPlane()
    rule = faults.add("kv.swap_out", "drop", when="*")
    b = _paged(cfg, params, host_pages=16, faults=faults)
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n)
    assert rule.fired >= 1
    assert b.host_tier.stats()["swap_parcels"] == 0


def test_drill_swap_out_corrupt_detected_and_exact(tiny):
    """A parcel corrupted in host storage fails checksum verification at
    restore and the request recomputes — outputs stay exact."""
    cfg, params = tiny
    faults = FaultPlane()
    rule = faults.add("kv.swap_out", "corrupt", when="1")
    fb0 = _counter("batcher.kv_swaps.fallback")
    b = _paged(cfg, params, host_pages=16, faults=faults)
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n)
    assert rule.fired == 1
    assert _counter("batcher.kv_swaps.fallback") - fb0 >= 1


def test_drill_swap_in_drop_falls_back_exact(tiny):
    cfg, params = tiny
    faults = FaultPlane()
    rule = faults.add("kv.swap_in", "drop", when="1")
    b = _paged(cfg, params, host_pages=16, faults=faults)
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n)
    assert rule.fired == 1


def test_drill_swap_in_corrupt_detected_and_exact(tiny):
    """A parcel corrupted on the restore path fails checksum verification
    — the resume falls back to exact recompute instead of splicing bad
    KV, and the fallback is metered."""
    cfg, params = tiny
    faults = FaultPlane()
    rule = faults.add("kv.swap_in", "corrupt", when="1")
    fb0 = _counter("batcher.kv_swaps.fallback")
    b = _paged(cfg, params, host_pages=16, faults=faults)
    rids, res = _run_storm(b)
    for rid, (ids, n) in zip(rids, STORM):
        assert res[rid] == solo(cfg, params, ids, n)
    assert rule.fired == 1
    assert _counter("batcher.kv_swaps.fallback") - fb0 >= 1


def test_drill_spill_drop_degrades_to_cold_prefill(tiny):
    """kv.spill drop: nothing moves to the host — the later hit misses
    (cold prefill), tokens unchanged."""
    cfg, params = tiny
    faults = FaultPlane()
    faults.add("kv.spill", "drop", when="*", tag="out")
    sp0 = _counter("batcher.host_tier.spilled_pages")
    b = _spill_batcher(cfg, params, host_pages=32, faults=faults)
    r1 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    first = b.run()[r1]
    _evict_cache(b)
    r2 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    out = b.run()[r2]
    assert _counter("batcher.host_tier.spilled_pages") == sp0
    assert out == first  # cold prefill recomputes the same bytes (bf16)
    b.assert_pool_consistent()


def test_drill_spill_corrupt_detected(tiny):
    """Corrupted spilled pages are rejected at restore (checksum) — the
    hit degrades toward cold prefill instead of reading bad KV."""
    cfg, params = tiny
    faults = FaultPlane()
    rule = faults.add("kv.spill", "corrupt", when="*", tag="out")
    b = _spill_batcher(cfg, params, host_pages=32, faults=faults)
    r1 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    first = b.run()[r1]
    _evict_cache(b)
    r2 = b.submit(SHARED + [9, 9], max_new_tokens=12)
    out = b.run()[r2]
    assert rule.fired >= 1
    assert out == first
    b.assert_pool_consistent()


# -- server-level drive ------------------------------------------------------


def test_server_serves_int8_with_host_tier(tiny):
    """End to end through the HTTP gateway: an int8 + host-tier batcher
    behind InferenceServer serves an overcommitted burst — completions
    arrive, usage reports cached tokens on the shared-prefix repeat, and
    the pool audits clean across tiers."""
    import asyncio

    from distributed_llms_tpu.cluster.client import ServingClient

    cfg, params = tiny
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    tok = ByteTokenizer()

    def mk():
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=3, max_len=64, chunk_steps=4, page_size=16,
            paged_pages=9, prefix_cache=True, kv_bits=8, host_pages=16,
        )

    async def drive():
        srv = InferenceServer(mk(), model_name="tiered", host="127.0.0.1",
                              port=0)
        host, port = await srv.start()
        c = ServingClient(host, port, max_retries=0)
        outs = await asyncio.gather(*[
            c.completions({"prompt": f"tier burst {i}", "max_tokens": 24})
            for i in range(4)
        ])
        assert all(s == 200 for s, _ in outs), outs
        # Shared-prefix repeat: int8 pages share content-addressed.
        s1, o1 = await c.completions(
            {"prompt": "shared prefix " * 4, "max_tokens": 4})
        s2, o2 = await c.completions(
            {"prompt": "shared prefix " * 4, "max_tokens": 4})
        assert s1 == 200 and s2 == 200
        cached = o2["usage"]["prompt_tokens_details"]["cached_tokens"]
        assert cached > 0
        srv.batcher.assert_pool_consistent()
        await srv.stop()

    asyncio.run(drive())
