"""Grammar-constrained structured output (runtime/constrain.py + the
batcher/server mask leg).

Core invariants:
- every constrained completion PARSES: regex-constrained outputs
  full-match their pattern and schema-constrained outputs json.loads +
  validate, at temperature 0 AND temperature > 0;
- free rows in a mixed batch are byte-identical (tokens AND logprobs) to
  a batch with no constrained neighbors — the mask path adds exactly 0.0
  to their logits;
- composition: constrained x {prefix cache, chunked prefill,
  preempt+swap-restore, overlap on/off, int8 KV pages} stays byte-stable;
- logit_bias / banned_tokens ride the SAME mask mechanism (no second
  path) with the same isolation guarantees;
- serving: malformed schemas answer a structured 400 BEFORE admission,
  response_format round-trips end to end over HTTP, and "n": K choices
  share the prompt's KV pages through the refcounted pool.
"""

import asyncio
import json
import re

import jax
import numpy as np
import pytest

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import constrain as C
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

TOK = ByteTokenizer()

TOOL_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"enum": ["get_weather", "get_time"]},
        "args": {
            "type": "object",
            "properties": {
                "city": {"type": "string", "maxLength": 8},
                "celsius": {"type": "boolean"},
            },
            "required": ["city", "celsius"],
        },
    },
    "required": ["name", "args"],
}
RF_SCHEMA = {"type": "json_schema", "json_schema": {"schema": TOOL_SCHEMA}}


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def make(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("batch_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("tokenizer", TOK)
    kw.setdefault("eos_id", TOK.eos_id)
    kw.setdefault("pad_id", TOK.pad_id)
    return ContinuousBatcher(cfg, params, **kw)


def _paged(tiny, **kw):
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 16)
    kw.setdefault("paged_pages", 9)
    return make(tiny, **kw)


def text_of(b, out):
    """Decode a result, dropping the terminating EOS if present."""
    if out and out[-1] == TOK.eos_id:
        out = out[:-1]
    return TOK.decode(out)


# -- compiler unit tests ----------------------------------------------------


def test_regex_char_dfa_semantics():
    dfa = C.regex_to_char_dfa(r"(?:ab|a[0-9]{2,3})c?")
    for s, want in [("ab", True), ("a12", True), ("a123c", True),
                    ("abc", True), ("a1", False), ("a1234", False),
                    ("", False), ("b", False)]:
        assert C.char_dfa_matches(dfa, s.encode()) == want, s
    # an empty language fails at compile, not at serve time
    with pytest.raises(C.ConstraintError, match="matches nothing"):
        C.regex_to_char_dfa(r"a[^\x00-\xff]b")
    with pytest.raises(C.ConstraintError, match="repetition"):
        C.regex_to_char_dfa("a{3,2}")


def test_schema_to_regex_agrees_with_python_re():
    rx = C.schema_to_regex(TOOL_SCHEMA)
    good = {"name": "get_time", "args": {"city": "oslo", "celsius": True}}
    bad = {"name": "nope", "args": {"city": "oslo", "celsius": True}}
    s_good = json.dumps(good, separators=(",", ":"))
    s_bad = json.dumps(bad, separators=(",", ":"))
    assert re.fullmatch(rx, s_good)
    assert not re.fullmatch(rx, s_bad)
    assert C.validates(TOOL_SCHEMA, good)
    assert not C.validates(TOOL_SCHEMA, bad)
    # arrays + numbers + null
    rx2 = C.schema_to_regex({"type": "array", "items": {"type": "number"},
                             "maxItems": 3})
    assert re.fullmatch(rx2, "[1.5,0,2]")
    assert not re.fullmatch(rx2, "[1,2,3,4]")
    assert re.fullmatch(C.schema_to_regex({"type": "null"}), "null")


def test_schema_subset_rejections():
    with pytest.raises(C.ConstraintError, match="required"):
        C.schema_to_regex({"type": "object",
                           "properties": {"a": {"type": "null"}},
                           "required": []})
    with pytest.raises(C.ConstraintError, match="unsupported schema type"):
        C.schema_to_regex({"type": "frobnicate"})
    with pytest.raises(C.ConstraintError, match="unsupported schema keyword"):
        C.schema_to_regex({"anyOf": [{"type": "null"}]})
    with pytest.raises(C.ConstraintError, match="enum"):
        C.schema_to_regex({"enum": []})
    with pytest.raises(C.ConstraintError, match="unsupported escape"):
        C.regex_to_char_dfa(r"\ba\b")
    # The keyword set is an ALLOWLIST: an unenforced constraint must 400,
    # never be silently ignored (the output would violate the schema).
    with pytest.raises(C.ConstraintError, match="unsupported schema keyword"):
        C.schema_to_regex({"type": "integer", "maximum": 10})
    with pytest.raises(C.ConstraintError, match="minimum"):
        C.schema_to_regex({"type": "integer", "minimum": 5})
    with pytest.raises(C.ConstraintError, match="additionalProperties"):
        C.schema_to_regex({"type": "object", "properties": {},
                           "required": [], "additionalProperties": True})
    # ... while enforceable/annotation keys pass.
    assert C.schema_to_regex({"type": "integer", "minimum": 0}) \
        == "(?:0|[1-9][0-9]{0,14})"
    assert "\\{\\}" == C.schema_to_regex(
        {"type": "object", "properties": {}, "required": [],
         "additionalProperties": False, "title": "t"})


def test_string_length_bounds_are_utf8_bytes():
    schema = {"type": "string", "minLength": 4, "maxLength": 4}
    rx = C.schema_to_regex(schema)
    # The grammar counts BYTES; validates() must use the same measure, or
    # a grammar-legal output would fail its own schema.
    assert re.fullmatch(rx, '"abcd"')
    assert C.validates(schema, "abcd")
    assert C.validates(schema, "éé")       # 2 chars, 4 UTF-8 bytes
    assert not C.validates(schema, "abc")  # 3 bytes


def test_compile_cache_hit_path():
    C.clear_cache()
    rf = {"type": "regex", "regex": "[0-9]{1,4}"}
    a = C.compile_request(rf, tokenizer=TOK, vocab_size=512,
                          eos_id=TOK.eos_id)
    st = C.cache_stats()
    b = C.compile_request(rf, tokenizer=TOK, vocab_size=512,
                          eos_id=TOK.eos_id)
    st2 = C.cache_stats()
    assert b is a  # the LRU returned the SAME automaton object
    assert st2["hits"] == st["hits"] + 1
    assert st2["misses"] == st["misses"]


# -- constrained generation: parse guarantees -------------------------------


def test_constrained_outputs_match_regex_greedy_and_sampled(tiny):
    pat = "[0-9]{2,6}"
    rf = {"type": "regex", "regex": pat}
    b = make(tiny, seed=3)
    rids = [
        b.submit([7, 1, 9], max_new_tokens=12, response_format=rf),
        b.submit([4, 4], max_new_tokens=12, temperature=1.5,
                 response_format=rf),
        b.submit([9, 8], max_new_tokens=12, temperature=0.8, top_p=0.95,
                 response_format=rf),
    ]
    res = b.run()
    rows0 = METRICS.get_counter("batcher.constrain.rows")
    assert rows0 >= 3
    for r in rids:
        assert res[r][-1] == TOK.eos_id, res[r]
        assert re.fullmatch(pat, text_of(b, res[r])), res[r]


def test_constrained_json_schema_parses_and_validates(tiny):
    b = make(tiny, seed=11)
    rids = [
        b.submit([60 + i, 2, 3], max_new_tokens=70,
                 temperature=(0.0 if i % 2 == 0 else 1.1),
                 response_format=RF_SCHEMA)
        for i in range(4)
    ]
    res = b.run()
    for r in rids:
        obj = json.loads(text_of(b, res[r]))
        assert C.validates(TOOL_SCHEMA, obj), obj


def test_free_rows_byte_identical_next_to_constrained(tiny):
    """The SAME batch (same submission order, prompts, budgets, seed)
    with the third request constrained vs free: the two free rows —
    one greedy, one sampled — must be byte-identical in tokens AND
    logprobs (their mask row is exactly zero, and the rng stream is
    consumption-aligned: one split per admission, one per chunk)."""

    def drive(constrained):
        b = make(tiny, seed=5)
        rids = [
            b.submit([7, 1, 9], max_new_tokens=10),
            b.submit([4, 4, 4], max_new_tokens=8, temperature=1.3),
        ]
        kw = ({"response_format": {"type": "regex",
                                   "regex": "[a-z]{4,12}"}}
              if constrained else {})
        b.submit([2, 2], max_new_tokens=16, **kw)
        res = b.run()
        return ([res[r] for r in rids],
                [b.result_logprobs[r] for r in rids])

    toks_free, lps_free = drive(False)
    toks_mixed, lps_mixed = drive(True)
    assert toks_mixed == toks_free
    # Bit-identity, not approximate: the free rows' logits never saw the
    # mask (their bias row adds exactly 0.0).
    assert lps_mixed == lps_free


# -- ride-alongs: logit_bias / banned_tokens --------------------------------


def test_logit_bias_and_banned_tokens_share_the_mask_path(tiny):
    b0 = make(tiny)
    r0 = b0.submit([7, 1, 9], max_new_tokens=8)
    free = b0.run()[r0]

    # +100 bias dominates every tiny-model logit: greedy emits the token.
    b = make(tiny)
    r = b.submit([7, 1, 9], max_new_tokens=4, logit_bias={"65": 100.0})
    assert b.run()[r][:1] == [65]

    # Banning greedy's first choice changes the path; the banned id never
    # appears; an unbiased neighbor in the same batch stays exact.
    b2 = make(tiny)
    rb = b2.submit([7, 1, 9], max_new_tokens=8, banned_tokens=[free[0]])
    rn = b2.submit([7, 1, 9], max_new_tokens=8)
    res = b2.run()
    assert free[0] not in res[rb]
    assert res[rb] != free
    assert res[rn] == free

    # Validation: range and shape errors raise BEFORE anything queues.
    with pytest.raises(ValueError, match="logit_bias"):
        b2.submit([1], max_new_tokens=2, logit_bias={"65": 101.0})
    with pytest.raises(ValueError, match="logit_bias"):
        b2.submit([1], max_new_tokens=2, logit_bias={"x": 1.0})
    with pytest.raises(ValueError, match="banned"):
        b2.submit([1], max_new_tokens=2, banned_tokens=[512])
    with pytest.raises(ValueError, match="banned"):
        b2.submit([1], max_new_tokens=2, banned_tokens=[])


def test_speculative_rejects_constraints(tiny):
    cfg, params = tiny
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=4,
        tokenizer=TOK, eos_id=TOK.eos_id, pad_id=TOK.pad_id,
        draft_params=params, draft_cfg=cfg, spec_k=2,
    )
    for kw in (dict(response_format={"type": "regex", "regex": "[0-9]+"}),
               dict(logit_bias={"5": 1.0}),
               dict(banned_tokens=[5])):
        with pytest.raises(ValueError, match="speculative"):
            b.submit([1, 2, 3], max_new_tokens=4, **kw)


# -- composition ------------------------------------------------------------


def test_constrained_overlap_on_off_byte_stable(tiny):
    pat = {"type": "regex", "regex": "[0-9]{2,20}"}

    def drive(overlap):
        b = make(tiny, seed=9, overlap=overlap)
        rc = b.submit([7, 1], max_new_tokens=24, response_format=pat)
        rs = b.submit([4, 4], max_new_tokens=24, temperature=1.2,
                      response_format=pat)
        rf = b.submit([9, 9], max_new_tokens=10)
        res = b.run()
        return res[rc], res[rs], res[rf]

    assert drive(True) == drive(False)


def test_constrained_chunked_prefill_matches_monolithic(tiny):
    prompt = list(range(40, 58))  # long enough to chunk
    rf = {"type": "regex", "regex": "[0-9]{2,10}"}
    mono = make(tiny)
    rm = mono.submit(prompt, max_new_tokens=14, response_format=rf)
    want = mono.run()[rm]
    chunked = make(tiny, prefill_chunk=5)
    rc = chunked.submit(prompt, max_new_tokens=14, response_format=rf)
    assert chunked.run()[rc] == want
    assert re.fullmatch("[0-9]{2,10}", text_of(mono, want))


def test_constrained_prefix_cache_composes(tiny):
    # 32-token shared prompt = 2 full pages; the second constrained
    # request admits off the cached run and must produce the same bytes.
    prompt = [5] * 33
    rf = {"type": "regex", "regex": "[0-9]{2,10}"}
    b = _paged(tiny, prefix_cache=True)
    r1 = b.submit(prompt, max_new_tokens=10, response_format=rf)
    res1 = b.run()
    r2 = b.submit(prompt, max_new_tokens=10, response_format=rf)
    res2 = b.run()
    assert b.prefix_cached_tokens[r2] >= 32
    assert res2[r2] == res1[r1]
    assert re.fullmatch("[0-9]{2,10}", text_of(b, res2[r2]))
    b.assert_pool_consistent()


def test_constrained_preempt_swap_restore_byte_exact(tiny):
    # Pool pressure (3 rows x 44-token budgets against 9 pages) forces
    # swap-preemption; the roomy pool serves the byte-exact reference.
    # The 40-digit floor keeps every row decoding long enough to be a
    # victim (no early EOS), and the automaton state must survive the
    # round trip (restore replays the emitted prefix through the DFA).
    rf = {"type": "regex", "regex": "[0-9]{40,60}"}
    reqs = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]

    def drive(pages, host_pages):
        b = _paged(tiny, paged_pages=pages, host_pages=host_pages)
        rids = [b.submit(ids, max_new_tokens=n, response_format=rf)
                for ids, n in reqs]
        res = b.run()
        b.assert_pool_consistent()
        return b, [res[r] for r in rids]

    ref_b, want = drive(16, 0)
    assert ref_b.preemptions == 0
    swaps0 = METRICS.get_counter("batcher.kv_swaps.out")
    got_b, got = drive(9, 16)
    assert got_b.preemptions >= 1  # pressure actually fired
    assert METRICS.get_counter("batcher.kv_swaps.out") > swaps0
    assert got == want  # byte-exact across preempt + swap restore
    for out in got:
        assert re.fullmatch("[0-9]{40,60}", text_of(got_b, out))


def test_constrained_int8_kv_valid_and_deterministic(tiny):
    rf = {"type": "regex", "regex": "[0-9]{2,12}"}

    def drive():
        b = _paged(tiny, kv_bits=8)
        r = b.submit([7, 1, 9], max_new_tokens=14, response_format=rf)
        out = b.run()[r]
        b.assert_pool_consistent()
        return b, out

    b1, o1 = drive()
    _, o2 = drive()
    assert o1 == o2  # int8 pages: deterministic
    assert re.fullmatch("[0-9]{2,12}", text_of(b1, o1))


# -- serving: HTTP surface --------------------------------------------------


async def _request(host, port, method, path, body=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    data = await reader.read()
    writer.close()
    return status, data


def test_server_constrained_end_to_end(tiny):
    async def drive():
        srv = InferenceServer(_paged(tiny, prefix_cache=True),
                              host="127.0.0.1", port=0)
        host, port = await srv.start()
        try:
            # Malformed schema: structured 400 BEFORE admission — no
            # mailbox, no queue entry, and the engine still serves.
            code, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 4, "response_format":
                 {"type": "json_schema", "json_schema": {"schema": {
                     "type": "object",
                     "properties": {"a": {"type": "null"}},
                     "required": []}}}},
            )
            assert code == 400
            assert json.loads(raw)["error"]["type"] == \
                "invalid_request_error"
            assert srv._inflight() == 0 and not srv.batcher.has_queued()
            code, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 4,
                 "response_format": {"type": "regex", "regex": "["}},
            )
            assert code == 400

            # A valid schema-constrained completion round-trips: the text
            # parses and validates.  (A compact schema — the paged test
            # engine's 64-token rows bound prompt + completion.)
            small = {"type": "object",
                     "properties": {"name": {"enum": ["get_weather",
                                                      "get_time"]}},
                     "required": ["name"]}
            code, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "tool:", "max_tokens": 30,
                 "response_format":
                     {"type": "json_schema",
                      "json_schema": {"schema": small}}},
            )
            assert code == 200, raw
            body = json.loads(raw)
            obj = json.loads(body["choices"][0]["text"])
            assert C.validates(small, obj), obj

            # n-best: K choices admit as K rows sharing the prompt's KV
            # pages via the refcounted pool (prefix-cache retain path);
            # greedy makes every choice identical, cached_tokens reports
            # the reuse, and the pool audits clean afterwards.
            prompt = "n" * 40  # 41 ids with BOS -> 2 full 16-token pages
            code, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": prompt, "max_tokens": 6, "n": 3},
            )
            assert code == 200, raw
            body = json.loads(raw)
            texts = [c["text"] for c in body["choices"]]
            assert len(texts) == 3 and len(set(texts)) == 1
            assert body["usage"]["completion_tokens"] > 0
            assert body["usage"]["prompt_tokens_details"][
                "cached_tokens"] >= 32
            srv.batcher.assert_pool_consistent()
        finally:
            await srv.stop()

    asyncio.run(drive())


def test_server_constrained_kill_switch(tiny):
    async def drive():
        srv = InferenceServer(make(tiny), host="127.0.0.1", port=0,
                              constrained=False)
        host, port = await srv.start()
        try:
            code, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 4,
                 "response_format": {"type": "regex", "regex": "[0-9]+"}},
            )
            assert code == 400
            assert b"disabled" in raw
            # logit_bias rides the same gate
            code, _ = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 4, "logit_bias": {"5": 1}},
            )
            assert code == 400
        finally:
            await srv.stop()

    asyncio.run(drive())
