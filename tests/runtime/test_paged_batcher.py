"""Paged KV cache for the continuous batcher (vLLM-style block tables,
TPU-native static shapes — ops/decode_attn.paged_decode_attention).

Invariants pinned here:
- exact tokens: paged serving equals solo generate_tokens per request;
- memory: the pool is SMALLER than batch_slots * max_len yet serves the
  same workload (rows allocate only prompt+budget pages);
- backpressure: a dry pool queues requests instead of overcommitting, and
  freed pages are reused by later requests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new, eos_id=-1):
    arr = jnp.asarray([ids], jnp.int32)
    lens = jnp.asarray([len(ids)], jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, arr, lens, jax.random.key(9), max_new_tokens=n_new,
        eos_id=eos_id, pad_id=0,
    )
    toks = np.asarray(out)[0].tolist()
    if eos_id >= 0 and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
    return toks


def _paged(cfg, params, **kw):
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("paged_pages", 9)  # 8 usable + scratch — vs 3*64/16 = 12
    return ContinuousBatcher(cfg, params, **kw)


def test_paged_mixed_budgets_match_solo(tiny):
    """More requests than slots, mixed lengths/budgets, pool smaller than
    slots*max_len — every request equals its solo run."""
    cfg, params = tiny
    reqs = [
        ([7, 1, 9], 6),
        ([4, 4, 4, 4, 4, 4], 12),
        ([100, 3, 5, 2], 3),
        ([9, 8, 7, 6, 5], 9),
        ([11, 12], 15),
        ([42], 8),
    ]
    b = _paged(cfg, params)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"request {rid} diverged"
    # Every page returned to the pool at the end, and the allocator's
    # partition/refcount invariants audit clean (PagePool.assert_consistent
    # — the recovery-path leak detector, also run after every supervisor
    # engine restart).
    assert sorted(b.free_pages) == list(range(1, 9))
    b.assert_pool_consistent()


def test_paged_backpressure_and_reuse(tiny):
    """A pool too small for all requests at once serves them anyway by
    queueing admissions until pages free up."""
    cfg, params = tiny
    # Each request needs ceil((2+14)/16)=1 page; pool has 2 usable pages,
    # so at most 2 of the 5 requests can be in flight.
    b = _paged(cfg, params, paged_pages=3, batch_slots=3, max_len=32,
               page_size=16)
    reqs = [([5, i], 14) for i in range(5)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"request {rid} diverged"
    assert sorted(b.free_pages) == [1, 2]
    b.assert_pool_consistent()


def test_paged_prefix_caching(tiny):
    cfg, params = tiny
    b = _paged(cfg, params)
    prefix = [3, 1, 4, 1, 5]
    b.register_prefix("sys", prefix)
    suffix = [9, 2, 6]
    rid = b.submit(suffix, max_new_tokens=8, prefix="sys")
    res = b.run()
    assert res[rid] == solo(cfg, params, prefix + suffix, 8)


def test_paged_kernel_program_runs(tiny, monkeypatch):
    """With a kernel-tileable model (head_dim 128) the paged Pallas program
    (not the gather fallback) serves decode — spy on pallas_call."""
    from distributed_llms_tpu.ops import decode_attn

    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    calls = []
    orig = decode_attn.pl.pallas_call
    monkeypatch.setattr(
        decode_attn.pl, "pallas_call",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw),
    )
    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, num_heads=2,
        num_kv_heads=2,
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=4,
        paged_pages=9, page_size=16,
    )
    reqs = [([7, 1, 9], 6), ([4, 4], 9)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    assert calls, "paged kernel did not run"
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n)


def test_runtime_config_knobs_reach_engine_batcher(tiny):
    """RuntimeConfig.paged_pages/page_size flow through
    engine.continuous_batcher (the path the cluster worker uses); a mesh
    whose KV heads cannot shard degrades (config-inherited) or rejects
    (explicit), while a divisible mesh serves paged natively."""
    from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg, params = tiny
    rt = RuntimeConfig(max_seq_len=64, paged_pages=9, page_size=16)
    eng = InferenceEngine(cfg, rt, params)
    b = eng.continuous_batcher(batch_slots=2)
    assert b.paged and b.page_size == 16 and len(b.free_pages) == 8
    rid = b.submit([5, 6, 7], max_new_tokens=4)
    assert b.run()[rid] == solo(cfg, params, [5, 6, 7], 4)
    # paged_pages=0 explicitly opts back into contiguous.
    assert not eng.continuous_batcher(batch_slots=2, paged_pages=0).paged

    # llama-tiny has 2 KV heads: model=4 cannot shard the pool.
    pm = make_parallel_model(cfg, MeshConfig(data=2, model=4))
    mesh_eng = InferenceEngine(cfg, rt, params, parallel=pm)
    # Config-INHERITED paged on a NON-DIVISIBLE mesh degrades to
    # contiguous (a shared cluster config must not error mesh workers'
    # requests)...
    assert not mesh_eng.continuous_batcher(batch_slots=2).paged
    # ...but an EXPLICIT request on that mesh raises.
    with pytest.raises(ValueError, match="does not divide"):
        mesh_eng.continuous_batcher(paged_pages=9)
    # A DIVISIBLE mesh serves paged natively (mesh-native paged serving —
    # pool sharded on KV heads; byte-exactness pinned in
    # tests/runtime/test_mesh_paged.py).
    pm2 = make_parallel_model(cfg, MeshConfig(data=4, model=2))
    mesh_eng2 = InferenceEngine(cfg, rt, params, parallel=pm2)
    b2 = mesh_eng2.continuous_batcher(batch_slots=4)
    assert b2.paged and b2.pm is not None


def test_paged_batcher_over_quantized_weights(monkeypatch):
    """Weight-only quantized serving composes with PAGED batching (the
    contiguous leg is pinned by test_batcher.py): int8-resident blocks flow
    through the paged admission prefill and decode chunks into the fused
    dequant-matmul PROGRAM — a kernel-tileable config (hidden 256) plus a
    spy on _quant_matmul_2d proves the kernel (not the dequant fallback)
    ran — and tokens equal the quantized solo decode."""
    from distributed_llms_tpu.checkpoint import quantize as quant_lib
    from distributed_llms_tpu.ops import quant_matmul as qm

    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    calls = []
    orig = qm._quant_matmul_2d
    monkeypatch.setattr(
        qm, "_quant_matmul_2d",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw),
    )
    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, intermediate_size=256,
        num_heads=2, num_kv_heads=2,
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    qparams = {
        **params, "blocks": quant_lib.quantize_tree(params["blocks"], bits=8)
    }
    b = ContinuousBatcher(
        cfg, qparams, batch_slots=2, max_len=64, chunk_steps=4,
        paged_pages=9, page_size=16,
    )
    reqs = [([7, 1, 9], 6), ([4, 4, 4, 4], 9)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    assert calls, "fused dequant-matmul program did not run"
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, qparams, ids, n), f"req {rid} diverged"


def test_paged_rejects_bad_config(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="multiple of page_size"):
        ContinuousBatcher(cfg, params, max_len=60, paged_pages=8, page_size=16)
    with pytest.raises(ValueError, match="full-depth row"):
        ContinuousBatcher(cfg, params, max_len=64, paged_pages=3, page_size=16)
