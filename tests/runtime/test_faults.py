"""Deterministic fault injection (runtime/faults.py) and the serving
supervisor built on it (runtime/server.py).

The acceptance contract pinned here is the crash-only one: with N in-flight
requests and an injected decode-step fault, the engine restarts
automatically, every zero-streamed request completes with temp-0 tokens
IDENTICAL to an uninjected run, partially-streamed requests receive a
structured error, the page pool audits clean afterward, and
``server_engine_restarts`` increments exactly once.  Plus: per-request
deadlines (finish_reason "timeout", rows verifiably freed) and the engine
watchdog flipping /healthz.
"""

import asyncio
import json

import jax
import pytest

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import (
    FaultPlane, FaultRule, InjectedFault,
)
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def make_batcher(tiny, faults=None, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("paged_pages", 13)
    kw.setdefault("page_size", 16)
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        faults=faults, **kw
    )


def expected_text(tiny, prompt: str, n_new: int) -> str:
    b = make_batcher(tiny)
    rid = b.submit(prompt, max_new_tokens=n_new)
    return b.tokenizer.decode(b.run()[rid])


# -- spec grammar -----------------------------------------------------------


def test_parse_grammar():
    plane = FaultPlane.parse(
        "batcher.decode:raise@3,proto.send/HEARTBEAT:drop@2+,"
        "batcher.decode:stall@1:0.5,proto.recv:close@*"
    )
    assert [r.action for r in plane.rules] == ["raise", "drop", "stall", "close"]
    r0, r1, r2, r3 = plane.rules
    assert (r0.first, r0.last, r0.tag) == (3, 3, None)
    assert (r1.first, r1.last, r1.tag) == (2, None, "HEARTBEAT")
    assert (r2.arg, r2.first) == (0.5, 1)
    assert (r3.first, r3.last) == (1, None)
    # Round-trips through describe() -> parse().
    again = FaultPlane.parse(plane.describe())
    assert [r.describe() for r in again.rules] == \
        [r.describe() for r in plane.rules]
    assert FaultPlane.parse(None).rules == []
    assert FaultPlane.parse(" ").rules == []


def test_parse_rejects_malformed():
    for bad in ("decode", "decode:explode", "decode:raise@0",
                "decode:stall@1", ":raise", "decode:delay@2"):
        with pytest.raises(ValueError):
            FaultPlane.parse(bad)


def test_fire_windows_and_tags():
    plane = FaultPlane.parse("s:drop@2,s/T:drop@1+")
    # Untagged hits: only the windowed untagged rule counts them.
    assert plane.fire("s") is None          # hit 1: not due
    assert plane.fire("s").action == "drop"  # hit 2: fires
    assert plane.fire("s") is None          # hit 3: window passed
    # Tagged hits match BOTH rules; the first due rule wins.
    assert plane.fire("s", tag="T").action == "drop"
    assert plane.fire("s", tag="X") is None  # tag mismatch for rule 2
    assert plane.rules[1].fired == 1
    # add() arms mid-run.
    rule = plane.add("s", "drop", when="*")
    assert plane.fire("s").action == "drop"
    assert rule.fired == 1


def test_raise_and_stall_applied_by_fire():
    import time

    plane = FaultPlane.parse("a:raise@1,b:stall@1:0.05")
    with pytest.raises(InjectedFault, match="injected fault at a"):
        plane.fire("a")
    t0 = time.perf_counter()
    assert plane.fire("b").action == "stall"
    assert time.perf_counter() - t0 >= 0.05


# -- batcher-level injection ------------------------------------------------


def test_decode_raise_propagates_and_respawn_is_exact(tiny):
    want = expected_text(tiny, "hello", 8)
    plane = FaultPlane.parse("batcher.decode:raise@1")
    b = make_batcher(tiny, faults=plane)
    b.submit("hello", max_new_tokens=8)
    with pytest.raises(InjectedFault):
        b.run()
    # The crash-recovery primitive: a respawn rebuilds pool + caches fresh
    # and (the rule having fired) decodes the same request exactly.
    b2 = b.respawn()
    b2._next_rid = b._next_rid
    rid = b2.submit("hello", max_new_tokens=8)
    assert b2.tokenizer.decode(b2.run()[rid]) == want
    b2.assert_pool_consistent()
    assert plane.rules[0].fired == 1  # shared plane: fired stays fired


def test_admit_raise_propagates_and_respawn_is_exact(tiny):
    """A crash inside the admission round (batcher.admit) propagates out
    of run(); the respawned engine admits and decodes the same request
    exactly — the admission leg of the crash-recovery contract."""
    want = expected_text(tiny, "hello", 8)
    plane = FaultPlane.parse("batcher.admit:raise@1")
    b = make_batcher(tiny, faults=plane)
    b.submit("hello", max_new_tokens=8)
    with pytest.raises(InjectedFault):
        b.run()
    b2 = b.respawn()
    b2._next_rid = b._next_rid
    rid = b2.submit("hello", max_new_tokens=8)
    assert b2.tokenizer.decode(b2.run()[rid]) == want
    b2.assert_pool_consistent()
    assert plane.rules[0].fired == 1


def test_page_alloc_exhaust_backpressures_then_serves(tiny):
    """An injected dry pool takes the real back-pressure path (requeue,
    FIFO preserved) and the request completes exactly once the rule's
    window passes."""
    want = expected_text(tiny, "pool", 6)
    plane = FaultPlane.parse("batcher.page_alloc:exhaust@1")
    b = make_batcher(tiny, faults=plane)
    rid = b.submit("pool", max_new_tokens=6)
    res = b.run()
    assert b.tokenizer.decode(res[rid]) == want
    assert plane.rules[0].fired == 1
    b.assert_pool_consistent()


def test_pool_audit_catches_leaks(tiny):
    b = make_batcher(tiny)
    rid = b.submit("audit me", max_new_tokens=4)
    b.run()
    b.assert_pool_consistent()
    # Sabotage: a dangling refcount (the recovery-path leak class) and a
    # page missing from every partition must both fail the audit.
    page = b.free_pages.pop()
    with pytest.raises(AssertionError, match="leaked"):
        b.assert_pool_consistent()
    b.pool.page_refs[page] = 1
    with pytest.raises(AssertionError, match="diverge"):
        b.assert_pool_consistent()
    del b.pool.page_refs[page]
    b.free_pages.append(page)
    b.assert_pool_consistent()
    assert rid in b.results


# -- the serving supervisor -------------------------------------------------


async def _request(host, port, method, path, body=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    data = await reader.read()
    writer.close()
    return status, data


def run_with_server(batcher, fn, **srv_kw):
    async def driver():
        srv = InferenceServer(batcher, model_name="tiny", host="127.0.0.1",
                              port=0, **srv_kw)
        host, port = await srv.start()
        try:
            return await asyncio.wait_for(fn(host, port, srv), timeout=600)
        finally:
            await srv.stop()

    return asyncio.run(driver())


def test_supervisor_restart_retries_and_fails_structured(tiny):
    """THE crash acceptance test (see module docstring)."""
    prompts = ["alpha", "bravo!", "charlie?", "delta d"]
    wants = {p: expected_text(tiny, p, 8) for p in prompts}
    # batch_slots=2: two requests admit (and stream their admission token)
    # before the first decode chunk crashes; the other two sit queued with
    # zero streamed tokens.
    plane = FaultPlane.parse("batcher.decode:raise@1")
    restarts0 = METRICS.get_counter("server.engine_restarts")
    retried0 = METRICS.get_counter("server.requests_retried")

    async def fn(host, port, srv):
        outs = await asyncio.gather(*[
            _request(host, port, "POST", "/v1/completions",
                     {"prompt": p, "max_tokens": 8})
            for p in prompts
        ])
        completed, errored = [], []
        for (status, raw), p in zip(outs, prompts):
            body = json.loads(raw)
            if status == 200:
                # Zero-streamed at crash time: re-admitted, temp-0 tokens
                # identical to the uninjected run.
                assert body["choices"][0]["text"] == wants[p], p
                completed.append(p)
            else:
                # Partially streamed: structured engine error.
                assert status == 500
                assert body["error"]["type"] == "engine_error", body
                assert "restarted" in body["error"]["message"]
                errored.append(p)
        assert len(completed) == 2 and len(errored) == 2, (completed, errored)
        # Exactly one restart; both retried requests counted.
        assert METRICS.get_counter("server.engine_restarts") - restarts0 == 1
        assert METRICS.get_counter("server.requests_retried") - retried0 == 2
        # The fresh pool audits clean once everything drained.
        for _ in range(100):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        srv.batcher.assert_pool_consistent()
        # /healthz reports the restart and a healthy engine.
        status, raw = await _request(host, port, "GET", "/healthz")
        health = json.loads(raw)
        assert status == 200 and health["engine_restarts"] == 1

    run_with_server(make_batcher(tiny, faults=plane), fn)


def test_retry_budget_exhausts_to_structured_error(tiny):
    """A crash on EVERY chunk re-admits only max_request_retries times,
    then fails the request with the structured restart error instead of
    looping forever."""
    plane = FaultPlane.parse("batcher.decode:raise@1+")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "doomed", "max_tokens": 8},
        )
        # The request streamed its admission token before each crash, so
        # the FIRST restart already fails it partially-streamed — bounded
        # either way, never an infinite supervisor loop.
        assert status == 500
        assert json.loads(raw)["error"]["type"] == "engine_error"
        assert srv._restarts >= 1

    run_with_server(make_batcher(tiny, faults=plane), fn,
                    max_request_retries=1)


def test_request_timeout_returns_partial_and_frees_row(tiny):
    """Deadline acceptance: timeout_s expires mid-generation ->
    finish_reason "timeout" with the tokens produced so far, and the row's
    pages are verifiably freed afterward."""
    plane = FaultPlane.parse("batcher.decode:stall@1+:0.1")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "slow", "max_tokens": 64, "timeout_s": 0.25},
        )
        assert status == 200
        out = json.loads(raw)
        assert out["choices"][0]["finish_reason"] == "timeout"
        assert 0 < out["usage"]["completion_tokens"] < 64
        # The row must actually free (engine acked the deadline cancel).
        for _ in range(100):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        assert all(r.rid is None for r in srv.batcher.rows)
        srv.batcher.assert_pool_consistent()

    run_with_server(make_batcher(tiny, faults=plane), fn)


def test_server_default_timeout_applies(tiny):
    plane = FaultPlane.parse("batcher.decode:stall@1+:0.1")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "slow", "max_tokens": 64},
        )
        assert status == 200
        assert json.loads(raw)["choices"][0]["finish_reason"] == "timeout"
        # Bad timeout values 400.
        for bad in (0, -1, "soon", True):
            status, _ = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 2, "timeout_s": bad},
            )
            assert status == 400, bad

    run_with_server(make_batcher(tiny, faults=plane), fn,
                    request_timeout_s=0.25)


def test_timeout_of_queued_request_is_shed_503(tiny):
    """A request whose deadline expires while it is still QUEUED (slot
    held by another row) is SHED at the next chunk boundary — a 503 with
    Retry-After and a structured overloaded_error, NOT an empty 200
    "timeout": nothing was ever produced, so the client should retry
    elsewhere/later (PR 2 answered 200 here, admitted-doomed style)."""
    import time

    plane = FaultPlane.parse("batcher.decode:stall@1+:0.05")
    shed0 = METRICS.get_counter("server.requests_shed_total")

    async def fn(host, port, srv):
        long_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "slot hog", "max_tokens": 48},
        ))
        for _ in range(500):
            if srv._requests:
                break
            await asyncio.sleep(0.01)
        assert srv._requests
        t0 = time.perf_counter()
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "queued", "max_tokens": 8, "timeout_s": 0.2},
        )
        dt = time.perf_counter() - t0
        assert status == 503, raw
        out = json.loads(raw)
        assert out["error"]["type"] == "overloaded_error", out
        assert "shed" in out["error"]["message"]
        # Chunk-boundary shed, nowhere near the 10 s grace fallback.
        assert dt < 5.0, dt
        assert METRICS.get_counter("server.requests_shed_total") > shed0
        status, _ = await long_task
        assert status == 200

    run_with_server(make_batcher(tiny, batch_slots=1, faults=plane), fn)


def test_unrecoverable_engine_rejects_new_requests(tiny):
    """When the respawn itself fails, in-flight requests get the
    structured engine error, NEW requests get an immediate 500 instead of
    hanging on a dead queue, and /healthz goes (and stays) unhealthy."""
    plane = FaultPlane.parse("batcher.decode:raise@1")

    def bad_factory():
        raise RuntimeError("no memory left for a fresh pool")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "doomed", "max_tokens": 8},
        )
        assert status == 500
        assert json.loads(raw)["error"]["message"] == "engine unrecoverable"
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "after the fall", "max_tokens": 2},
        )
        assert status == 500
        assert json.loads(raw)["error"]["type"] == "engine_error"
        status, raw = await _request(host, port, "GET", "/healthz")
        assert status == 503
        assert json.loads(raw)["engine_alive"] is False

    run_with_server(make_batcher(tiny, faults=plane), fn,
                    batcher_factory=bad_factory)


def test_watchdog_flips_healthz_on_stall(tiny):
    """A stalled engine (wedged chunk) with in-flight work flips /healthz
    unhealthy; it reports healthy again once the work drains."""
    plane = FaultPlane.parse("batcher.decode:stall@2:1.2")

    async def fn(host, port, srv):
        req_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "wedge", "max_tokens": 16},
        ))
        unhealthy_seen = False
        for _ in range(100):
            status, raw = await _request(host, port, "GET", "/healthz")
            health = json.loads(raw)
            if status == 503 and health["engine_stalled"]:
                unhealthy_seen = True
                break
            await asyncio.sleep(0.05)
        assert unhealthy_seen, "watchdog never flipped /healthz"
        status, _ = await req_task
        assert status == 200
        for _ in range(100):
            status, raw = await _request(host, port, "GET", "/healthz")
            if status == 200:
                break
            await asyncio.sleep(0.05)
        assert status == 200

    run_with_server(make_batcher(tiny, faults=plane), fn,
                    watchdog_timeout_s=0.3)


def test_healthz_unhealthy_while_draining(tiny):
    async def fn(host, port, srv):
        status, raw = await _request(host, port, "GET", "/healthz")
        assert status == 200
        # An in-flight request holds the drain open long enough to observe
        # the draining state (an empty drain completes immediately).
        req_task = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hold the drain open", "max_tokens": 32},
        ))
        for _ in range(500):
            if srv._requests:
                break
            await asyncio.sleep(0.01)
        assert srv._requests
        stop_task = asyncio.create_task(srv.stop(drain_timeout=30.0))
        await asyncio.sleep(0)  # let stop() flip _draining
        status, raw = await _request(host, port, "GET", "/healthz")
        assert status == 503
        assert json.loads(raw)["status"] == "draining"
        status, _ = await req_task  # drains to completion
        assert status == 200
        await stop_task

    run_with_server(make_batcher(tiny), fn)


def test_streamed_timeout_carries_finish_reason(tiny):
    plane = FaultPlane.parse("batcher.decode:stall@1+:0.1")

    async def fn(host, port, srv):
        reader, writer = await asyncio.open_connection(host, port)
        body = json.dumps({"prompt": "slow", "max_tokens": 64,
                           "timeout_s": 0.25, "stream": True}).encode()
        writer.write(
            f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        assert status == 200
        finish = None
        while True:
            line = await reader.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            fr = ev["choices"][0].get("finish_reason")
            if fr is not None:
                finish = fr
        writer.close()
        assert finish == "timeout"

    run_with_server(make_batcher(tiny, faults=plane), fn)


def test_watchdog_counts_batcher_held_rows(tiny):
    """The stall predicate must key on engine-held work, not just open
    HTTP handlers: once timed-out handlers answer their clients and leave
    _requests, a wedged engine still pins rows/pages — /healthz must keep
    reporting stalled rather than telling the load balancer "healthy"."""
    import time

    async def fn(host, port, srv):
        status, _ = await _request(host, port, "GET", "/healthz")
        assert status == 200
        # A wedged engine, reconstructed piecewise: a batcher-held row
        # with no open handler, and no progress for ages.
        srv.batcher.rows[0].rid = 12345
        srv._last_progress -= 10 * srv.watchdog_timeout_s
        status, raw = await _request(host, port, "GET", "/healthz")
        health = json.loads(raw)
        assert status == 503, health
        assert health["engine_stalled"] is True
        assert health["inflight_requests"] == 0
        # Row released + progress resumes -> healthy again.
        srv.batcher.rows[0].rid = None
        srv._last_progress = time.monotonic()
        status, _ = await _request(host, port, "GET", "/healthz")
        assert status == 200

    run_with_server(make_batcher(tiny), fn, watchdog_timeout_s=0.3)


def test_stop_hit_before_deadline_reports_stop(tiny):
    """A stop-sequence hit followed by the deadline expiring during the
    cancel-ack drain is a STOP, not a timeout: the response legitimately
    terminated before the deadline; only the row-free ack was late."""
    want = expected_text(tiny, "halt", 8)
    # First chunk lands fast and contains the stop; every later chunk
    # (the ack carrier) stalls past the deadline but inside the grace.
    plane = FaultPlane.parse("batcher.decode:stall@2+:1.5")

    async def fn(host, port, srv):
        status, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "halt", "max_tokens": 64, "timeout_s": 0.6,
             "stop": [want[0]]},
        )
        assert status == 200
        out = json.loads(raw)
        assert out["choices"][0]["finish_reason"] == "stop", out
        # The ack drained: row freed, pool clean.
        for _ in range(100):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        assert all(r.rid is None for r in srv.batcher.rows)
        srv.batcher.assert_pool_consistent()

    run_with_server(make_batcher(tiny, faults=plane), fn)
