"""Paged speculative decoding (round 17): the draft/verify chain joins the
paged pool, prefix cache, pressure ladder, and mixed scheduler.

The acceptance contract pinned here:

- **Byte-exactness.**  At temperature 0 the paged speculative engine's
  streams are IDENTICAL to (a) the contiguous speculative engine and
  (b) the non-speculative paged engine — across prefix-cache hits, int8
  pages, overlap on/off, mixed-step budgets, and the adaptive spec_k
  downshift (acceptance only changes arrival granularity, never bytes).
- **swap x spec (the ROADMAP's declared composition debt).**  A
  speculative row preempted mid-stream through the SWAP rung restores
  byte-exact (target pages verbatim from the host tier, draft cache
  rebuilt from prompt+emitted), and the host-budget-dry recompute
  fallback leg is equally exact.
- **Clear rejections.**  spec x {chunked prefill, mesh>1, constraints}
  still fail fast with actionable errors.
- **The audit holds.**  ``assert_pool_consistent()`` after every
  workload — scratch-tail pages release with their rows.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane, InjectedFault
from distributed_llms_tpu.runtime.scheduler import (MixedScheduler,
                                                    Scheduler,
                                                    SpecMixedScheduler,
                                                    make_scheduler)

# Spec programs crash long-lived XLA:CPU processes — whole-family
# fresh-process isolation (tests/conftest.py + test_isolated.ISOLATED).
pytestmark = pytest.mark.fragile_xla_cpu


@pytest.fixture(scope="module")
def models():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    dcfg = presets.get_preset("llama-tiny", vocab_size=512, num_layers=2)
    dparams = model_lib.init_params(jax.random.key(99), dcfg)  # unrelated
    return cfg, params, dcfg, dparams


def _mk(models, spec=True, self_draft=False, **kw):
    cfg, params, dcfg, dparams = models
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk_steps", 4)
    if spec:
        kw.setdefault("spec_k", 3)
        kw.setdefault("draft_params", params if self_draft else dparams)
        kw.setdefault("draft_cfg", cfg if self_draft else dcfg)
    return ContinuousBatcher(cfg, params, **kw)


def _run(b, reqs):
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    b.assert_pool_consistent()
    return [res[r] for r in rids]


REQS = [([7, 1, 9, 4, 2], 9), ([4, 4, 4], 5), ([11, 12], 12), ([42], 7),
        ([3, 1], 1)]
PAGED = dict(paged_pages=24, page_size=16)


# -- the composition matrix: byte-exact vs contiguous spec AND plain --------


def test_paged_spec_matches_plain_and_contiguous(models):
    """The tentpole invariant: paged speculative streams are bit-identical
    to the contiguous speculative engine's AND to the plain (greedy,
    non-spec) engine's — paged or not."""
    plain = _run(_mk(models, spec=False), REQS)
    plain_paged = _run(_mk(models, spec=False, **PAGED), REQS)
    spec_cont = _run(_mk(models), REQS)
    spec_paged = _run(_mk(models, **PAGED), REQS)
    assert plain == plain_paged == spec_cont == spec_paged


def test_paged_spec_self_draft_backfill(models):
    """Self-draft: every round fully accepts — hammers the draft backfill
    and the scratch-tail page walk round after round."""
    reqs = [([7, 1, 9], 13), ([5, 5], 11)]
    plain = _run(_mk(models, spec=False), reqs)
    sp = _run(_mk(models, self_draft=True, spec_k=4, **PAGED), reqs)
    assert plain == sp


def test_paged_spec_prefix_cache_hit_exact(models):
    """A cache-hit speculative admission (admit_row_auto_paged) skips the
    drafted TARGET prefill for the cached run — bytes and cached_tokens
    both match the non-spec paged engine."""
    shared = list(range(100, 118))  # 18 tokens > one 8-token page

    def leg(spec):
        b = _mk(models, spec=spec, paged_pages=30, page_size=8,
                prefix_cache=True)
        r1 = b.submit(shared + [11], max_new_tokens=6)
        o1 = b.run()
        r2 = b.submit(shared + [12], max_new_tokens=6)
        o2 = b.run()
        b.assert_pool_consistent()
        return o1[r1], o2[r2], b.prefix_cached_tokens[r2]

    p1, p2, pc = leg(False)
    s1, s2, sc = leg(True)
    assert (p1, p2) == (s1, s2)
    assert sc == pc and sc == 16  # two full 8-token pages served from cache


def test_paged_spec_int8_exact_vs_int8_plain(models):
    """spec x int8 pages: the verify window quantizes its writes exactly
    like the plain decode step, so streams equal the int8 plain engine's
    (quantization is parity-bounded vs bf16, but spec-vs-plain at one
    width is byte-exact)."""
    plain8 = _run(_mk(models, spec=False, kv_bits=8, **PAGED), REQS)
    spec8 = _run(_mk(models, kv_bits=8, **PAGED), REQS)
    assert plain8 == spec8


def test_paged_spec_overlap_on_vs_off(models):
    """The dispatch-ahead carry chains paged spec rounds device-resident;
    bytes identical with the overlap on or off."""
    on = _run(_mk(models, overlap=True, **PAGED), REQS)
    off = _run(_mk(models, overlap=False, **PAGED), REQS)
    assert on == off


def test_paged_spec_named_prefix_exact(models):
    """register_prefix x paged spec: the prefix KV seeds the target row at
    the spec table width; the draft prefills prefix+suffix itself."""
    def leg(spec):
        b = _mk(models, spec=spec, **PAGED)
        b.register_prefix("sys", [9, 8, 7, 6, 5])
        rids = [b.submit([1, 2], max_new_tokens=7, prefix="sys"),
                b.submit([4, 4, 4], max_new_tokens=6)]
        res = b.run()
        b.assert_pool_consistent()
        return [res[r] for r in rids]

    assert leg(False) == leg(True)


# -- swap x spec: the ROADMAP's declared composition debt -------------------


STORM = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]


def test_swap_preempt_spec_byte_exact(models):
    """Pin swap x spec byte-exact: an overcommitted speculative storm with
    the host tier armed SWAPS victims out mid-stream; every restored
    stream equals the never-preempted paged-spec run AND the contiguous
    spec run — and the spec accounting survives the preemption."""
    ref = _run(_mk(models, batch_slots=3, paged_pages=22, page_size=16,
                   spec_k=3), STORM)           # roomy pool: no preemption
    cont = _run(_mk(models, batch_slots=3, spec_k=3), STORM)  # contiguous
    out0 = METRICS.get_counter("batcher.kv_swaps.out")
    in0 = METRICS.get_counter("batcher.kv_swaps.in")
    b = _mk(models, batch_slots=3, paged_pages=9, page_size=16,
            spec_k=3, host_pages=16)
    got = _run(b, STORM)
    assert got == ref == cont
    assert b.preemptions >= 1
    assert METRICS.get_counter("batcher.kv_swaps.out") - out0 >= 1
    assert METRICS.get_counter("batcher.kv_swaps.in") - in0 >= 1
    assert b.spec_stats["rounds"] > 0


def test_swap_spec_host_budget_dry_recompute_fallback(models):
    """The same storm with a 1-page host tier: every victim falls back to
    exact recompute (draft re-prefilled from prompt+emitted at
    re-admission) — still byte-exact, and the fallback counter says so."""
    ref = _run(_mk(models, batch_slots=3, paged_pages=22, page_size=16,
                   spec_k=3), STORM)
    fb0 = METRICS.get_counter("batcher.kv_swaps.fallback")
    in0 = METRICS.get_counter("batcher.kv_swaps.in")
    b = _mk(models, batch_slots=3, paged_pages=9, page_size=16,
            spec_k=3, host_pages=1)
    got = _run(b, STORM)
    assert got == ref
    assert b.preemptions >= 1
    assert METRICS.get_counter("batcher.kv_swaps.fallback") - fb0 >= 1
    assert METRICS.get_counter("batcher.kv_swaps.in") == in0


def test_swap_spec_streams_once_across_restore(models):
    """Streamed deliveries across a spec swap restore never re-deliver
    and fire done exactly once per rid."""
    b = _mk(models, batch_slots=3, paged_pages=9, page_size=16,
            spec_k=3, host_pages=16)
    deliveries, dones = {}, {}

    def on_tokens(rid, toks, done, lps):
        deliveries.setdefault(rid, []).extend(toks)
        if done:
            dones[rid] = dones.get(rid, 0) + 1

    rids = [b.submit(ids, max_new_tokens=n) for ids, n in STORM]
    res = b.run(on_tokens=on_tokens)
    b.assert_pool_consistent()
    assert b.preemptions >= 1
    for rid in rids:
        assert deliveries[rid] == res[rid], "stream diverged from result"
        assert dones[rid] == 1


# -- adaptive spec_k downshift ----------------------------------------------


def test_adaptive_k_downshift_deterministic_and_exact(models):
    """An unrelated draft's acceptance collapses, the EMA downshifts k —
    bytes still equal the plain engine's, two identical runs downshift
    identically, and the downshift counter moved."""
    reqs = [([7, 1, 9, 4, 2], 24), ([4, 4, 4], 20)]
    plain = _run(_mk(models, spec=False, **PAGED), reqs)

    def leg():
        b = _mk(models, spec_k=4, **PAGED)
        return _run(b, reqs), dict(b.spec_stats)

    got1, stats1 = leg()
    got2, stats2 = leg()
    assert got1 == plain and got2 == plain
    assert stats1 == stats2, "downshift schedule is nondeterministic"
    assert stats1["downshifts"] >= 1, "cold draft never downshifted"
    assert stats1["rejected"] > 0


def test_adaptive_k_off_never_downshifts(models):
    reqs = [([7, 1, 9, 4, 2], 16)]
    plain = _run(_mk(models, spec=False, **PAGED), reqs)
    b = _mk(models, spec_k=4, spec_adaptive_k=False, **PAGED)
    assert _run(b, reqs) == plain
    assert b.spec_stats["downshifts"] == 0


def test_token_budget_clamps_spec_rounds(models):
    """Mixed-step budget accounting: with token_budget tighter than
    n_active*(spec_k+1), the scheduler clamps every round's draft length
    (downshifts fire even with a perfect self-draft) and bytes stay
    identical to the unbudgeted run."""
    reqs = [([7, 1, 9], 12), ([5, 5], 12)]
    free = _run(_mk(models, self_draft=True, spec_k=4, **PAGED), reqs)
    d0 = METRICS.get_counter("batcher.spec.k_downshifts")
    b = _mk(models, self_draft=True, spec_k=4, token_budget=6, **PAGED)
    got = _run(b, reqs)
    assert got == free
    assert b.spec_stats["downshifts"] >= 1
    assert METRICS.get_counter("batcher.spec.k_downshifts") > d0


# -- scheduler policy hooks (model-free) ------------------------------------


def test_spec_round_k_policy_hooks():
    """The budget-aware spec policy subclass: mixed+speculative resolves
    to SpecMixedScheduler; the budget clamp bounds n_active*(k+1); the
    EMA scales per-row k; alternate and adaptive-off never downshift."""
    s = make_scheduler("mixed", speculative=True, token_budget=8)
    assert isinstance(s, SpecMixedScheduler)
    # Budget clamp: 2 rows at k=4 would cost 10 > 8 -> kb=3 (cost 8).
    assert s.spec_round_k(4, (1.0, 1.0), 2) == [3, 3]
    # EMA downshift: a cold row drops toward 1, a hot row keeps kb.
    assert s.spec_round_k(4, (1.0, 0.1), 2) == [3, 1]
    assert s.spec_round_k(4, (0.0, 0.5), 1) == [1, 2]
    # No budget: only the EMA clamps.
    s2 = make_scheduler("mixed", speculative=True)
    assert s2.spec_round_k(4, (1.0, 0.4), 4) == [4, 2]
    # Adaptive off / alternate policy: always the full k.
    s3 = make_scheduler("mixed", speculative=True, spec_adaptive=False)
    assert s3.spec_round_k(4, (0.0, 0.0), 2) == [4, 4]
    s4 = make_scheduler("alternate", speculative=True, token_budget=4)
    assert type(s4) is Scheduler
    assert s4.spec_round_k(4, (0.0,), 3) == [4]
    # Non-speculative mixed stays the plain MixedScheduler.
    assert type(make_scheduler("mixed")) is MixedScheduler


# -- pool geometry ----------------------------------------------------------


def test_spec_scratch_tail_geometry(models):
    """Spec page tables carry the scratch-tail pages (the contiguous
    engine's +spec_k+1 headroom, as pages) and the pool floor check
    accounts for them."""
    cfg, params, dcfg, dparams = models
    b = _mk(models, spec_k=3, **PAGED)
    assert b.pages_per_row == -(-(64 + 3 + 1) // 16) == 5
    assert _mk(models, spec=False, **PAGED).pages_per_row == 4
    # 5 pages + 1 scratch is the spec floor at max_len 64 / page 16.
    with pytest.raises(ValueError, match="full-depth row"):
        _mk(models, spec_k=3, paged_pages=5, page_size=16)
    _mk(models, spec=False, paged_pages=5, page_size=16)  # plain fits


# -- fault drill + supervisor respawn ---------------------------------------


def test_spec_verify_raise_drill_respawn_exact(models):
    """batcher.spec_verify raise drill: the crash propagates out of run()
    (the supervisor contract), the rule counts exactly one firing, and a
    respawn serves the same request byte-exact."""
    want = _run(_mk(models, **PAGED), [([7, 1, 9], 8)])
    plane = FaultPlane.parse("batcher.spec_verify/verify:raise@2")
    b = _mk(models, faults=plane, **PAGED)
    b.submit([7, 1, 9], max_new_tokens=8)
    with pytest.raises(InjectedFault):
        b.run()
    assert plane.rules[0].fired == 1
    b2 = b.respawn()
    rid = b2.submit([7, 1, 9], max_new_tokens=8)
    assert [b2.run()[rid]] == want
    b2.assert_pool_consistent()
    # The draft-tagged leg drills the same site's other phase.
    plane_d = FaultPlane.parse("batcher.spec_verify/draft:raise@1")
    bd = _mk(models, faults=plane_d, **PAGED)
    bd.submit([4, 4], max_new_tokens=4)
    with pytest.raises(InjectedFault):
        bd.run()
    assert plane_d.rules[0].fired == 1


def test_spec_verify_stall_drill_serves_exact(models):
    """batcher.spec_verify stall drill: a slow verify (the engine thread
    blocked at the verification boundary) delays but never corrupts —
    tokens equal the uninjected run and the stall really slept."""
    import time

    want = _run(_mk(models, **PAGED), [([7, 1, 9], 8)])
    plane = FaultPlane.parse("batcher.spec_verify/verify:stall@1:0.05")
    b = _mk(models, faults=plane, **PAGED)
    rid = b.submit([7, 1, 9], max_new_tokens=8)
    t0 = time.perf_counter()
    assert [b.run()[rid]] == want
    assert time.perf_counter() - t0 >= 0.05
    assert plane.rules[0].fired == 1
    b.assert_pool_consistent()


def test_spec_metrics_accrue(models):
    r0 = METRICS.get_counter("batcher.spec.rounds")
    a0 = METRICS.get_counter("batcher.spec.accepted_tokens")
    b = _mk(models, self_draft=True, **PAGED)
    _run(b, [([7, 1, 9], 10)])
    assert METRICS.get_counter("batcher.spec.rounds") > r0
    assert METRICS.get_counter("batcher.spec.accepted_tokens") > a0
    assert 0.0 <= METRICS.get_gauge("batcher.spec.acceptance") <= 1.0


# -- rejections stay clear --------------------------------------------------


def test_spec_rejections_still_clear(models):
    cfg, params, dcfg, dparams = models
    spec = dict(draft_params=dparams, draft_cfg=dcfg)
    # chunked prefill: the draft admission prefills monolithically.
    with pytest.raises(ValueError, match="chunked prefill"):
        ContinuousBatcher(cfg, params, max_len=64, prefill_chunk=8, **spec)
    # mesh > 1: the draft/verify chain has no SPMD rule.
    fake_mesh = types.SimpleNamespace(shape={"data": 1, "model": 1})
    fake_pm = types.SimpleNamespace(pipelined=False, seq_parallel=False,
                                    mesh=fake_mesh, kv_dtype=None)
    with pytest.raises(ValueError, match="single-device"):
        ContinuousBatcher(cfg, params, max_len=64, parallel=fake_pm, **spec)
    # constraints: the token mask would need to ride both models.
    b = _mk(models, **PAGED)
    with pytest.raises(ValueError, match="constrained"):
        b.submit([1, 2], max_new_tokens=4,
                 response_format={"type": "regex", "regex": "a+"})
    # per-request sampling overrides: one static warp config per engine.
    with pytest.raises(ValueError, match="engine-wide"):
        b.submit([1, 2], max_new_tokens=4, temperature=0.9)
