"""Disaggregated prefill/decode serving (ISSUE 7 acceptance).

The contract pinned here: a fleet with dedicated PREFILL-role replicas
(admission/chunked prefill + KV page export) and DECODE-role replicas
(verified import + decode) behind the router's handoff plane serves
every request temp-0 BYTE-EXACT vs a colocated reference — and every
way the handoff can fail (prefill crash/stall/partition mid-handoff,
frame corruption, duplicate delivery, digest mismatch, transfer-retry
exhaustion, an empty prefill tier) either heals transparently (retry,
idempotent re-delivery) or degrades to COLOCATED prefill on the decode
replica, never to wrong bytes.  Pool audits stay clean on both roles.

The chaos acceptance test (2 prefill + 2 decode under storm surviving a
prefill crash mid-handoff + a corrupted frame + a stalled transfer) is
tier-1; the bigger storm variant is marked slow.
"""

import asyncio
import json

import pytest

import jax

from distributed_llms_tpu.cluster.fleet import ReplicaFleet
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane
from distributed_llms_tpu.runtime.router import ReplicaRouter
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

PAGE = 16


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _replica_batcher(tiny, pages=12, **bkw):
    cfg, params = tiny
    tok = ByteTokenizer()
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=2, max_len=96, chunk_steps=4,
        paged_pages=pages, page_size=PAGE, prefix_cache=True, **bkw,
    )


@pytest.fixture(scope="module")
def warmed(tiny):
    """Warm the process-wide jit cache with the replicas' program shapes
    (paged admission, cache-hit admission — the handed-off request's
    path — and decode) so the fast watchdogs below never mistake a cold
    compile for a wedged engine."""
    b = _replica_batcher(tiny)
    for prompt in ("warm short", "a much longer warming prompt xxxx!!",
                   "a much longer warming prompt xxxx!!"):
        b.submit(prompt, max_new_tokens=4)
        b.run()
    return tiny


def role_factory(tiny, role, batcher_kw=None, **srv_kw):
    srv_kw.setdefault("watchdog_timeout_s", 2.0)
    bkw = batcher_kw or {}

    def make_server():
        return InferenceServer(
            _replica_batcher(tiny, **bkw), model_name="tiny",
            host="127.0.0.1", port=0,
            batcher_factory=lambda: _replica_batcher(tiny, **bkw),
            role=role, **srv_kw,
        )

    return make_server


def run_with_disagg_fleet(tiny, n_prefill, n_decode, fn, faults=None,
                          srv_kw=None, router_kw=None, batcher_kw=None):
    """Boot an (n_prefill prefill + n_decode decode)-role fleet behind a
    handoff-enabled router, wait healthy, run ``fn``, tear down.  The
    shared ``faults`` plane serves the event-loop sites (xfer.*,
    prefill.crash, replica.*, router.*): every server's batcher gets it
    too, which is safe here because batcher.* rules are never armed on
    it in these tests."""

    async def driver():
        factories = (
            [role_factory(tiny, "prefill", batcher_kw=batcher_kw,
                          **(srv_kw or {}))] * n_prefill
            + [role_factory(tiny, "decode", batcher_kw=batcher_kw,
                            **(srv_kw or {}))] * n_decode
        )
        names = [f"p{i}" for i in range(n_prefill)] \
            + [f"d{i}" for i in range(n_decode)]
        fleet = ReplicaFleet(factories, names=names,
                             probe_interval_s=0.05, probe_timeout_s=2.0,
                             faults=faults)
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, tokenizer=ByteTokenizer(),
            page_size=PAGE, handoff=True, faults=faults,
            **(router_kw or {}),
        )
        await fleet.start()
        if faults is not None:
            # xfer.send / prefill.crash fire on the serving replicas'
            # own planes (batcher.faults); xfer.recv / xfer.verify on the
            # decode replicas'.  Point them all at the shared plane so a
            # test arms ONE rule set.
            for h in fleet.replicas:
                h.server.batcher.faults = faults
        host, port = await router.start()
        try:
            assert await fleet.wait_healthy(timeout_s=120.0)
            return await asyncio.wait_for(
                fn(host, port, fleet, router), timeout=600
            )
        finally:
            await router.stop()
            await fleet.stop()

    return asyncio.run(driver())


async def _request(host, port, method, path, body=None):
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    data = await reader.read()
    writer.close()
    return status, headers, data


def expected_texts(tiny, reqs):
    """Reference texts from one roomy, un-faulted COLOCATED batcher —
    byte-exactness must be invariant to where prefill ran."""
    cfg, params = tiny
    tok = ByteTokenizer()
    b = ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        batch_slots=4, max_len=96, chunk_steps=4, paged_pages=40,
        page_size=PAGE,
    )
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return {p: tok.decode(res[rid]) for rid, (p, n) in zip(rids, reqs)}


LONG = "disaggregate this considerable prompt please! "  # > 2 full pages


def _audit_all(fleet):
    for h in fleet.replicas:
        if h.server is not None and h.server._engine is not None \
                and h.server._engine.is_alive():
            h.server.batcher.assert_pool_consistent()


# -- the happy path ---------------------------------------------------------


def test_disagg_roundtrip_exact_and_offloads_prefill(warmed):
    tiny = warmed
    """A long prompt is prefilled on the prefill tier, its KV pages ship
    verified to the decode replica, and the decode admission serves the
    prompt from the imported pages (usage.cached_tokens proves it) —
    output byte-exact vs a colocated reference."""
    reqs = [(LONG + "tail one", 8), ("tiny", 4)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        h0 = METRICS.get_counter("router.handoffs")
        imp0 = METRICS.get_counter("batcher.kv_pages_imported")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        # The decode replica served the shipped pages from its cache.
        cached = body["usage"]["prompt_tokens_details"]["cached_tokens"]
        assert cached >= PAGE, body["usage"]
        assert METRICS.get_counter("router.handoffs") > h0
        assert METRICS.get_counter("batcher.kv_pages_imported") > imp0
        # The SAME prompt again: the decode replica provably already
        # holds the run (epoch-valid affinity), so the router must skip
        # the redundant multi-MB transfer — and still serve exact bytes
        # from the resident pages.
        h1 = METRICS.get_counter("router.handoffs")
        sk0 = METRICS.get_counter("router.handoff_skips")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert body["usage"]["prompt_tokens_details"]["cached_tokens"] \
            >= cached
        assert METRICS.get_counter("router.handoffs") == h1
        assert METRICS.get_counter("router.handoff_skips") > sk0
        # A prompt under one full page skips the handoff plane entirely
        # (nothing exportable) and still completes exactly.
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[1][0], "max_tokens": reqs[1][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[1][0]]
        # Roles hold: completions never land on the prefill tier.
        assert all(
            h.last_report.get("role") == h.role for h in fleet.replicas
            if h.last_report
        )
        _audit_all(fleet)

    run_with_disagg_fleet(tiny, 1, 1, fn)


def test_chunked_prefill_on_prefill_role_exports_complete_pages(warmed):
    """The chunked-prefill x disaggregation corner: a prefill-role
    replica whose admission takes the CHUNKED path (prompt >
    prefill_chunk, consumed in bites across scheduling rounds) must
    still publish the prompt's FULL digest-chained page run and export
    every full page for the handoff — and the decode replica must serve
    the forwarded request from those imported pages, byte-exact vs a
    monolithic colocated reference."""
    tiny = warmed
    prompt = LONG + "tail one"
    reqs = [(prompt, 8)]
    wants = expected_texts(tiny, reqs)
    tok_ids = ByteTokenizer().encode(prompt)
    n_exportable = (len(tok_ids) - 1) // PAGE  # capped one page short
    assert n_exportable >= 2  # the corner needs a multi-page chunked run

    # Warm the CHUNKED program shapes (prefill_chunk_step + chunked
    # finish + cache-hit chunked continuation) before any watchdog is
    # armed — the jit cache is process-wide, so the fleet's replicas
    # never mistake a cold compile for a wedged engine.
    b = _replica_batcher(tiny, prefill_chunk=PAGE)
    for _ in range(2):  # second pass takes the cache-hit chunked path
        b.submit(prompt, max_new_tokens=2)
        b.run()

    async def fn(host, port, fleet, router):
        exp0 = METRICS.get_counter("batcher.kv_pages_exported")
        imp0 = METRICS.get_counter("batcher.kv_pages_imported")
        h0 = METRICS.get_counter("router.handoffs")
        ch0 = METRICS.get_counter("batcher.prefill_chunks")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": prompt, "max_tokens": 8},
        )
        body = json.loads(raw)
        assert status == 200, body
        # Byte-exact vs the monolithic colocated reference: chunked
        # prefill, the handoff, AND the imported-page continuation all
        # compose without changing a single token.
        assert body["choices"][0]["text"] == wants[prompt]
        assert METRICS.get_counter("router.handoffs") > h0
        # The prefill replica exported the COMPLETE run (every full page
        # the chunked finish published), not just a prefix of it ...
        assert METRICS.get_counter("batcher.kv_pages_exported") - exp0 \
            == n_exportable
        # ... the decode replica adopted them ...
        assert METRICS.get_counter("batcher.kv_pages_imported") - imp0 \
            == n_exportable
        # ... and its (also chunked) admission served the prompt from
        # the imported pages rather than re-prefilling it.
        cached = body["usage"]["prompt_tokens_details"]["cached_tokens"]
        assert cached >= n_exportable * PAGE, body["usage"]
        # The CHUNKED path really ran (not a silent monolithic
        # fallback): the prefill replica bit the uncached prompt off in
        # PAGE-sized chunks (ceil(len/PAGE) bites) — a regression to
        # monolithic admission would leave the counter flat.
        bites = METRICS.get_counter("batcher.prefill_chunks") - ch0
        assert bites >= -(-len(tok_ids) // PAGE), bites
        _audit_all(fleet)

    run_with_disagg_fleet(
        tiny, 1, 1, fn,
        batcher_kw={"prefill_chunk": PAGE},
        srv_kw={"watchdog_timeout_s": 10.0},
    )


# -- transfer-level faults heal in place ------------------------------------


def test_handoff_corrupt_frame_and_dup_delivery_absorbed(warmed):
    tiny = warmed
    """A corrupted first transfer attempt is rejected by the receiver's
    checksum verify and NACKed; the jittered retry succeeds — the
    request never notices.  A duplicated frame is absorbed idempotently
    via the digest check (no double import)."""
    plane = FaultPlane()
    corrupt = plane.add("xfer.send", "corrupt", when="1")
    dup = plane.add("xfer.send", "dup", when="3")
    # Distinct FIRST pages: a shared leading page would make the second
    # request's digest run affinity-warm on the decode replica and skip
    # its handoff entirely (the optimization the roundtrip test pins).
    reqs = [("corrupt leg " + LONG, 8), ("dup leg!!!! " + LONG, 8)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        vf0 = METRICS.get_counter("xfer.verify_failures")
        rt0 = METRICS.get_counter("xfer.retries")
        dd0 = METRICS.get_counter("xfer.dup_deliveries")
        fb0 = METRICS.get_counter("router.handoff_fallbacks")
        for p, n in reqs:
            status, _, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": p, "max_tokens": n},
            )
            body = json.loads(raw)
            assert status == 200, body
            assert body["choices"][0]["text"] == wants[p], p
        assert corrupt.fired == 1
        assert dup.fired == 1
        assert METRICS.get_counter("xfer.verify_failures") > vf0
        assert METRICS.get_counter("xfer.retries") > rt0
        assert METRICS.get_counter("xfer.dup_deliveries") > dd0
        # Both healed inside the transfer plane: no degradation needed.
        assert METRICS.get_counter("router.handoff_fallbacks") == fb0
        _audit_all(fleet)

    run_with_disagg_fleet(tiny, 1, 1, fn, faults=plane)


# -- the degradation ladder -------------------------------------------------


def test_verify_rejection_exhausts_retries_falls_back_colocated(warmed):
    tiny = warmed
    """Every delivery failing verification (digest mismatch) exhausts the
    bounded transfer retries; the handoff reports failure and the router
    serves the request COLOCATED on the decode replica — byte-exact."""
    plane = FaultPlane()
    rule = plane.add("xfer.verify", "corrupt", when="*")
    reqs = [(LONG + "mismatch leg", 8)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        fb0 = METRICS.get_counter("router.handoff_fallbacks")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert rule.fired >= 2  # initial attempt + >= 1 retry, all rejected
        assert METRICS.get_counter("router.handoff_fallbacks") > fb0
        _audit_all(fleet)

    run_with_disagg_fleet(tiny, 1, 1, fn, faults=plane,
                          srv_kw=dict(xfer_max_retries=1,
                                      xfer_attempt_s=2.0))


def test_transfer_stall_past_deadline_falls_back_colocated(warmed):
    tiny = warmed
    """A transfer stalled past the router's handoff deadline degrades to
    colocated prefill — the client sees only (slightly later) exact
    bytes."""
    plane = FaultPlane()
    rule = plane.add("xfer.send", "delay", when="1", arg=5.0)
    reqs = [(LONG + "stalled leg!", 8)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        fb0 = METRICS.get_counter("router.handoff_fallbacks")
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": reqs[0][0], "max_tokens": reqs[0][1]},
        )
        body = json.loads(raw)
        assert status == 200, body
        assert body["choices"][0]["text"] == wants[reqs[0][0]]
        assert rule.fired == 1
        assert METRICS.get_counter("router.handoff_fallbacks") > fb0
        _audit_all(fleet)

    run_with_disagg_fleet(tiny, 1, 1, fn, faults=plane,
                          router_kw=dict(handoff_deadline_s=1.0))


def test_prefill_crash_mid_handoff_falls_back_colocated(warmed):
    tiny = warmed
    """The prefill replica dies ABRUPTLY serving the handoff (sockets
    severed unflushed): the router observes the reset, degrades to
    colocated prefill, and the request completes exactly.  With the
    prefill tier dead, LATER requests skip the handoff plane entirely
    (no_prefill_replica) and still complete exactly."""
    plane = FaultPlane()
    rule = plane.add("prefill.crash", "close", when="1")
    # Distinct first pages: request 2 must attempt its OWN handoff (a
    # shared leading page would be affinity-warm and skip the plane).
    reqs = [("crash victim " + LONG, 8), ("after crash! " + LONG, 8)]
    wants = expected_texts(tiny, reqs)

    async def fn(host, port, fleet, router):
        fb0 = METRICS.get_counter("router.handoff_fallbacks")
        for p, n in reqs:
            status, _, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": p, "max_tokens": n},
            )
            body = json.loads(raw)
            assert status == 200, body
            assert body["choices"][0]["text"] == wants[p], p
        assert rule.fired == 1
        assert METRICS.get_counter("router.handoff_fallbacks") - fb0 >= 2
        # The probe loop marks the self-killed prefill replica unhealthy;
        # completions keep flowing off the decode tier regardless.
        for _ in range(200):
            if fleet["p0"].state != "healthy":
                break
            await asyncio.sleep(0.02)
        assert fleet["p0"].state != "healthy"
        _audit_all(fleet)

    run_with_disagg_fleet(tiny, 1, 1, fn, faults=plane)


# -- import-plane unit invariants -------------------------------------------


def test_kv_import_partial_overlap_allocates_only_missing(warmed):
    tiny = warmed
    """A transfer whose digest chain PARTIALLY overlaps already-resident
    content imports only the missing pages: no capacity demanded for
    pages it does not need, no scatter for content that would lose
    first-writer-wins, full duplicates absorbed with zero pool work —
    and the pool audits clean throughout."""
    import numpy as np

    from distributed_llms_tpu.runtime.batcher import PrefixCache

    b = _replica_batcher(tiny)
    l, _nb, blk, kvh, hd = b.cache.k.shape
    ids_a = list(range(1, 2 * PAGE + 1))          # pages A1, A2
    ids_b = ids_a[:PAGE] + list(range(100, 100 + PAGE))  # A1 shared, B2 new
    dig_a = PrefixCache.page_digests(ids_a, PAGE, 2)
    dig_b = PrefixCache.page_digests(ids_b, PAGE, 2)
    assert dig_a[0] == dig_b[0] and dig_a[1] != dig_b[1]

    def payload(seed):
        shape = (l, 2, blk, kvh, hd)
        k = np.full(shape, float(seed), np.float32)
        return k, k + 1.0

    results = []
    imp0 = METRICS.get_counter("batcher.kv_pages_imported")
    ka, va = payload(1)
    b.submit_kv_import(dig_a, ka, va, lambda ok, r: results.append((ok, r)))
    b._drain_kv_imports()
    assert results[-1] == (True, "imported")
    after_a = b.pool.stats()  # A1+A2 parked content-cached in the LRU
    assert after_a["cached_pages"] == 2
    kb, vb = payload(2)
    b.submit_kv_import(dig_b, kb, vb, lambda ok, r: results.append((ok, r)))
    b._drain_kv_imports()
    assert results[-1] == (True, "imported")
    # Only B2 allocated: exactly one page moved free -> content-cached.
    after_b = b.pool.stats()
    assert after_b["free_pages"] == after_a["free_pages"] - 1
    assert after_b["cached_pages"] == after_a["cached_pages"] + 1
    assert METRICS.get_counter("batcher.kv_pages_imported") - imp0 == 3
    # Exact duplicate: zero pool work, acked as such.
    b.submit_kv_import(dig_a, ka, va, lambda ok, r: results.append((ok, r)))
    b._drain_kv_imports()
    assert results[-1] == (True, "duplicate")
    assert b.pool.stats() == after_b
    b.assert_pool_consistent()


# -- THE chaos acceptance test ----------------------------------------------


def _disagg_storm(warmed, n_req, n_new):
    tiny = warmed
    # Distinct first pages so every request attempts its own handoff
    # (shared leading pages would be affinity-warm after the first).
    reqs = [(f"storm {i:02d} " + LONG, n_new) for i in range(n_req)]
    wants = expected_texts(tiny, reqs)
    plane = FaultPlane()
    # One prefill replica crashes abruptly mid-handoff, one transfer
    # frame is corrupted in flight (retry heals it), one transfer stalls
    # past the handoff deadline (degrades to colocated) — all while the
    # storm runs at ~1.5x the decode tier's pool capacity.
    crash = plane.add("prefill.crash", "close", when="2")
    corrupt = plane.add("xfer.send", "corrupt", when="3")
    stall = plane.add("xfer.send", "delay", when="5", arg=6.0)

    async def one(host, port, i, p, n):
        if i % 5 == 4:  # a streamed minority rides along
            reader, writer = await asyncio.open_connection(host, port)
            payload = json.dumps(
                {"prompt": p, "max_tokens": n, "stream": True}
            ).encode()
            writer.write(
                f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
            )
            await writer.drain()
            raw = await reader.read()
            writer.close()
            return ("sse", raw)
        return ("http", await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": n},
        ))

    async def fn(host, port, fleet, router):
        fb0 = METRICS.get_counter("router.handoff_fallbacks")
        ho0 = METRICS.get_counter("router.handoffs")

        async def staggered(i, p, n):
            await asyncio.sleep(i * 0.06)
            return await one(host, port, i, p, n)

        tasks = [asyncio.create_task(staggered(i, p, n))
                 for i, (p, n) in enumerate(reqs)]
        outs = await asyncio.gather(*tasks)

        completed = shed = stream_failed = 0
        for (kind, out), (p, n) in zip(outs, reqs):
            if kind == "http":
                status, headers, raw = out
                body = json.loads(raw)
                if status == 200:
                    assert body["choices"][0]["text"] == wants[p], p
                    completed += 1
                else:
                    assert status in (429, 503), (status, body)
                    assert body["error"]["type"] in (
                        "overloaded_error", "engine_error",
                    ), body
                    assert int(headers["retry-after"]) >= 1
                    shed += 1
            else:
                head, _, text = out.decode().partition("\r\n\r\n")
                status_line = head.split("\r\n", 1)[0]
                if "200" not in status_line:
                    assert any(c in status_line for c in ("429", "503")), head
                    assert ("overloaded_error" in text
                            or "engine_error" in text), text
                    shed += 1
                elif "engine_error" in text:
                    stream_failed += 1
                else:
                    assert "[DONE]" in text, text
                    got = "".join(
                        json.loads(line[len("data: "):])["choices"][0]["text"]
                        for line in text.split("\n\n")
                        if line.startswith("data: ")
                        and not line.startswith("data: [DONE]")
                    )
                    assert got == wants[p], p
                    completed += 1
        assert completed + shed + stream_failed == n_req
        assert completed >= 3, (completed, shed, stream_failed)
        # Every armed drill actually fired, and every handoff failure was
        # COUNTED as a degradation (crash + stall at minimum; the
        # crashed prefill replica also costs later handoffs their tier
        # when it was the only one picked).
        assert crash.fired == 1, "prefill crash never fired"
        assert corrupt.fired >= 1, "frame corruption never fired"
        assert stall.fired >= 1, "transfer stall never fired"
        assert METRICS.get_counter("router.handoff_fallbacks") - fb0 >= 2
        assert METRICS.get_counter("router.handoffs") > ho0
        # Fleet steady state: surviving replicas drain, pools audit clean
        # on BOTH roles.
        for _ in range(400):
            if all(not h.inflight for h in fleet.replicas):
                break
            await asyncio.sleep(0.02)
        for h in fleet.replicas:
            if h.server._engine is not None and h.server._engine.is_alive():
                for _ in range(200):
                    if all(r.rid is None for r in h.server.batcher.rows):
                        break
                    await asyncio.sleep(0.05)
                h.server.batcher.assert_pool_consistent()
        alive_decode = [
            h for h in fleet.replicas if h.role == "decode"
            and h.server._engine is not None and h.server._engine.is_alive()
        ]
        assert len(alive_decode) == 2, "a decode replica died in the storm"

    run_with_disagg_fleet(tiny, 2, 2, fn, faults=plane,
                          router_kw=dict(handoff_deadline_s=2.5))


def test_chaos_disagg_storm(warmed):
    """ISSUE 7 acceptance: a 2-prefill + 2-decode fleet under storm
    survives one prefill crash mid-handoff, one corrupted transfer
    frame, and one stalled transfer — every completion byte-exact vs an
    unfaulted colocated reference, every handoff failure degraded to
    colocated prefill or a structured 429/503/engine_error, pool audits
    clean on both roles."""
    _disagg_storm(warmed, n_req=10, n_new=16)


@pytest.mark.slow
def test_chaos_disagg_storm_big(warmed):
    """The bigger storm variant: more offered load, same invariants."""
    _disagg_storm(warmed, n_req=18, n_new=24)
