"""KV sessions + host-DRAM spill (SURVEY §7 hard part 3 — the reference's
kv_host_spill never existed; its master kept no state between calls)."""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.parallel.api import make_parallel_model
from distributed_llms_tpu.runtime.engine import InferenceEngine


def _engine(spill=False, max_resident=4, preset="llama-tiny", parallel=None, **rt_kw):
    rt = RuntimeConfig(
        max_decode_steps=4, kv_host_spill=spill,
        max_resident_sessions=max_resident, max_seq_len=96, **rt_kw,
    )
    eng = InferenceEngine.from_preset(preset, rt, vocab_size=512)
    if parallel is not None:
        pm = make_parallel_model(
            eng.cfg, parallel, num_microbatches=2 if parallel.pipe > 1 else 1,
            devices=jax.devices()[: parallel.num_devices],
            kv_dtype=rt.kv_cache_dtype,  # match the single-device engine
        )
        eng = InferenceEngine(eng.cfg, rt, eng.params, parallel=pm)
    return eng


def test_session_first_turn_matches_oneshot():
    eng = _engine()
    sid, res = eng.start_session(["hello"], max_new_tokens=6)
    ref = eng.generate_text(["hello"], max_new_tokens=6)
    assert res.text == ref.text


def test_session_continuation_matches_growing_oneshot():
    """turn 1 + turn 2 through a session == one-shot generate over the full
    concatenated history (greedy, same weights)."""
    eng = _engine()
    sid, r1 = eng.start_session(["abcd"], max_new_tokens=5)
    r2 = eng.continue_session(sid, ["efgh"], max_new_tokens=5)

    # one-shot over the identical token history: prompt1 + gen1 + prompt2
    tok = eng.tokenizer
    history = tok.encode("abcd") + list(r1.tokens[0]) + tok.encode("efgh")
    import jax.numpy as jnp

    from distributed_llms_tpu.runtime import generate as gen_lib

    prompt = jnp.asarray([history], dtype=jnp.int32)
    lens = jnp.asarray([len(history)], dtype=jnp.int32)
    out = gen_lib.generate_tokens(
        eng.params, eng.cfg, prompt, lens, jax.random.key(eng.rt.seed),
        max_new_tokens=5, eos_id=tok.eos_id, pad_id=tok.pad_id,
    )
    assert np.array_equal(r2.tokens[0], np.asarray(out)[0])


def test_session_budget_enforced():
    eng = _engine()
    sid, _ = eng.start_session(["hello"], max_new_tokens=6)
    with pytest.raises(ValueError, match="exceeds session max_len"):
        eng.continue_session(sid, ["x" * 200], max_new_tokens=6)


def test_unknown_session_errors():
    eng = _engine()
    with pytest.raises(KeyError, match="unknown session"):
        eng.continue_session("session-999", ["x"])


def test_spill_and_restore_bit_exact():
    """With max_resident=1, opening a second session spills the first to
    host DRAM; continuing the first restores it and produces exactly what a
    no-spill engine produces."""
    eng = _engine(spill=True, max_resident=1)
    ctl = _engine(spill=False)

    sid_a, _ = eng.start_session(["first conversation"], max_new_tokens=4)
    sid_b, _ = eng.start_session(["second conversation"], max_new_tokens=4)
    sess_a = eng.sessions.get(sid_a)
    assert sess_a.spilled, "LRU session should have spilled to host"
    snap = METRICS.snapshot()["gauges"]
    assert snap["kv_spill.host_bytes"] > 0
    assert snap["kv_spill.spilled_sessions"] == 1

    ca, _ = ctl.start_session(["first conversation"], max_new_tokens=4)
    ctl.start_session(["second conversation"], max_new_tokens=4)

    r = eng.continue_session(sid_a, ["next turn"], max_new_tokens=4)
    r_ctl = ctl.continue_session(ca, ["next turn"], max_new_tokens=4)
    assert not sess_a.spilled
    assert np.array_equal(r.tokens, r_ctl.tokens)
    # b was evicted to make room for a
    assert eng.sessions.get(sid_b).spilled


def test_session_through_parallel_mesh(devices8):
    """Sessions serve through the pipelined/TP mesh path too: pp=2, tp=2,
    spill on, exact match vs single-device sessions."""
    mesh_cfg = MeshConfig(data=1, pipe=2, model=2)
    eng = _engine(spill=True, max_resident=1, parallel=mesh_cfg)
    ctl = _engine()

    sid, r1 = eng.start_session(["mesh session"], max_new_tokens=4)
    _ = eng.start_session(["other"], max_new_tokens=4)  # evicts the first
    assert eng.sessions.get(sid).spilled
    r2 = eng.continue_session(sid, ["more"], max_new_tokens=4)

    cid, c1 = ctl.start_session(["mesh session"], max_new_tokens=4)
    c2 = ctl.continue_session(cid, ["more"], max_new_tokens=4)
    assert r1.text == c1.text
    assert np.array_equal(r2.tokens, c2.tokens)


def test_end_session_frees_state():
    eng = _engine()
    sid, _ = eng.start_session(["bye"], max_new_tokens=2)
    eng.end_session(sid)
    with pytest.raises(KeyError):
        eng.continue_session(sid, ["x"])
