"""Overload-safe serving (runtime/batcher.py + runtime/server.py, PR 3).

The acceptance contract pinned here: under pool exhaustion (real or
injected) at roughly twice the KV pool's token capacity of offered load,
every request either COMPLETES with temp-0 tokens identical to its solo run
(preempted rows resume via recompute) or is SHED with a structured 429/503
carrying Retry-After — never an engine_error, never a wedge — and the page
allocator audits clean afterward (``assert_pool_consistent``).

Mechanisms covered:
- on-demand page growth: admission takes prompt + one decode page; chunk
  boundaries grow rows as they actually reach new pages;
- preemption with recompute: a dry pool preempts the lowest-priority /
  most-recently-admitted row — pages freed now, emitted tokens kept, the
  request requeued to prefill prompt + emitted prefix (exact at temp 0);
- priority admission order and the strictly-lower-priority admission guard;
- queue-deadline shedding (batcher-side) and the server's cost gate /
  queue-full 429s with Retry-After;
- chunked prefill over the paged pool (pages allocated only at the finish);
- the _Mailbox leak class around front-door rejections;
- ServingClient's Retry-After-honoring jittered backoff.
"""

import asyncio
import json
import random
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llms_tpu.cluster.client import ServingClient
from distributed_llms_tpu.core.observability import METRICS
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
from distributed_llms_tpu.runtime.faults import FaultPlane, InjectedFault
from distributed_llms_tpu.runtime.server import InferenceServer
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new, eos_id=-1):
    out = gen_lib.generate_tokens(
        params, cfg, jnp.asarray([ids], jnp.int32),
        jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
        max_new_tokens=n_new, eos_id=eos_id, pad_id=0,
    )
    toks = np.asarray(out)[0].tolist()
    if eos_id >= 0 and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
    return toks


def _paged(cfg, params, **kw):
    kw.setdefault("batch_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("page_size", 16)
    kw.setdefault("paged_pages", 9)
    return ContinuousBatcher(cfg, params, **kw)


# -- on-demand growth -------------------------------------------------------


def test_admission_reserves_prompt_plus_one_page_only(tiny):
    """A long-budget request admits holding pages for its prompt plus one
    decode page — NOT its full prompt+budget footprint — and the growth
    loop adds the rest only as decode actually reaches them, with tokens
    identical to the fully-reserved run."""
    cfg, params = tiny
    b = _paged(cfg, params, batch_slots=1)
    grown0 = METRICS.get_counter("batcher.pages_grown")
    rid = b.submit([7, 1, 9, 2], max_new_tokens=44)  # full need: 3 pages
    b._admit_pending()
    assert len(b.rows[0].pages) == 2, "admission over-reserved"
    res = b.run()
    assert res[rid] == solo(cfg, params, [7, 1, 9, 2], 44)
    assert METRICS.get_counter("batcher.pages_grown") - grown0 >= 1
    b.assert_pool_consistent()
    assert sorted(b.free_pages) == list(range(1, 9))
    # The watermark view saw the growth.
    stats = b.pool.stats()
    assert stats["peak_held"] == 3 and stats["free_pages"] == 8


def test_growth_overcommit_preempts_and_stays_exact(tiny):
    """Three rows whose FULL footprints exceed the pool together admit
    anyway (on-demand), growth drains the pool, the loser is preempted and
    resumes via recompute — every token stream still equals its solo run,
    and the allocator audits clean."""
    cfg, params = tiny
    b = _paged(cfg, params)  # 8 usable pages; 3 rows x 3 full pages = 9
    reqs = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"rid {rid} diverged"
    assert b.preemptions >= 1
    b.assert_pool_consistent()
    assert sorted(b.free_pages) == list(range(1, 9))


def test_preempt_raise_drill_respawn_serves_exact(tiny):
    """A crash at the preemption decision point (batcher.preempt, fired
    just before a victim is evicted) propagates out of run(); the respawn
    replays the same overcommitted workload and every stream still equals
    its solo run."""
    cfg, params = tiny
    plane = FaultPlane.parse("batcher.preempt:raise@1")
    b = _paged(cfg, params, faults=plane)
    reqs = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]
    for ids, n in reqs:
        b.submit(ids, max_new_tokens=n)
    with pytest.raises(InjectedFault):
        b.run()  # overcommit forces a preemption; the drill crashes it
    assert plane.rules[0].fired == 1
    b2 = b.respawn()
    rids = [b2.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b2.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"rid {rid} diverged"
    assert b2.preemptions >= 1
    b2.assert_pool_consistent()


def test_preemption_streams_resume_without_duplicates(tiny):
    """Streamed deliveries across a preemption: the resumed row continues
    from where it left off — concatenated deliveries equal the final
    result, nothing re-delivers, and done fires exactly once per rid."""
    cfg, params = tiny
    b = _paged(cfg, params)
    reqs = [([7, 1, 9, 2], 44), ([4, 4, 4, 4], 44), ([9, 8, 7, 3], 44)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    streamed: dict[int, list[int]] = {rid: [] for rid in rids}
    dones: dict[int, int] = {rid: 0 for rid in rids}

    def cb(rid, toks, done, lps):
        streamed[rid].extend(toks)
        if done:
            dones[rid] += 1

    res = b.run(on_tokens=cb)
    assert b.preemptions >= 1
    for rid in rids:
        assert streamed[rid] == res[rid], f"rid {rid} stream diverged"
        assert dones[rid] == 1
    b.assert_pool_consistent()


# -- priority ---------------------------------------------------------------


def test_priority_orders_admission(tiny):
    cfg, params = tiny
    b = _paged(cfg, params, batch_slots=1)
    done_order = []
    r_lo = b.submit([1, 2, 3], max_new_tokens=4, priority=0)
    r_mid = b.submit([7, 7, 7], max_new_tokens=4, priority=1)
    r_hi = b.submit([4, 5, 6], max_new_tokens=4, priority=5)
    b.run(on_tokens=lambda rid, t, d, l: done_order.append(rid) if d else None)
    assert done_order == [r_hi, r_mid, r_lo]


def test_admission_never_preempts_equal_priority(tiny):
    """The admission path preempts only STRICTLY lower-priority victims:
    an injected dry pool with only same-priority residents back-pressures
    (PR 2's behavior) instead of livelocking two requests trading pages."""
    cfg, params = tiny
    plane = FaultPlane.parse("batcher.page_alloc/admit:exhaust@2")
    b = _paged(cfg, params, batch_slots=2, faults=plane)
    p0 = METRICS.get_counter("batcher.preemptions_total")
    r1 = b.submit([5, 5], max_new_tokens=4)
    r2 = b.submit([6, 6], max_new_tokens=4)  # admission 2 sees a dry pool
    res = b.run()
    assert plane.rules[0].fired == 1
    assert METRICS.get_counter("batcher.preemptions_total") == p0
    assert res[r1] == solo(cfg, params, [5, 5], 4)
    assert res[r2] == solo(cfg, params, [6, 6], 4)
    b.assert_pool_consistent()


def test_higher_priority_admission_preempts_lower(tiny):
    """A higher-priority arrival whose admission finds the pool dry evicts
    a lower-priority resident; the victim resumes later and both streams
    stay exact."""
    cfg, params = tiny
    plane = FaultPlane.parse("batcher.page_alloc/admit:exhaust@2")
    b = _paged(cfg, params, batch_slots=2, faults=plane)
    r_lo = b.submit([5, 5], max_new_tokens=24, priority=0)
    b._admit_pending()  # r_lo resident (page_alloc hit 1: not due)
    assert b.rows[0].rid == r_lo
    r_hi = b.submit([6, 6], max_new_tokens=4, priority=3)
    res = b.run()  # r_hi's admission (hit 2) sees a dry pool -> preempts
    assert b.preemptions >= 1
    assert res[r_lo] == solo(cfg, params, [5, 5], 24)
    assert res[r_hi] == solo(cfg, params, [6, 6], 4)
    b.assert_pool_consistent()


def test_finished_at_admission_row_is_never_a_victim(tiny):
    """A row that FINISHED at admission (max_new_tokens=1) still holds its
    rid and pages until the publish sweep — preempting it would requeue a
    completed request with a fresh 1-token budget and emit a second token
    past max_tokens.  A dry pool must back-pressure instead."""
    cfg, params = tiny
    plane = FaultPlane.parse("batcher.page_alloc/admit:exhaust@2")
    b = _paged(cfg, params, batch_slots=2, faults=plane)
    preempt0 = METRICS.get_counter("batcher.preemptions_total")
    r_one = b.submit([5, 5, 7], max_new_tokens=1)
    b._admit_pending()  # r_one admits AND finishes (hit 1: not due)
    assert b.rows[0].rid == r_one and not b.active[0] and b.rows[0].pages
    # Higher priority, so only the finished-row skip (not the
    # strictly-lower-priority guard) protects r_one from eviction when
    # this admission (hit 2) sees an injected dry pool.
    r_hi = b.submit([6, 6], max_new_tokens=4, priority=3)
    res = b.run()
    assert plane.rules[0].fired == 1
    assert METRICS.get_counter("batcher.preemptions_total") == preempt0
    assert res[r_one] == solo(cfg, params, [5, 5, 7], 1)
    assert len(res[r_one]) == 1, "completed request emitted extra tokens"
    assert res[r_hi] == solo(cfg, params, [6, 6], 4)
    b.assert_pool_consistent()


# -- queue-deadline shedding (batcher) --------------------------------------


def test_expired_queued_request_sheds_not_admits(tiny):
    cfg, params = tiny
    b = _paged(cfg, params, batch_slots=1)
    shed0 = METRICS.get_counter("batcher.shed_total")
    r1 = b.submit([1, 2, 3], max_new_tokens=8)
    r2 = b.submit([4, 5, 6], max_new_tokens=8,
                  deadline=time.perf_counter() - 0.5)
    dones = []
    res = b.run(on_tokens=lambda rid, t, d, l: dones.append(rid) if d else None)
    assert res[r2] == [] and b.shed[r2].startswith("queue deadline")
    assert r2 in dones  # the done delivery fired (servers key on it)
    assert len(res[r1]) == 8
    assert METRICS.get_counter("batcher.shed_total") - shed0 == 1
    b.assert_pool_consistent()


def test_expired_preempted_request_finishes_with_partial_not_shed(tiny):
    """A PREEMPTED request whose deadline lapses while requeued for
    recompute already streamed tokens — it must FINISH with that partial
    output (the serving layer reports finish_reason "timeout"), never be
    shed as never-worked-on: a shed claims a retry is safe, which would
    duplicate the delivered prefix."""
    cfg, params = tiny
    b = _paged(cfg, params, batch_slots=1)
    shed0 = METRICS.get_counter("batcher.shed_total")
    from distributed_llms_tpu.runtime.batcher import _Request

    rid = 7
    b._next_rid = rid + 1
    b.queue.append(_Request(
        rid, [5, 5, 9, 9, 11, 12], 10,
        deadline=time.perf_counter() - 0.1,
        resume_emitted=[9, 11, 12], resume_lps=[-0.1, -0.2, -0.3],
    ))
    dones = []
    b._on_tokens = lambda r, t, d, l: dones.append(r) if d else None
    b._shed_expired_queued()
    b._on_tokens = None
    assert b.results[rid] == [9, 11, 12]
    assert b.result_logprobs[rid] == [-0.1, -0.2, -0.3]
    assert rid not in b.shed, "partial-output request was shed"
    assert dones == [rid]
    assert METRICS.get_counter("batcher.shed_total") == shed0
    b.assert_pool_consistent()


def test_shed_decision_reads_the_injected_lockstep_clock(tiny):
    """The queue-deadline shed is a declared LOCKSTEP_DECISIONS surface
    (graftsync GS101): it reads the injected lockstep clock, never the
    wall clock, so mesh processes fed the same clock value shed
    identically.  Witness both directions: a deadline long expired by
    WALL time stays alive while the injected clock sits before it, and
    advancing the injected clock past a wall-clock-future deadline
    sheds."""
    cfg, params = tiny
    t = {"now": 0.0}
    b = _paged(cfg, params, batch_slots=1, clock=lambda: t["now"])
    r1 = b.submit([1, 2, 3], max_new_tokens=4,
                  deadline=time.perf_counter() - 0.5)  # wall: expired
    res = b.run()
    assert res[r1] == solo(cfg, params, [1, 2, 3], 4)
    assert r1 not in b.shed, "shed consulted the wall clock"
    r2 = b.submit([4, 5, 6], max_new_tokens=4,
                  deadline=time.perf_counter() + 3600.0)  # wall: far future
    t["now"] = time.perf_counter() + 7200.0
    res2 = b.run()
    assert res2[r2] == [] and b.shed[r2].startswith("queue deadline")
    b.assert_pool_consistent()


# -- chunked prefill over the paged pool ------------------------------------


def test_chunked_prefill_paged_matches_solo(tiny):
    """Chunked prefill now composes with paged KV: the prompt chunks into
    the pageless transient row, pages are allocated only at the finishing
    splice, and tokens equal the monolithic (and solo) run."""
    cfg, params = tiny
    long_p = list(np.random.RandomState(3).randint(1, 500, size=23))
    b = _paged(cfg, params, batch_slots=2, prefill_chunk=5)
    r_long = b.submit(long_p, max_new_tokens=6)
    r_short = b.submit([4, 4, 4], max_new_tokens=5)
    res = b.run()
    assert res[r_long] == solo(cfg, params, long_p, 6)
    assert res[r_short] == solo(cfg, params, [4, 4, 4], 5)
    b.assert_pool_consistent()
    assert sorted(b.free_pages) == list(range(1, 9))


def test_preemption_storm_during_chunked_prefill(tiny):
    """Preemption firing WHILE a chunked prefill is in flight: the
    prefilling slot holds no pool pages (nothing to corrupt), growth
    preempts a page-holding row instead, the prefill's own finish waits
    out the pressure, and everything ends exact with a clean audit."""
    cfg, params = tiny
    long_p = list(np.random.RandomState(4).randint(1, 500, size=24))
    plane = FaultPlane.parse("batcher.page_alloc/grow:exhaust@1")
    b = _paged(cfg, params, batch_slots=3, prefill_chunk=6, faults=plane)
    preempt0 = METRICS.get_counter("batcher.preemptions_total")
    r_a = b.submit([7, 1, 9, 2], max_new_tokens=40)
    r_b = b.submit([4, 4, 4, 4], max_new_tokens=40)
    r_long = b.submit(long_p, max_new_tokens=6, priority=2)
    res = b.run()
    assert plane.rules[0].fired == 1
    assert METRICS.get_counter("batcher.preemptions_total") > preempt0
    assert res[r_a] == solo(cfg, params, [7, 1, 9, 2], 40)
    assert res[r_b] == solo(cfg, params, [4, 4, 4, 4], 40)
    assert res[r_long] == solo(cfg, params, long_p, 6)
    b.assert_pool_consistent()
    assert sorted(b.free_pages) == list(range(1, 9))


# -- preemption vs the automatic prefix cache -------------------------------


SHARED = list(np.random.RandomState(7).randint(1, 500, size=40))


def test_preempted_row_holding_cached_prefix_pages(tiny):
    """A preempted victim may hold refcounted prefix-cache pages shared
    with a surviving row: preemption drops only the victim's references —
    the survivor keeps reading the shared pages, the resume re-hits the
    cache (recompute is cheap), and the allocator audits clean."""
    cfg, params = tiny
    shared16 = SHARED[:16]  # exactly one cacheable page
    b = _paged(cfg, params, batch_slots=2, paged_pages=16,
               prefix_cache=True)
    # Publish the shared prompt page once.
    r0 = b.submit(shared16 + [3], max_new_tokens=2)
    assert b.run()[r0] == solo(cfg, params, shared16 + [3], 2)
    pc = b.prefix_cache
    assert len(pc.lru) >= 1
    # Two hitting rows share the cached page and carry growth-needing
    # budgets (19-token prompt -> 3 initial pages, 4 at full depth);
    # force a growth-time preemption while both live.
    plane = FaultPlane.parse("batcher.page_alloc/grow:exhaust@1")
    b.faults = plane
    checked = {}
    r1 = b.submit(shared16 + [7, 1, 9], max_new_tokens=40)
    r2 = b.submit(shared16 + [4, 4, 2], max_new_tokens=40)

    def cb(rid, toks, done, lps):
        if b.preemptions and "at_preempt" not in checked:
            # The survivor still references the shared page: it must stay
            # refcounted (never freed) even though the victim released.
            shared_live = [p for p in pc.page_hash if p in b.page_refs]
            checked["at_preempt"] = bool(shared_live)
            b.assert_pool_consistent()

    res = b.run(on_tokens=cb)
    assert b.preemptions >= 1
    assert checked.get("at_preempt"), "no shared page survived preemption"
    assert res[r1] == solo(cfg, params, shared16 + [7, 1, 9], 40)
    assert res[r2] == solo(cfg, params, shared16 + [4, 4, 2], 40)
    b.assert_pool_consistent()


# -- HTTP plumbing helpers --------------------------------------------------


async def _request(host, port, method, path, body=None):
    """Raw request; returns (status, headers dict, body bytes)."""
    reader, writer = await asyncio.open_connection(host, port)
    payload = b"" if body is None else json.dumps(body).encode()
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    data = await reader.read()
    writer.close()
    return status, headers, data


def make_batcher(tiny, faults=None, **kw):
    cfg, params = tiny
    tok = ByteTokenizer()
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("paged_pages", 8)  # 7 usable = 112-token capacity
    kw.setdefault("page_size", 16)
    return ContinuousBatcher(
        cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
        faults=faults, **kw
    )


def run_with_server(batcher, fn, **srv_kw):
    async def driver():
        srv = InferenceServer(batcher, model_name="tiny", host="127.0.0.1",
                              port=0, **srv_kw)
        host, port = await srv.start()
        try:
            return await asyncio.wait_for(fn(host, port, srv), timeout=600)
        finally:
            await srv.stop()

    return asyncio.run(driver())


def expected_texts(tiny, reqs):
    """Reference texts from a roomy, un-faulted batcher (exactness is
    batching-invariant — pinned by the paged tests)."""
    b = make_batcher(tiny, paged_pages=40, batch_slots=4)
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    res = b.run()
    return {p: b.tokenizer.decode(res[rid])
            for rid, (p, n) in zip(rids, reqs)}


# -- THE overload acceptance test -------------------------------------------


def test_overload_storm_completes_or_sheds_structured(tiny):
    """~2x pool-capacity offered load + injected growth exhaustion: every
    request either completes with exact temp-0 text or sheds as 429/503
    with Retry-After and a structured overloaded_error — zero
    engine_error — and the pool audits clean after the storm."""
    prompts = [(f"storm request {i}", 40) for i in range(5)]
    wants = expected_texts(tiny, prompts)
    # Offered: 5 x ~(16 prompt + 40 new) ~ 280 tokens vs 112-token pool
    # capacity ~ 2.5x.  The grow-site exhaust forces one deterministic
    # preemption on top of the real pressure.
    plane = FaultPlane.parse("batcher.page_alloc/grow:exhaust@1")
    preempt0 = METRICS.get_counter("batcher.preemptions_total")

    async def fn(host, port, srv):
        outs = await asyncio.gather(*[
            _request(host, port, "POST", "/v1/completions",
                     {"prompt": p, "max_tokens": n,
                      "priority": (5 if i == 0 else 0)})
            for i, (p, n) in enumerate(prompts)
        ])
        completed, shed = 0, 0
        for (status, headers, raw), (p, n) in zip(outs, prompts):
            body = json.loads(raw)
            if status == 200:
                assert body["choices"][0]["finish_reason"] == "length", body
                assert body["choices"][0]["text"] == wants[p], p
                completed += 1
            else:
                # Structured shed: 429 (cost gate / queue full) or 503
                # (queue-deadline), always with Retry-After and an
                # overloaded_error type — never engine_error.
                assert status in (429, 503), (status, body)
                assert body["error"]["type"] == "overloaded_error", body
                assert int(headers["retry-after"]) >= 1
                shed += 1
        assert completed >= 1 and completed + shed == len(prompts)
        assert shed >= 1, "cost gate never shed at 2.5x offered load"
        assert METRICS.get_counter("batcher.preemptions_total") > preempt0
        # Pool integrity after the storm, once the engine drains.
        for _ in range(200):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        srv.batcher.assert_pool_consistent()
        # The occupancy view is exported on /metrics.
        _, _, raw = await _request(host, port, "GET", "/metrics")
        text = raw.decode()
        for fam in ("batcher_pool_free_pages", "batcher_pool_held_pages",
                    "batcher_pool_min_available", "batcher_preemptions_total",
                    "server_requests_shed_total"):
            assert fam in text, fam

    run_with_server(make_batcher(tiny, faults=plane), fn,
                    shed_cost_factor=1.0)


@pytest.mark.slow
def test_overload_storm_large_with_backoff(tiny):
    """Nightly-sized storm: 16 requests at >2x capacity through
    ServingClient's Retry-After backoff — with retries, goodput recovers
    (more requests complete than slots exist) and the audit stays clean."""
    prompts = [(f"big storm req {i:02d}", 32) for i in range(16)]
    wants = expected_texts(tiny, prompts)

    async def fn(host, port, srv):
        clients = [
            ServingClient(host, port, max_retries=8, backoff_base_s=0.05,
                          backoff_cap_s=0.4, retry_after_cap_s=0.2,
                          rng=random.Random(i))
            for i in range(len(prompts))
        ]
        outs = await asyncio.gather(*[
            c.completions({"prompt": p, "max_tokens": n})
            for c, (p, n) in zip(clients, prompts)
        ])
        completed = 0
        for (status, body), (p, n) in zip(outs, prompts):
            if status == 200:
                assert body["choices"][0]["text"] == wants[p], p
                completed += 1
            else:
                assert body["error"]["type"] == "overloaded_error", body
        assert completed > 4, f"only {completed} completed despite backoff"
        assert sum(c.retries_taken for c in clients) >= 1
        for _ in range(200):
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        srv.batcher.assert_pool_consistent()

    run_with_server(make_batcher(tiny), fn, shed_cost_factor=1.5)


# -- front-door gates, Retry-After, and the mailbox leak class --------------


def test_cost_gate_429_retry_after_and_no_mailbox_leak(tiny):
    async def fn(host, port, srv):
        shed0 = METRICS.get_counter("server.requests_shed_total")
        status, headers, raw = await _request(
            host, port, "POST", "/v1/completions",
            # 400-token budget vs 112-token capacity at factor 1.0.
            {"prompt": "too big to ever fit", "max_tokens": 400},
        )
        body = json.loads(raw)
        assert status == 429 and body["error"]["type"] == "overloaded_error"
        assert int(headers["retry-after"]) >= 1
        assert METRICS.get_counter("server.requests_shed_total") > shed0
        # Nothing pre-registered survived the shed: no mailbox, no queue
        # entry — the leak class this gate's ordering must never recreate.
        assert not srv._requests
        assert not srv.batcher.queue
        # A small request still serves.
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "small", "max_tokens": 4},
        )
        assert status == 200
        assert not srv._requests

    run_with_server(make_batcher(tiny), fn, shed_cost_factor=1.0)


def test_queue_full_429_retry_after_and_no_mailbox_leak(tiny):
    async def fn(host, port, srv):
        status, headers, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hello", "max_tokens": 4},
        )
        # max_pending=0: every request 429s at the queue-full gate.
        body = json.loads(raw)
        assert status == 429 and body["error"]["type"] == "overloaded_error"
        assert "queue is full" in body["error"]["message"]
        assert int(headers["retry-after"]) >= 1
        assert not srv._requests and not srv.batcher.queue

    run_with_server(make_batcher(tiny), fn, max_pending=0)


def test_submit_crash_does_not_strand_mailboxes(tiny):
    """A non-ValueError failure inside the registration/submit block
    (e.g. a broken batcher invariant) must not leave _Mailbox entries in
    _requests — each leaked entry permanently inflates the queue-full
    gate until a healthy server 429s everything."""
    async def fn(host, port, srv):
        orig = srv.batcher.submit

        def boom(*a, **kw):
            raise RuntimeError("batcher invariant violated")

        srv.batcher.submit = boom
        try:
            await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "doomed", "max_tokens": 4},
            )
        except (IndexError, ConnectionError, asyncio.IncompleteReadError):
            pass  # the handler died; a torn connection is acceptable
        # ... but it must have cleaned its registration.
        assert not srv._requests
        srv.batcher.submit = orig
        status, _, raw = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "fine", "max_tokens": 4},
        )
        assert status == 200
        assert not srv._requests

    run_with_server(make_batcher(tiny), fn)


def test_priority_field_validation(tiny):
    async def fn(host, port, srv):
        for bad in ("high", 1.5, True):
            status, _, raw = await _request(
                host, port, "POST", "/v1/completions",
                {"prompt": "x", "max_tokens": 2, "priority": bad},
            )
            assert status == 400, (bad, raw)
        status, _, _ = await _request(
            host, port, "POST", "/v1/completions",
            {"prompt": "x", "max_tokens": 2, "priority": -3},
        )
        assert status == 200

    run_with_server(make_batcher(tiny), fn)


def test_serving_client_backoff_honors_retry_after(tiny):
    """ServingClient retries a queue-full 429 with Retry-After-honoring
    jittered backoff and lands the request once the slot drains."""
    plane = FaultPlane.parse("batcher.decode:stall@1+:0.05")

    async def fn(host, port, srv):
        hog = asyncio.create_task(_request(
            host, port, "POST", "/v1/completions",
            {"prompt": "hog", "max_tokens": 48},
        ))
        for _ in range(500):
            if srv._requests:
                break
            await asyncio.sleep(0.01)
        assert srv._requests  # max_pending=1: the next request 429s
        client = ServingClient(host, port, max_retries=60,
                               backoff_base_s=0.02, backoff_cap_s=0.2,
                               retry_after_cap_s=0.1, rng=random.Random(1))
        status, body = await client.completions(
            {"prompt": "patient", "max_tokens": 4}
        )
        assert status == 200, body
        assert client.retries_taken >= 1
        await hog

    run_with_server(make_batcher(tiny, faults=plane), fn, max_pending=1)
