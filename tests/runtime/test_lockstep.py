"""Lockstep-determinism property test — the DYNAMIC witness for what
graftsync (tools/graftsync) checks statically.

A multi-process mesh takes every scheduling decision in lockstep: the
same queue + admission history must produce the SAME admission order,
victim choice, bite sizes, spec-round clamps, and sync-trigger lists in
every process, or SPMD dispatch deadlocks.  The sneakiest way to break
that is hash/set order: ``PYTHONHASHSEED`` differs per process unless
pinned, string hashes (tenant ids!) differ with it, and any decision
that leaks set-iteration order diverges even on identical state.

So the witness is run as SUBPROCESSES (the hash seed is fixed at
interpreter start and cannot be changed in-process): one fixed scenario
replayed under PYTHONHASHSEED=0 and PYTHONHASHSEED=1 must print
byte-identical decision traces.  The scenario leans on the surfaces
where hash order could plausibly leak — ``TenantScheduler``'s ``_live``
set and per-tenant buckets keyed by client-minted strings — plus the
mixed/spec hooks (bite sizing, victim selection, round clamps, sync
triggers) over fixed ``SyncView`` snapshots.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[2]

# The driver replays one deterministic scheduling scenario and prints the
# decision trace.  It runs in a fresh interpreter so PYTHONHASHSEED takes
# effect; anything nondeterministic in a decision shows up as a trace
# diff between seeds.
DRIVER = '''
import sys
from types import SimpleNamespace

from distributed_llms_tpu.runtime.scheduler import (
    MixedScheduler, SpecMixedScheduler, SyncView, TenantScheduler)


def req(rid, priority=0, tenant=None, prompt=4):
    return SimpleNamespace(rid=rid, priority=priority, tenant=tenant,
                           ids=[1] * prompt)


out = []

# -- tenant-fair admission: the surface most exposed to hash order -----
# (string tenant ids bucket into dicts and the _live set; the VTC lift
# reduces over live counters).  Tenant names chosen so their hashes --
# and therefore any leaked set order -- differ across PYTHONHASHSEED.
sched = TenantScheduler(tenant_weights={"gold": 4.0, "free": 1.0},
                        tenant_max_rows=2, token_budget=64)
queue = [
    req(1, 0, "gold"), req(2, 1, "free"), req(3, 0, "bronze"),
    req(4, 2, None), req(5, 0, "gold", 8), req(6, 1, "zinc"),
    req(7, 0, "free"), req(8, 3, "bronze"), req(9, 0, "iron"),
    req(10, 1, "gold"),
]
admitted = []
while queue:
    pick = sched.admission_order(queue)
    if pick is None:
        # Every backlogged tenant sits at its row cap: free the oldest
        # resident (chunk boundary) and retry -- also exercises the
        # true-up/refund path mid-scenario.
        r, emitted = admitted.pop(0)
        sched.note_freed(r, emitted)
        out.append(f"freed rid={r.rid}")
        continue
    queue.remove(pick)
    sched.note_admitted(pick, est_tokens=len(pick.ids) + 16)
    admitted.append((pick, 5))
    out.append(f"admit rid={pick.rid} tenant={pick.tenant}")
for r, emitted in admitted:
    sched.note_freed(r, emitted)
out.append("vtc " + ",".join(
    f"{t}={v:.4f}" for t, v in sorted(sched._vtc.items())))

# -- mixed policy hooks over fixed inputs ------------------------------
m = MixedScheduler(token_budget=32, chunk_steps=8)
for remaining, n_active in [(100, 0), (100, 4), (7, 31), (64, 32)]:
    out.append(f"bite {remaining},{n_active} -> "
               f"{m.prefill_bite(remaining, n_active)}")
cands = [(0, 1, 3), (1, 0, 5), (2, 0, 4), (3, 2, 1)]
out.append(f"victim -> {m.select_victim(cands)}")
out.append(f"victim<1 -> {m.select_victim(cands, below_priority=1)}")

s = SpecMixedScheduler(token_budget=24, speculative=True)
out.append(f"spec_k -> {s.spec_round_k(4, [1.0, 0.4, 0.75, 0.1], 3)}")

views = [
    SyncView(any_active=True, cancel_dirty=False, queued=True,
             kv_imports=False, prefills=1, head_prefill_left=0,
             live_budgets=(4, 9), chunks_ahead=1,
             grow_blocked=lambda: False),
    SyncView(any_active=True, cancel_dirty=False, queued=False,
             kv_imports=False, prefills=1, head_prefill_left=12,
             live_budgets=(40, 90), chunks_ahead=1,
             grow_blocked=lambda: True),
    SyncView(any_active=False, cancel_dirty=True, queued=False,
             kv_imports=True, prefills=0, head_prefill_left=0,
             live_budgets=(), chunks_ahead=0,
             grow_blocked=lambda: False),
]
for v in views:
    out.append("sync " + ",".join(m.sync_triggers(v)))

sys.stdout.write("\\n".join(out) + "\\n")
'''


def _trace(tmp_path: Path, hashseed: str) -> str:
    driver = tmp_path / "lockstep_driver.py"
    driver.write_text(DRIVER, encoding="utf-8")
    env = dict(os.environ,
               PYTHONHASHSEED=hashseed,
               PYTHONPATH=str(ROOT),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, str(driver)], capture_output=True,
                       text=True, env=env, cwd=ROOT, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_decision_traces_identical_across_hash_seeds(tmp_path):
    t0 = _trace(tmp_path, "0")
    t1 = _trace(tmp_path, "1")
    # The scenario actually ran (all ten admissions + the hook probes).
    assert t0.count("admit rid=") == 10
    assert "sync " in t0 and "spec_k" in t0
    # THE property: different hash seeds, byte-identical decisions.
    assert t0 == t1, (
        "scheduling decisions diverged under PYTHONHASHSEED skew -- a "
        "hash/set-order dependency leaked onto the lockstep decision "
        "path:\n--- seed 0 ---\n" + t0 + "--- seed 1 ---\n" + t1
    )


def test_trace_is_stable_within_a_seed(tmp_path):
    """Same seed twice -> same trace: the scenario itself carries no
    incidental nondeterminism (so a cross-seed diff above can only mean
    a hash-order leak, not a flaky driver)."""
    assert _trace(tmp_path, "0") == _trace(tmp_path, "0")
