"""Decode-loop tests: greedy generation matches repeated full forwards,
ragged batches are handled per-row, EOS freezes rows, samplers behave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.models import model, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime import sampling
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer, pad_batch
from distributed_llms_tpu.runtime.engine import InferenceEngine
from distributed_llms_tpu.core.config import RuntimeConfig


@pytest.fixture(scope="module", params=["gpt2-tiny", "llama-tiny"])
def setup(request):
    cfg = presets.get_preset(request.param)
    params = model.init_params(jax.random.key(0), cfg)
    return cfg, params


def _reference_greedy(params, cfg, prompt_row, n_new):
    """Greedy decode by repeated FULL forward passes (no cache) — slow but
    trivially correct oracle."""
    toks = list(np.asarray(prompt_row))
    for _ in range(n_new):
        logits, _ = model.forward(params, cfg, jnp.asarray([toks], dtype=jnp.int32))
        toks.append(int(np.asarray(logits)[0, -1].argmax()))
    return toks[len(prompt_row):]


def test_greedy_matches_full_forward_oracle(setup):
    cfg, params = setup
    prompt = jnp.array([[5, 23, 90, 3]], dtype=jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, prompt, jnp.array([4], dtype=jnp.int32), jax.random.key(0),
        max_new_tokens=6,
    )
    ref = _reference_greedy(params, cfg, prompt[0], 6)
    assert np.asarray(out)[0].tolist() == ref


def test_ragged_batch_matches_single_rows(setup):
    """Each row of a ragged batch must decode exactly as it would alone."""
    cfg, params = setup
    rows = [[7, 1, 9], [4, 4, 4, 4, 4, 4], [100]]
    arr, lens = pad_batch(rows, pad_id=0)
    out = gen_lib.generate_tokens(
        params, cfg, jnp.asarray(arr), jnp.asarray(lens), jax.random.key(0),
        max_new_tokens=5,
    )
    out = np.asarray(out)
    for i, row in enumerate(rows):
        single = gen_lib.generate_tokens(
            params, cfg, jnp.asarray([row], dtype=jnp.int32),
            jnp.array([len(row)], dtype=jnp.int32), jax.random.key(0),
            max_new_tokens=5,
        )
        assert out[i].tolist() == np.asarray(single)[0].tolist(), f"row {i} diverged"


def test_eos_freezes_row(setup):
    cfg, params = setup
    prompt = jnp.array([[5, 23, 90, 3]], dtype=jnp.int32)
    lens = jnp.array([4], dtype=jnp.int32)
    free = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(0), max_new_tokens=6
    )
    eos = int(np.asarray(free)[0, 2])  # force the 3rd generated token to be EOS
    out = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(0), max_new_tokens=6,
        eos_id=eos, pad_id=0,
    )
    row = np.asarray(out)[0]
    eos_pos = row.tolist().index(eos)
    assert all(t == 0 for t in row[eos_pos + 1 :]), row


def test_sampling_temperature_zero_is_greedy():
    logits = jnp.array([[0.1, 3.0, -1.0], [2.0, 1.0, 0.0]])
    out = sampling.sample(jax.random.key(0), logits, temperature=0.0)
    assert out.tolist() == [1, 0]


def test_top_k_restricts_support():
    logits = jnp.array([[0.0, 1.0, 2.0, 3.0]])
    counts = set()
    for i in range(50):
        t = sampling.sample(jax.random.key(i), logits, temperature=1.0, top_k=2)
        counts.add(int(t[0]))
    assert counts <= {2, 3} and len(counts) == 2


def test_top_p_keeps_top1_at_low_p():
    logits = jnp.array([[0.0, 5.0, 1.0]])
    for i in range(20):
        t = sampling.sample(jax.random.key(i), logits, temperature=1.0, top_p=0.1)
        assert int(t[0]) == 1


def test_engine_end_to_end_bytes():
    eng = InferenceEngine.from_preset(
        "gpt2-tiny", RuntimeConfig(max_decode_steps=8), vocab_size=ByteTokenizer.vocab_size
    )
    res = eng.generate_text(["hello", "hi"], max_new_tokens=8)
    assert len(res.text) == 2
    assert res.tokens.shape == (2, 8)
    assert res.tokens_per_second > 0


def test_engine_rejects_vocab_mismatch():
    """Tokenizer ids beyond model vocab would NaN-fill embeddings; the
    engine must reject the pairing loudly."""
    with pytest.raises(ValueError, match="vocab"):
        InferenceEngine.from_preset("gpt2-tiny", RuntimeConfig())  # vocab 256 < 259


def test_top_p_keeps_nucleus_not_just_top1():
    """Regression: top-p cutoff must be the *min* kept logit — with p=0.9 the
    nucleus {3,2,1} of [[0,1,2,3]] should all be sampleable."""
    logits = jnp.log(jnp.array([[0.05, 0.15, 0.3, 0.5]]))
    seen = set()
    for i in range(120):
        t = sampling.sample(jax.random.key(i), logits, temperature=1.0, top_p=0.9)
        seen.add(int(t[0]))
    assert seen == {1, 2, 3}, seen
