"""Training checkpoint/resume (SURVEY §5.4: absent in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig, ModelConfig
from distributed_llms_tpu.models import model as model_lib
from distributed_llms_tpu.models.presets import get_preset
from distributed_llms_tpu.parallel.api import make_parallel_model
from distributed_llms_tpu.runtime import train, train_ckpt


def _setup(parallel=None):
    cfg = get_preset("llama-tiny")
    params = model_lib.init_params(jax.random.key(0), cfg)
    trainer = train.Trainer(cfg, train.default_optimizer(1e-2), parallel=parallel)
    if parallel is not None:
        params = parallel.shard_params(params)
    return cfg, params, trainer


def _tokens(cfg, key=1, batch=4):
    return jax.random.randint(
        jax.random.key(key), (batch, 17), 0, cfg.vocab_size, dtype=jnp.int32
    )


def test_save_restore_roundtrip(tmp_path):
    cfg, params, trainer = _setup()
    opt_state = trainer.init(params)
    step_fn = trainer.make_step()
    toks = _tokens(cfg)
    params, opt_state, _ = step_fn(params, opt_state, toks, None)

    train_ckpt.save_train_state(str(tmp_path), 1, params, opt_state)
    assert train_ckpt.latest_step(str(tmp_path)) == 1
    step, p2, o2 = train_ckpt.restore_train_state(str(tmp_path))
    assert step == 1
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed training continues bit-identically vs uninterrupted training
    toks2 = _tokens(cfg, key=2)
    _, _, loss_resumed = step_fn(p2, o2, toks2, None)
    _, _, loss_cont = step_fn(params, opt_state, toks2, None)
    np.testing.assert_allclose(float(loss_resumed), float(loss_cont), rtol=1e-6)


def test_restore_onto_mesh_shardings(tmp_path):
    """Resume lands on the live mesh: restored arrays adopt the template's
    NamedShardings (device_put on boot, SURVEY §5.4)."""
    pm = make_parallel_model(
        get_preset("llama-tiny"), MeshConfig(data=2, model=2),
        devices=jax.devices()[:4],
    )
    cfg, params, trainer = _setup(parallel=pm)
    opt_state = trainer.init(params)
    train_ckpt.save_train_state(str(tmp_path), 7, params, opt_state)

    template = {"step": 0, "params": params, "opt_state": opt_state}
    step, p2, o2 = train_ckpt.restore_train_state(str(tmp_path), template=template)
    assert step == 7
    want = params["blocks"]["attn"]["wq"].sharding
    got = p2["blocks"]["attn"]["wq"].sharding
    assert got == want
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_keep_prunes_old_checkpoints(tmp_path):
    cfg, params, trainer = _setup()
    opt_state = trainer.init(params)
    for s in range(5):
        train_ckpt.save_train_state(str(tmp_path), s, params, opt_state, keep=2)
    names = train_ckpt.list_checkpoints(str(tmp_path))
    assert names == ["step_00000003", "step_00000004"]


def test_restore_missing_errors(tmp_path):
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        train_ckpt.restore_train_state(str(tmp_path))
