"""Continuous batching (runtime/batcher.py).

Core invariant: scheduling must never change results — at temperature 0,
every request's tokens equal a solo run of runtime.generate.generate_tokens
on that request, regardless of admission order, slot reuse, or which other
requests share the batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new, eos_id=-1):
    arr = jnp.asarray([ids], jnp.int32)
    lens = jnp.asarray([len(ids)], jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, arr, lens, jax.random.key(9), max_new_tokens=n_new,
        eos_id=eos_id, pad_id=0,
    )
    toks = np.asarray(out)[0].tolist()
    if eos_id >= 0 and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
        # generate pads after EOS; the batcher stops emitting there.
    elif eos_id >= 0:
        pass
    return toks


def test_single_request_matches_solo_generate(tiny):
    cfg, params = tiny
    ids = [7, 1, 9, 4]
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=4)
    rid = b.submit(ids, max_new_tokens=10)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 10)


def test_mixed_lengths_all_match_solo(tiny):
    """Requests of different prompt lengths and budgets, more requests than
    slots — forcing slot reuse mid-flight — all match their solo runs."""
    cfg, params = tiny
    reqs = [
        ([7, 1, 9], 6),
        ([4, 4, 4, 4, 4, 4], 12),
        ([100, 3, 5, 2], 3),
        ([9, 8, 7, 6, 5], 9),
        ([11, 12], 15),
        ([200, 201, 202, 203, 204, 205, 206], 5),
        ([42], 8),
    ]
    b = ContinuousBatcher(cfg, params, batch_slots=3, max_len=64, chunk_steps=4)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"request {rid} diverged"


def test_budget_one_token(tiny):
    cfg, params = tiny
    ids = [5, 6, 7]
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=32, chunk_steps=4)
    rid = b.submit(ids, max_new_tokens=1)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 1)


def test_eos_frees_slot_early(tiny):
    """Pick an EOS id the model actually emits (from a probe run); the row
    must stop at EOS and the published result must end there."""
    cfg, params = tiny
    ids = [3, 14, 15]
    probe = solo(cfg, params, ids, 12)
    eos = probe[2]  # force an early stop at the 3rd generated token
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=5, eos_id=eos
    )
    rid = b.submit(ids, max_new_tokens=12)
    other = b.submit([8, 8, 8, 8], max_new_tokens=12)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 12, eos_id=eos)
    assert res[rid][-1] == eos and len(res[rid]) <= 4
    assert res[other] == solo(cfg, params, [8, 8, 8, 8], 12, eos_id=eos)


def test_late_submission_joins_inflight_batch(tiny):
    """A request submitted while others are mid-decode is admitted into a
    freed slot and still matches its solo run."""
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=3)
    r1 = b.submit([7, 1, 9], max_new_tokens=4)
    r2 = b.submit([4, 4, 4, 4], max_new_tokens=13)
    # Drive a couple of chunks manually, then inject a new request.
    b._admit_pending()
    was = np.asarray(b.active)
    toks, b.cache, b.last_tok, b.real_lens, b.valid, b.active, b.budget = (
        __import__(
            "distributed_llms_tpu.runtime.batcher", fromlist=["decode_chunk"]
        ).decode_chunk(
            b.params, b.cfg, b.cache, b.last_tok, b.real_lens, b.valid,
            b.active, b.budget, b._split_rng(), b.chunk_steps,
            eos_id=b.eos_id, pad_id=b.pad_id, **b.sampling,
        )
    )
    b._collect(np.asarray(toks), was)
    r3 = b.submit([9, 9, 1], max_new_tokens=6)
    res = b.run()
    assert res[r1] == solo(cfg, params, [7, 1, 9], 4)
    assert res[r2] == solo(cfg, params, [4, 4, 4, 4], 13)
    assert res[r3] == solo(cfg, params, [9, 9, 1], 6)


def test_submit_rejects_oversized(tiny):
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds"):
        b.submit(list(range(10)), max_new_tokens=10)


def test_engine_integration(tiny):
    """engine.continuous_batcher wires tokenizer + sampling config; text
    prompts round-trip through the byte tokenizer."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg, params = tiny
    eng = InferenceEngine(cfg, RuntimeConfig(max_seq_len=64), params)
    b = eng.continuous_batcher(batch_slots=2, chunk_steps=4)
    rid = b.submit("hi", max_new_tokens=6)
    res = b.run()
    ids = eng.tokenizer.encode("hi")
    assert res[rid] == solo(cfg, params, ids, 6)

    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model

    pm = make_parallel_model(cfg, MeshConfig(data=2, model=4))
    mesh_eng = InferenceEngine(cfg, RuntimeConfig(), params, parallel=pm)
    with pytest.raises(ValueError, match="single-device"):
        mesh_eng.continuous_batcher()
