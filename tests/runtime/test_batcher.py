"""Continuous batching (runtime/batcher.py).

Core invariant: scheduling must never change results — at temperature 0,
every request's tokens equal a solo run of runtime.generate.generate_tokens
on that request, regardless of admission order, slot reuse, or which other
requests share the batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.batcher import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def solo(cfg, params, ids, n_new, eos_id=-1):
    arr = jnp.asarray([ids], jnp.int32)
    lens = jnp.asarray([len(ids)], jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, arr, lens, jax.random.key(9), max_new_tokens=n_new,
        eos_id=eos_id, pad_id=0,
    )
    toks = np.asarray(out)[0].tolist()
    if eos_id >= 0 and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
        # generate pads after EOS; the batcher stops emitting there.
    elif eos_id >= 0:
        pass
    return toks


def test_single_request_matches_solo_generate(tiny):
    cfg, params = tiny
    ids = [7, 1, 9, 4]
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=4)
    rid = b.submit(ids, max_new_tokens=10)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 10)


def test_mixed_lengths_all_match_solo(tiny):
    """Requests of different prompt lengths and budgets, more requests than
    slots — forcing slot reuse mid-flight — all match their solo runs."""
    cfg, params = tiny
    reqs = [
        ([7, 1, 9], 6),
        ([4, 4, 4, 4, 4, 4], 12),
        ([100, 3, 5, 2], 3),
        ([9, 8, 7, 6, 5], 9),
        ([11, 12], 15),
        ([200, 201, 202, 203, 204, 205, 206], 5),
        ([42], 8),
    ]
    b = ContinuousBatcher(cfg, params, batch_slots=3, max_len=64, chunk_steps=4)
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        assert res[rid] == solo(cfg, params, ids, n), f"request {rid} diverged"


def test_budget_one_token(tiny):
    cfg, params = tiny
    ids = [5, 6, 7]
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=32, chunk_steps=4)
    rid = b.submit(ids, max_new_tokens=1)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 1)


def test_eos_frees_slot_early(tiny):
    """Pick an EOS id the model actually emits (from a probe run); the row
    must stop at EOS and the published result must end there."""
    cfg, params = tiny
    ids = [3, 14, 15]
    probe = solo(cfg, params, ids, 12)
    eos = probe[2]  # force an early stop at the 3rd generated token
    b = ContinuousBatcher(
        cfg, params, batch_slots=2, max_len=64, chunk_steps=5, eos_id=eos
    )
    rid = b.submit(ids, max_new_tokens=12)
    other = b.submit([8, 8, 8, 8], max_new_tokens=12)
    res = b.run()
    assert res[rid] == solo(cfg, params, ids, 12, eos_id=eos)
    assert res[rid][-1] == eos and len(res[rid]) <= 4
    assert res[other] == solo(cfg, params, [8, 8, 8, 8], 12, eos_id=eos)


def test_late_submission_joins_inflight_batch(tiny):
    """A request submitted while others are mid-decode is admitted into a
    freed slot and still matches its solo run."""
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=3)
    r1 = b.submit([7, 1, 9], max_new_tokens=4)
    r2 = b.submit([4, 4, 4, 4], max_new_tokens=13)
    # Drive a couple of chunks manually, then inject a new request.
    b._admit_pending()
    was = b.active.copy()
    toks, b.cache, last_tok, real_lens, valid, active, budget, *_aux = (
        __import__(
            "distributed_llms_tpu.runtime.batcher", fromlist=["decode_chunk"]
        ).decode_chunk(
            b.params, b.cfg, b.cache, b.last_tok, b.real_lens, b.valid,
            b.active, b.budget, b._split_rng(), b.chunk_steps,
            eos_id=b.eos_id, pad_id=b.pad_id, **b.sampling,
        )
    )
    # State mirrors are host numpy (writable) — same conversion run() does.
    b.last_tok, b.real_lens, b.valid, b.active, b.budget = (
        np.array(last_tok), np.array(real_lens), np.array(valid),
        np.array(active), np.array(budget),
    )
    b._collect(np.asarray(toks), was)
    r3 = b.submit([9, 9, 1], max_new_tokens=6)
    res = b.run()
    assert res[r1] == solo(cfg, params, [7, 1, 9], 4)
    assert res[r2] == solo(cfg, params, [4, 4, 4, 4], 13)
    assert res[r3] == solo(cfg, params, [9, 9, 1], 6)


@pytest.mark.parametrize("seed", [0, 1])
def test_random_workload_property(tiny, seed):
    """Seeded stress: random prompt lengths, budgets, slot counts, and
    chunk sizes — every request must still match its solo run exactly."""
    rng = np.random.RandomState(seed)
    cfg, params = tiny
    slots = int(rng.randint(1, 5))
    chunk = int(rng.randint(2, 7))
    b = ContinuousBatcher(
        cfg, params, batch_slots=slots, max_len=64, chunk_steps=chunk
    )
    reqs = []
    for _ in range(int(rng.randint(3, 9))):
        n_prompt = int(rng.randint(1, 20))
        ids = rng.randint(1, 500, size=n_prompt).tolist()
        budget = int(rng.randint(1, 64 - n_prompt))
        reqs.append((b.submit(ids, max_new_tokens=budget), ids, budget))
    res = b.run()
    for rid, ids, budget in reqs:
        assert res[rid] == solo(cfg, params, ids, budget), (
            f"seed={seed} slots={slots} chunk={chunk} ids={ids} budget={budget}"
        )


def test_submit_rejects_oversized(tiny):
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds"):
        b.submit(list(range(10)), max_new_tokens=10)
    with pytest.raises(ValueError, match="empty prompt"):
        b.submit([], max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        b.submit([1, 2], max_new_tokens=0)


def test_quantized_params_match_quantized_solo(tiny):
    """Continuous batching over weight-only quantized params: per-request
    tokens equal the solo run with the same quantized tree."""
    from distributed_llms_tpu.checkpoint import quantize as quant_lib

    cfg, params = tiny
    qparams = {**params, "blocks": quant_lib.quantize_tree(params["blocks"], bits=8)}
    b = ContinuousBatcher(cfg, qparams, batch_slots=2, max_len=64, chunk_steps=4)
    r1 = b.submit([7, 1, 9], max_new_tokens=6)
    r2 = b.submit([4, 4, 4, 4, 4], max_new_tokens=9)
    res = b.run()
    assert res[r1] == solo(cfg, qparams, [7, 1, 9], 6)
    assert res[r2] == solo(cfg, qparams, [4, 4, 4, 4, 4], 9)


def test_prefix_cached_requests_match_concatenated_solo(tiny):
    """Prefix caching: requests sharing a registered prefix must produce
    exactly the tokens of a solo run on prefix+suffix — the prefix KV is
    computed once and reused, never recomputed per request."""
    cfg, params = tiny
    prefix = [50, 51, 52, 53, 54, 55, 56]
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=4)
    b.register_prefix("sys", prefix)
    suffixes = [[7, 1, 9], [4, 4], [9, 8, 7, 6]]
    rids = [b.submit(sfx, max_new_tokens=6, prefix="sys") for sfx in suffixes]
    plain = b.submit([3, 3, 3], max_new_tokens=5)  # no prefix, same batch
    res = b.run()
    for rid, sfx in zip(rids, suffixes):
        assert res[rid] == solo(cfg, params, prefix + sfx, 6), f"suffix {sfx}"
    assert res[plain] == solo(cfg, params, [3, 3, 3], 5)


def test_long_prefix_short_suffix_bucket_does_not_overflow(tiny):
    """A long prefix leaves less room than the suffix's bucket size: the
    admission must clamp the bucket (forward's cache_index+T contract), not
    silently clamp the cache write and corrupt the row."""
    cfg, params = tiny
    prefix = list(range(100, 150))  # 50 tokens in a 64-slot cache
    b = ContinuousBatcher(cfg, params, batch_slots=1, max_len=64, chunk_steps=4)
    b.register_prefix("long", prefix)
    sfx = [7, 1, 9, 4, 2, 8, 6, 5, 3, 11]  # 10 tokens; bucket(10)=16 > 64-50
    rid = b.submit(sfx, max_new_tokens=4, prefix="long")
    res = b.run()
    assert res[rid] == solo(cfg, params, prefix + sfx, 4)


def test_prefix_errors(tiny):
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=1, max_len=32)
    with pytest.raises(KeyError, match="unknown prefix"):
        b.submit([1, 2], prefix="nope")
    with pytest.raises(ValueError, match="does not fit"):
        b.register_prefix("big", list(range(40)))
    b.register_prefix("sys", [5, 6, 7])
    with pytest.raises(ValueError, match="exceeds"):
        b.submit(list(range(20)), max_new_tokens=20, prefix="sys")


def test_engine_integration(tiny):
    """engine.continuous_batcher wires tokenizer + sampling config; text
    prompts round-trip through the byte tokenizer."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg, params = tiny
    eng = InferenceEngine(cfg, RuntimeConfig(max_seq_len=64), params)
    b = eng.continuous_batcher(batch_slots=2, chunk_steps=4)
    rid = b.submit("hi", max_new_tokens=6)
    res = b.run()
    ids = eng.tokenizer.encode("hi")
    assert res[rid] == solo(cfg, params, ids, 6)

    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model

    # GSPMD dp/tp meshes get a mesh-capable batcher since round 4
    # (tests/parallel/test_mesh_batcher.py); only pipelined / seq-parallel
    # meshes — which bring their own decode schedules — are rejected.
    pm = make_parallel_model(cfg, MeshConfig(data=2, model=4))
    mesh_eng = InferenceEngine(cfg, RuntimeConfig(), params, parallel=pm)
    mb = mesh_eng.continuous_batcher(batch_slots=2)
    # The engine's kv_cache_dtype is threaded onto the (frozen) mesh model —
    # same mesh, explicit kv dtype, never silently dropped.
    assert mb.pm.mesh is pm.mesh
    assert mb.pm.kv_dtype == RuntimeConfig().kv_cache_dtype
    assert mb.cache.k.dtype == jnp.bfloat16
    pm_pipe = make_parallel_model(cfg, MeshConfig(pipe=2, model=4))
    pipe_eng = InferenceEngine(cfg, RuntimeConfig(), params, parallel=pm_pipe)
    with pytest.raises(ValueError, match="data/tensor-parallel"):
        pipe_eng.continuous_batcher()


def test_streaming_deliveries_reassemble_results(tiny):
    """run(on_tokens=...): per-rid concatenation of streamed chunks equals
    the returned result, with exactly one done=True as the LAST delivery —
    across mixed budgets, EOS stops, and slot reuse."""
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          chunk_steps=4)
    reqs = [([7, 1, 9, 4, 2], 9), ([4, 4, 4], 1), ([11, 12], 12), ([42], 5)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    streamed: dict[int, list[int]] = {r: [] for r in rids}
    done_flags: dict[int, list[bool]] = {r: [] for r in rids}

    def on_tokens(rid, new, done, lps):
        assert not done_flags[rid] or not done_flags[rid][-1], \
            f"delivery after done for rid {rid}"
        streamed[rid].extend(new)
        done_flags[rid].append(done)

    res = b.run(on_tokens=on_tokens)
    for r in rids:
        assert streamed[r] == res[r], (r, streamed[r], res[r])
        assert done_flags[r].count(True) == 1 and done_flags[r][-1]
    # A later run() without a callback must not stream to the stale one.
    before = {r: list(v) for r, v in streamed.items()}
    rid2 = b.submit([9, 9], max_new_tokens=3)
    res2 = b.run()
    assert streamed == before and rid2 in res2


def test_streaming_callback_exception_no_duplicate_done(tiny):
    """A raising callback aborts the run, but state advances BEFORE each
    delivery: a later run() never re-delivers tokens or a second done."""
    cfg, params = tiny
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          chunk_steps=4)
    rids = [b.submit([7, 1, 9], max_new_tokens=6),
            b.submit([4, 4], max_new_tokens=6)]
    seen: list[tuple[int, tuple[int, ...], bool]] = []

    class Boom(RuntimeError):
        pass

    def raising(rid, new, done, lps):
        seen.append((rid, tuple(new), done))
        if done:
            raise Boom()

    import pytest as _pytest
    with _pytest.raises(Boom):
        b.run(on_tokens=raising)
    collect = {r: [] for r in rids}
    dones = {r: 0 for r in rids}
    res = b.run(on_tokens=lambda rid, new, done, lps: (
        collect[rid].extend(new), dones.__setitem__(rid, dones[rid] + bool(done))
    ))
    # Reassemble: pre-crash deliveries + post-crash deliveries == result.
    full = {r: [] for r in rids}
    total_dones = {r: 0 for r in rids}
    for rid, new, done in seen:
        full[rid].extend(new)
        total_dones[rid] += bool(done)
    for r in rids:
        full[r].extend(collect[r])
        total_dones[r] += dones[r]
        assert full[r] == res[r], (r, full[r], res[r])
        assert total_dones[r] == 1, (r, total_dones[r])
