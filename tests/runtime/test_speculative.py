"""Speculative decoding (runtime/speculative.py).

Core invariant: greedy speculative decode emits EXACTLY the tokens of
``generate.generate_tokens(..., temperature=0.0)`` on the target model alone
— for ANY draft model and any k.  The draft only changes speed (acceptance),
never results.  A deliberately different-seed draft exercises the rejection
path hard; draft == target exercises full acceptance (a == k every round).
"""

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime import generate as gen_lib
from distributed_llms_tpu.runtime.speculative import speculative_generate_tokens

# XLA:CPU nondeterministically SEGFAULTS compiling the speculative
# while_loop programs (two model scans inlined into one loop) — but only in
# a process that has already run ~150+ other tests (5/5 full-suite runs
# crashed on 2026-07-31, on five different members of the family —
# int4-draft, engine-level, lax.map-batched — and at three different
# stages: backend_compile_and_load, persistent-cache serialize, and
# deserialize; every fresh-process run passes).  The ENTIRE speculative
# test family therefore runs in a FRESH subprocess via test_isolated.py
# and is skipped in the main process.  This is an XLA:CPU compiler
# robustness issue, not a product bug: TPU uses a different compiler.
pytestmark = pytest.mark.fragile_xla_cpu  # shared marker: tests/conftest.py


@pytest.fixture(scope="module")
def pair():
    tcfg = presets.get_preset("llama-tiny", vocab_size=512)
    tparams = model_lib.init_params(jax.random.key(0), tcfg)
    dcfg = presets.get_preset("llama-tiny", vocab_size=512, num_layers=2)
    dparams = model_lib.init_params(jax.random.key(99), dcfg)  # unrelated
    return tcfg, tparams, dcfg, dparams


def ref_greedy(tcfg, tparams, prompt, lens, n, eos_id=-1):
    out = gen_lib.generate_tokens(
        tparams, tcfg, prompt, lens, jax.random.key(7), max_new_tokens=n,
        temperature=0.0, eos_id=eos_id, pad_id=0,
    )
    return np.asarray(out)


@pytest.mark.parametrize("k", [1, 3, 7])
def test_exact_match_any_draft(pair, k):
    tcfg, tparams, dcfg, dparams = pair
    prompt = jnp.asarray([[7, 1, 9, 4, 0, 0], [11, 12, 13, 14, 15, 16]],
                         jnp.int32)
    lens = jnp.asarray([4, 6], jnp.int32)
    want = ref_greedy(tcfg, tparams, prompt, lens, 13)
    got = speculative_generate_tokens(
        tparams, tcfg, dparams, dcfg, prompt, lens, k=k, max_new_tokens=13,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


@pytest.mark.parametrize("n,k", [(12, 3), (8, 4), (5, 7)])
def test_self_draft_full_acceptance(pair, n, k):
    """Draft == target: every in-play draft agrees, so rounds ==
    ceil((n-1)/(k+1)) and acceptance is exactly 100% for ANY n, k —
    `drafted` is budget-aware (min(k, remaining) per round), so a mid-round
    budget clamp must not read as a rejection."""
    tcfg, tparams, _, _ = pair
    prompt = jnp.asarray([[3, 5, 8]], jnp.int32)
    lens = jnp.asarray([3], jnp.int32)
    want = ref_greedy(tcfg, tparams, prompt, lens, n)
    got, stats = speculative_generate_tokens(
        tparams, tcfg, tparams, tcfg, prompt, lens, k=k, max_new_tokens=n,
        return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    rounds = int(stats["rounds"])
    # tok0 comes from prefill; each round then commits up to k+1 tokens.
    assert rounds == -(-(n - 1) // (k + 1)), rounds
    assert int(stats["accepted"]) == int(stats["drafted"]) > 0


def test_eos_freeze_matches_reference(pair):
    """Pick an EOS id that actually occurs in the reference output; rows must
    emit it then pad, exactly like generate_tokens."""
    tcfg, tparams, dcfg, dparams = pair
    prompt = jnp.asarray([[7, 1, 9, 4], [2, 2, 2, 2]], jnp.int32)
    lens = jnp.asarray([4, 4], jnp.int32)
    free = ref_greedy(tcfg, tparams, prompt, lens, 12)
    eos_id = int(free[0, 4])  # forces an early stop mid-round for row 0
    want = ref_greedy(tcfg, tparams, prompt, lens, 12, eos_id=eos_id)
    got = speculative_generate_tokens(
        tparams, tcfg, dparams, dcfg, prompt, lens, k=4, max_new_tokens=12,
        eos_id=eos_id,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_eos_on_first_token(pair):
    tcfg, tparams, dcfg, dparams = pair
    prompt = jnp.asarray([[7, 1, 9, 4]], jnp.int32)
    lens = jnp.asarray([4], jnp.int32)
    free = ref_greedy(tcfg, tparams, prompt, lens, 6)
    eos_id = int(free[0, 0])
    got = speculative_generate_tokens(
        tparams, tcfg, dparams, dcfg, prompt, lens, k=3, max_new_tokens=6,
        eos_id=eos_id,
    )
    assert np.asarray(got)[0].tolist() == [eos_id, 0, 0, 0, 0, 0]


def test_budget_not_exceeded_and_stats(pair):
    tcfg, tparams, dcfg, dparams = pair
    prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
    lens = jnp.asarray([3], jnp.int32)
    got, stats = speculative_generate_tokens(
        tparams, tcfg, dparams, dcfg, prompt, lens, k=5, max_new_tokens=4,
        return_stats=True,
    )
    assert np.asarray(got).shape == (1, 4)
    want = ref_greedy(tcfg, tparams, prompt, lens, 4)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert int(stats["rounds"]) >= 1
    assert 0 <= int(stats["accepted"]) <= int(stats["drafted"])


def test_windowed_target_exact(pair):
    """Sliding-window target (Mistral-style): the per-row masks AND the
    window in, so speculative equals plain windowed greedy."""
    _, _, dcfg, dparams = pair
    tcfg = presets.get_preset("llama-tiny", vocab_size=512, sliding_window=4)
    tparams = model_lib.init_params(jax.random.key(0), tcfg)
    prompt = jnp.asarray([[7, 1, 9, 4, 8, 2]], jnp.int32)
    lens = jnp.asarray([6], jnp.int32)
    want = ref_greedy(tcfg, tparams, prompt, lens, 10)
    got = speculative_generate_tokens(
        tparams, tcfg, dparams, dcfg, prompt, lens, k=3, max_new_tokens=10,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_quantized_draft_of_target(pair):
    """The self-speculation recipe: draft = int4-quantized target.  Exact
    output regardless of how well the quantized draft tracks the target."""
    from distributed_llms_tpu.checkpoint import quantize as quant_lib

    tcfg, tparams, _, _ = pair
    qparams = {**tparams,
               "blocks": quant_lib.quantize_tree(tparams["blocks"], bits=4)}
    prompt = jnp.asarray([[9, 8, 7, 6]], jnp.int32)
    lens = jnp.asarray([4], jnp.int32)
    want = ref_greedy(tcfg, tparams, prompt, lens, 10)
    got, stats = speculative_generate_tokens(
        tparams, tcfg, qparams, tcfg, prompt, lens, k=4, max_new_tokens=10,
        return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_engine_speculative_matches_generate_text():
    """Product path: attach a quantized self-draft, texts must equal plain
    generate_text exactly."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    eng = InferenceEngine.from_preset(
        "llama-tiny", RuntimeConfig(max_decode_steps=10, max_seq_len=128),
        vocab_size=300,
    )
    prompts = ["hello world", "abc"]
    want = eng.generate_text(prompts, max_new_tokens=10)
    eng.attach_draft(quantize_bits=4)
    got = eng.generate_text_speculative(prompts, max_new_tokens=10, k=3)
    assert got.text == want.text
    np.testing.assert_array_equal(got.tokens, want.tokens)


def test_engine_speculative_guards():
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    eng = InferenceEngine.from_preset(
        "llama-tiny", RuntimeConfig(max_decode_steps=8, max_seq_len=128),
        vocab_size=300,
    )
    with pytest.raises(ValueError, match="no draft"):
        eng.generate_text_speculative(["x"])
    with pytest.raises(ValueError, match="OR quantize_bits"):
        eng.attach_draft(eng.cfg, eng.params, quantize_bits=4)
    eng2 = InferenceEngine.from_preset(
        "llama-tiny",
        RuntimeConfig(max_decode_steps=8, max_seq_len=128, temperature=0.7),
        vocab_size=300,
    )
    eng2.attach_draft(quantize_bits=8)
    # temperature > 0 is supported (speculative sampling): valid tokens,
    # right shape, decodable.
    res = eng2.generate_text_speculative(["hello"], max_new_tokens=6, k=3,
                                         seed=11)
    assert res.tokens.shape == (1, 6)
    assert (res.tokens >= 0).all() and (res.tokens < 300).all()


def test_sampled_self_draft_always_accepts(pair):
    """Draft == target at temperature > 0: the acceptance ratio is 1, so
    every in-play draft is accepted (rejection would need u within float
    noise of 1)."""
    tcfg, tparams, _, _ = pair
    prompt = jnp.asarray([[3, 5, 8]], jnp.int32)
    lens = jnp.asarray([3], jnp.int32)
    _, stats = speculative_generate_tokens(
        tparams, tcfg, tparams, tcfg, prompt, lens, k=3, max_new_tokens=12,
        return_stats=True, temperature=0.8, rng=jax.random.key(42),
    )
    assert int(stats["accepted"]) == int(stats["drafted"]) > 0


def test_sampled_distribution_matches_plain_sampling():
    """Speculative sampling is distribution-preserving (Leviathan et al.):
    over many seeds, the joint empirical distribution of the first two
    sampled tokens must match plain ancestral sampling from the target —
    with a DIFFERENT draft model, so the rejection/residual path carries
    real weight.  Tiny 1-layer model, vocab 16, deterministic seeds."""
    n_seeds = 1200
    cfg = presets.get_preset("llama-tiny", vocab_size=16, num_layers=1,
                             num_heads=2, num_kv_heads=2, hidden_size=16,
                             intermediate_size=44)
    params = model_lib.init_params(jax.random.key(0), cfg)
    dparams = model_lib.init_params(jax.random.key(77), cfg)  # unrelated draft
    prompt = jnp.asarray([[7, 1, 9]], jnp.int32)
    lens = jnp.asarray([3], jnp.int32)

    def spec_pair(key):
        toks = speculative_generate_tokens(
            params, cfg, dparams, cfg, prompt, lens, k=2, max_new_tokens=2,
            temperature=0.9, rng=key,
        )
        return toks[0]

    def plain_pair(key):
        toks = gen_lib.generate_tokens(
            params, cfg, prompt, lens, key, max_new_tokens=2, temperature=0.9,
        )
        return toks[0]

    k1, k2, k3 = (jax.random.split(jax.random.fold_in(jax.random.key(123), i),
                                   n_seeds) for i in range(3))
    spec = np.asarray(jax.lax.map(spec_pair, k1, batch_size=n_seeds))
    plain_a = np.asarray(jax.lax.map(plain_pair, k2, batch_size=n_seeds))
    plain_b = np.asarray(jax.lax.map(plain_pair, k3, batch_size=n_seeds))

    def joint_hist(arr):
        h = np.zeros((16, 16))
        for a_, b_ in arr:
            h[a_, b_] += 1
        return h / len(arr)

    hs, hp_a, hp_b = joint_hist(spec), joint_hist(plain_a), joint_hist(plain_b)
    # Self-calibrated total-variation test: finite-sample TV between two
    # independent SAME-distribution empirical joints (plain-vs-plain) sets
    # the noise floor; the speculative joint must sit at that floor, not
    # above it.  A broken rejection/residual step moves whole conditional
    # rows and lands far outside 1.5x the null.
    null_tv = 0.5 * np.abs(hp_a - hp_b).sum()
    test_tv = 0.5 * np.abs(hs - hp_a).sum()
    assert test_tv < 1.5 * null_tv + 0.04, (
        f"TV {test_tv:.3f} vs same-distribution null {null_tv:.3f} — "
        "speculative sampling diverges from the target distribution"
    )


def test_config_driven_spec_routing():
    """RuntimeConfig(spec_decode=True): generate_text transparently routes
    greedy requests through the speculative loop (identical tokens), the
    self-draft attaches at construction, and a near-cap prompt falls back
    to the plain loop instead of erroring on the k+1 verify overshoot."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    rt = RuntimeConfig(max_decode_steps=8, max_seq_len=64, spec_decode=True,
                       spec_k=3)
    eng = InferenceEngine.from_preset("llama-tiny", rt, vocab_size=300,
                                      max_seq_len=64)
    assert eng.draft_params is not None  # attached at construction
    plain = InferenceEngine.from_preset(
        "llama-tiny", RuntimeConfig(max_decode_steps=8, max_seq_len=64),
        vocab_size=300, max_seq_len=64,
    )
    def acc_count():
        h = METRICS.snapshot()["histograms"].get("engine.spec_acceptance", {})
        return h.get("count", 0)

    before = acc_count()
    got = eng.generate_text(["hello world"], max_new_tokens=8)
    want = plain.generate_text(["hello world"], max_new_tokens=8)
    assert got.text == want.text
    np.testing.assert_array_equal(got.tokens, want.tokens)
    # the speculative path actually ran (acceptance metric observed)
    assert acc_count() == before + 1

    # 52 prompt-ish tokens + 8 new + k+1 > 64 cap: must fall back, not raise
    long_prompt = "x" * 52
    got2 = eng.generate_text([long_prompt], max_new_tokens=8)
    want2 = plain.generate_text([long_prompt], max_new_tokens=8)
    assert got2.text == want2.text


def test_spec_decode_quantized_engine_degrades_plain():
    """A shared cluster config with spec_decode=True must not brick workers
    serving quantized stores: the engine warns, skips the self-draft, and
    generate_text serves plain."""
    from distributed_llms_tpu.checkpoint import quantize as quant_lib
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg = presets.get_preset("llama-tiny", vocab_size=300)
    params = model_lib.init_params(jax.random.key(0), cfg)
    qparams = {**params,
               "blocks": quant_lib.quantize_tree(params["blocks"], bits=8)}
    rt = RuntimeConfig(max_decode_steps=6, max_seq_len=64, spec_decode=True)
    eng = InferenceEngine(cfg, rt, qparams)  # must NOT raise
    assert getattr(eng, "draft_params", None) is None
    plain = InferenceEngine(
        cfg, RuntimeConfig(max_decode_steps=6, max_seq_len=64), qparams
    )
    got = eng.generate_text(["hello"], max_new_tokens=6)
    want = plain.generate_text(["hello"], max_new_tokens=6)
    assert got.text == want.text


def test_spec_decode_config_mesh_degrades_plain():
    """Shared-config policy: spec_decode on a MESH engine degrades to plain
    serving with a warning (same convention as runtime.paged_pages there),
    never bricking the worker at construction."""
    from distributed_llms_tpu.core.config import MeshConfig, RuntimeConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    cfg = presets.get_preset("llama-tiny", vocab_size=300)
    pm = make_parallel_model(cfg, MeshConfig(data=2),
                             devices=jax.devices()[:2])
    params = model_lib.init_params(jax.random.key(0), cfg)
    eng = InferenceEngine(cfg, RuntimeConfig(spec_decode=True, max_seq_len=64,
                                             max_decode_steps=6),
                          params, parallel=pm)  # must NOT raise
    assert getattr(eng, "draft_params", None) is None
    res = eng.generate_text(["hi", "yo"], max_new_tokens=4)
    assert len(res.text) == 2


def test_rejects_bad_args(pair):
    tcfg, tparams, dcfg, dparams = pair
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    lens = jnp.asarray([2], jnp.int32)
    with pytest.raises(ValueError, match="k must be"):
        speculative_generate_tokens(tparams, tcfg, dparams, dcfg, prompt,
                                    lens, k=0)
    with pytest.raises(ValueError, match="vocabulary"):
        bad = presets.get_preset("llama-tiny", vocab_size=97)
        bparams = model_lib.init_params(jax.random.key(1), bad)
        speculative_generate_tokens(tparams, tcfg, bparams, bad, prompt, lens)
    with pytest.raises(ValueError, match="ragged_decode"):
        rcfg = dataclasses.replace(tcfg, ragged_decode=True)
        speculative_generate_tokens(tparams, rcfg, dparams, dcfg, prompt, lens)
