"""The model's real tokenizer rides with the shard store.

The reference tokenized with the model's own HF tokenizer on the master
(src/master/node.py:235-245).  Round 2's product path silently fell back to
byte-level ids (gibberish against a real vocab); these tests pin the fixed
chain: save_shards copies the tokenizer files into the store, the manifest
records them, InferenceEngine.from_store loads them, and the cluster path
(coordinator -> WorkerHost default engine factory) decodes real words.
"""

import asyncio
import json
import logging
import os

import jax
import pytest

from distributed_llms_tpu.checkpoint import store as store_lib
from distributed_llms_tpu.cluster.coordinator import Coordinator
from distributed_llms_tpu.cluster.worker import WorkerHost
from distributed_llms_tpu.core.config import ClusterConfig, RuntimeConfig
from distributed_llms_tpu.models import model as model_lib, presets
from distributed_llms_tpu.runtime.engine import InferenceEngine
from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer, HFTokenizer

VOCAB = {"<unk>": 0, "<eos>": 1, "hello": 2, "world": 3, "foo": 4, "bar": 5}

# The cluster tests below compile engine programs from a worker thread at
# the very TAIL of the suite (~300 tests of compile history in one
# process): 2/2 full-suite runs on 2026-07-31 segfaulted in
# backend_compile_and_load inside generate_text here, while every
# fresh-process run passes.  Shared marker — tests/conftest.py.
fragile_xla_cpu = pytest.mark.fragile_xla_cpu


def make_hf_tokenizer_dir(path: str) -> str:
    """Write a tiny real-vocab HF tokenizer (WordLevel) to ``path``."""
    tokenizers = pytest.importorskip("tokenizers")
    pytest.importorskip("transformers")
    Tokenizer, models, pre_tokenizers = (
        tokenizers.Tokenizer, tokenizers.models, tokenizers.pre_tokenizers
    )

    tok = Tokenizer(models.WordLevel(vocab=VOCAB, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    os.makedirs(path, exist_ok=True)
    tok.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "eos_token": "<eos>",
                "unk_token": "<unk>",
            },
            f,
        )
    return path


def make_store(tmp_path, with_tokenizer: bool) -> str:
    cfg = presets.get_preset("llama-tiny", vocab_size=512)
    params = model_lib.init_params(jax.random.key(0), cfg)
    store_dir = str(tmp_path / "store")
    tok_src = make_hf_tokenizer_dir(str(tmp_path / "ckpt")) if with_tokenizer else None
    store_lib.save_shards(
        params, store_dir, num_shards=1, model_config=cfg, tokenizer_src=tok_src
    )
    return store_dir


def test_manifest_records_tokenizer_and_engine_loads_it(tmp_path):
    store_dir = make_store(tmp_path, with_tokenizer=True)
    manifest = store_lib.load_manifest(store_dir)
    assert manifest["tokenizer"] == store_lib.TOKENIZER_DIR
    assert os.path.isfile(os.path.join(store_dir, "tokenizer", "tokenizer.json"))

    eng = InferenceEngine.from_store(store_dir, rt=RuntimeConfig(max_decode_steps=4))
    assert isinstance(eng.tokenizer, HFTokenizer)
    res = eng.generate_text(["hello world"], max_new_tokens=4)
    # Every decoded token comes from the real vocab, so the text is words
    # from VOCAB (or empty after special-token stripping) — never raw bytes.
    for word in res.text[0].split():
        assert word in VOCAB, f"decoded {word!r} is not in the real vocab"


def test_missing_tokenizer_warns_loudly(tmp_path):
    store_dir = make_store(tmp_path, with_tokenizer=False)
    manifest = store_lib.load_manifest(store_dir)
    assert manifest["tokenizer"] is None
    # The engine logger does not propagate (observability sets its own
    # handler), so capture with a handler attached to it directly.
    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    cap = Capture()
    logging.getLogger("engine").addHandler(cap)
    try:
        eng = InferenceEngine.from_store(store_dir)
    finally:
        logging.getLogger("engine").removeHandler(cap)
    assert isinstance(eng.tokenizer, ByteTokenizer)
    assert any(
        "no usable tokenizer" in r.getMessage() and "byte-level" in r.getMessage()
        for r in records
    ), "expected a loud byte-fallback warning for a real-vocab model"


def test_explicit_tokenizer_arg_still_wins(tmp_path):
    store_dir = make_store(tmp_path, with_tokenizer=True)
    eng = InferenceEngine.from_store(store_dir, tokenizer=ByteTokenizer())
    assert isinstance(eng.tokenizer, ByteTokenizer)


@fragile_xla_cpu
@pytest.mark.asyncio
async def test_cluster_mixed_budget_requests_via_continuous_batching(tmp_path):
    """coordinator.generate_requests: per-request budgets served through the
    worker's continuous batcher; each text equals the single-device engine
    generating that request alone."""
    store_dir = make_store(tmp_path, with_tokenizer=True)
    rt = RuntimeConfig(max_decode_steps=8)
    ccfg = ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=60.0, task_timeout_s=120.0,
    )
    coord = Coordinator(ccfg)
    await coord.start()
    wt = None
    try:
        w = WorkerHost("127.0.0.1", coord.port, cfg=ccfg, rt=rt)
        wt = asyncio.create_task(w.run())
        for _ in range(100):
            if w.worker_id is not None:
                break
            await asyncio.sleep(0.02)
        coord.plan_shards(1, store_dir=store_dir)
        await coord.place_shards()

        reqs = [
            {"prompt": "hello world", "max_new_tokens": 3},
            {"prompt": "foo bar", "max_new_tokens": 7},
            {"prompt": "hello", "max_new_tokens": 5},
        ]
        out = await coord.generate_requests(reqs)
        ref_eng = InferenceEngine.from_store(store_dir, rt=rt)
        for got, req in zip(out["text"], reqs):
            expect = ref_eng.generate_text(
                [req["prompt"]], max_new_tokens=req["max_new_tokens"]
            )
            assert got == expect.text[0], req
    finally:
        if wt is not None:
            wt.cancel()
        await coord.stop()


@fragile_xla_cpu
@pytest.mark.asyncio
async def test_cluster_path_decodes_real_words(tmp_path):
    """coordinator -> WorkerHost (default engine factory) -> generated text
    decoded with the store's real tokenizer, matching the single-device
    engine exactly — closes the last broken link in the product chain."""
    store_dir = make_store(tmp_path, with_tokenizer=True)
    rt = RuntimeConfig(max_decode_steps=4)
    ccfg = ClusterConfig(
        coordinator_host="127.0.0.1", coordinator_port=0,
        heartbeat_interval_s=0.2, heartbeat_timeout_s=60.0, task_timeout_s=120.0,
    )
    coord = Coordinator(ccfg)
    await coord.start()
    wt = None
    try:
        w = WorkerHost("127.0.0.1", coord.port, cfg=ccfg, rt=rt)
        wt = asyncio.create_task(w.run())
        for _ in range(100):
            if w.worker_id is not None:
                break
            await asyncio.sleep(0.02)
        assert w.worker_id is not None

        coord.plan_shards(1, store_dir=store_dir)
        await coord.place_shards()
        assert isinstance(w.engine.tokenizer, HFTokenizer)

        out = await coord.generate(["hello world"], max_new_tokens=4)
        ref = InferenceEngine.from_store(store_dir, rt=rt)
        expect = ref.generate_text(["hello world"], max_new_tokens=4)
        assert out["text"] == expect.text
        for word in out["text"][0].split():
            assert word in VOCAB
    finally:
        if wt is not None:
            wt.cancel()
        await coord.stop()
