"""Fresh-process runner for tests XLA:CPU cannot compile reliably in a
long-lived process.

The speculative while_loop programs (two model scans inlined into one
loop) nondeterministically SEGFAULT the XLA:CPU compiler when compiled
after ~150 other tests have run in the same process — 5/5 full-suite runs
on 2026-07-31 crashed there, on five different members of the family
(int4-draft, engine-level, lax.map-batched) and at three different stages
(backend_compile_and_load, persistent-cache serialize, deserialize) —
while every fresh-process run passes.  The whole speculative test family
is therefore marked skip-unless-DLT_RUN_ISOLATED in its home files
(module-level pytestmark) and executed here in ONE fresh subprocess —
full coverage, crash domain isolated, and a real failure in those tests
still fails the suite loudly through this runner.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ISOLATED = [
    "tests/runtime/test_speculative.py",
    "tests/runtime/test_spec_batcher.py",
    # Every OTHER test that compiles a speculative while_loop program —
    # grep for speculative_generate_tokens when adding tests outside the
    # two files above.
    "tests/models/test_sliding_window.py::"
    "test_ragged_windowed_speculative_matches_generate",
    # Cluster engine compiles at the suite TAIL (same crash class, plain
    # generate_text programs — see the marker in test_tokenizer_store.py).
    "tests/runtime/test_tokenizer_store.py::"
    "test_cluster_mixed_budget_requests_via_continuous_batching",
    "tests/runtime/test_tokenizer_store.py::"
    "test_cluster_path_decodes_real_words",
    # Round-5 compile-heavy additions: the crash budget is CUMULATIVE
    # (2026-07-31 round-5 runs died at whatever module compiled last —
    # tokenizer_store once, then train_ckpt once it was isolated), so new
    # big programs must not grow the main process past the round-4 green
    # budget.  These five compile pipelined/mesh/speculative programs.
    "tests/models/test_sliding_window.py::"
    "test_mesh_windowed_decode_matches_single_device",
    "tests/models/test_sliding_window.py::"
    "test_pipelined_windowed_decode_matches_single_device",
    "tests/runtime/test_batcher_sampling.py::"
    "test_speculative_logprobs_match_plain",
    "tests/runtime/test_batcher_sampling.py::"
    "test_speculative_penalties_match_plain",
    "tests/parallel/test_mesh_batcher.py::"
    "test_mesh_batcher_penalties_match_single_device",
    # Round-5 windowed-kernel additions (flash window band + windowed
    # ragged decode): each parametrization compiles fresh programs.
    "tests/ops/test_flash.py::test_windowed_static_matches_dense",
    "tests/ops/test_flash.py::test_windowed_dynamic_matches_dense",
    "tests/ops/test_flash.py::test_windowed_grad_matches_dot",
    "tests/ops/test_decode_attn.py::test_windowed_kernel_matches_dense",
    "tests/ops/test_decode_attn.py::test_batcher_windowed_ragged_matches_solo",
    "tests/models/test_sliding_window.py::test_flash_impl_matches_windowed_dot",
    # Chunked prefill (round 5): prefill_chunk_step compiles per bucket.
    "tests/runtime/test_chunked_prefill.py",
    # Dispatch-ahead overlap (round 13): the speculative leg compiles
    # spec_chunk programs — same crash class as test_spec_batcher.
    "tests/runtime/test_overlap.py::test_speculative_exact_on_vs_off",
    # Paged speculative decoding (round 17): every composition leg
    # compiles paged spec_chunk programs — same crash class as
    # test_spec_batcher.
    "tests/runtime/test_spec_paged.py",
    # Stall-free mixed batching (round 16): every fused-step composition
    # compiles mixed_step programs per pool/bucket config — the policy
    # hook tests at the top of the file are model-free and also run in
    # the main process.
    "tests/runtime/test_mixed_step.py",
]


def test_fragile_xla_cpu_tests_in_fresh_process():
    env = {**os.environ, "DLT_RUN_ISOLATED": "1"}
    # Never let an opted-in persistent compile cache reach the fragile
    # family: executable (de)serialization of these exact programs is 2 of
    # the 5 documented crash sites (tests/conftest.py).
    env.pop("DLT_TEST_CACHE_DIR", None)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         *ISOLATED],
        env=env, capture_output=True, text=True, timeout=3300, cwd=REPO,
    )
    assert r.returncode == 0, (
        f"isolated fragile tests failed (rc={r.returncode}):\n"
        f"{r.stdout[-3000:]}\n{r.stderr[-2000:]}"
    )
