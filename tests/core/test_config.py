import json

import pytest

from distributed_llms_tpu.core.config import Config, MeshConfig, load_config, save_config


def test_defaults():
    cfg = Config()
    assert cfg.model.family == "gpt2"
    assert cfg.mesh.num_devices == 1
    assert cfg.cluster.coordinator_port == 65432


def test_load_json_and_overrides(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"model": {"num_layers": 24}, "mesh": {"pipe": 4}}))
    cfg = load_config(str(p), overrides=["mesh.model=2", "model_id=llama-2-7b"])
    assert cfg.model.num_layers == 24
    assert cfg.mesh.pipe == 4
    assert cfg.mesh.model == 2
    assert cfg.model_id == "llama-2-7b"


def test_yaml_roundtrip(tmp_path):
    p = tmp_path / "cfg.yaml"
    save_config(Config(), str(p))
    cfg = load_config(str(p))
    assert cfg == Config()


def test_unknown_key_rejected(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"model": {"nun_layers": 24}}))
    with pytest.raises(ValueError, match="nun_layers"):
        load_config(str(p))


def test_mesh_shape():
    m = MeshConfig(data=2, pipe=2, model=2)
    assert m.num_devices == 8
    assert m.shape == (2, 2, 2, 1, 1)


def test_bad_attn_impl_rejected():
    import pytest
    from distributed_llms_tpu.core.config import ModelConfig
    with pytest.raises(ValueError, match="attn_impl"):
        ModelConfig(attn_impl="flsh")
