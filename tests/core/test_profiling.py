"""Profiling subsystem (SURVEY §5.1: absent in the reference)."""

import os

from distributed_llms_tpu.core import profiling
from distributed_llms_tpu.core.observability import METRICS


def test_step_timer_records_metrics():
    # Deterministic: a fake clock advances 10 ms per step instead of
    # sleeping wall-clock time (graftlint GL501 — fast tests don't sleep),
    # so the throughput gauge has an EXACT expected value.
    fake = {"now": 0.0}

    def clock() -> float:
        return fake["now"]

    timer = profiling.StepTimer("t_test", clock=clock)
    for _ in range(3):
        with timer.step(tokens=100):
            fake["now"] += 0.01
    snap = METRICS.snapshot()
    assert snap["histograms"]["t_test.step_seconds"]["count"] >= 3
    tps = snap["gauges"]["t_test.tokens_per_second"]
    assert abs(tps - 100 / 0.01) < 1e-6
    assert timer.steps == 3


def test_trace_writes_capture(tmp_path):
    import jax
    import jax.numpy as jnp

    out = str(tmp_path / "trace")
    with profiling.trace(out):
        with profiling.annotate("matmul-region"):
            x = jnp.ones((8, 8))
            jax.block_until_ready(x @ x)
    found = []
    for root, _, files in os.walk(out):
        found.extend(files)
    assert found, "profiler trace produced no files"


def test_record_memory_stats_returns_dict():
    stats = profiling.record_memory_stats(prefix="testdev")
    # CPU backends may expose no memory_stats; either way we get a dict and
    # any reported values land in the gauges.
    assert isinstance(stats, dict)
    snap = METRICS.snapshot()
    for name in stats:
        assert name in snap["gauges"]


def test_engine_generate_feeds_timer():
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    eng = InferenceEngine.from_preset(
        "gpt2-tiny", rt=RuntimeConfig(max_decode_steps=4, max_seq_len=64),
        vocab_size=512,  # byte tokenizer needs 256 + specials
    )
    res = eng.generate_text(["ab"], max_new_tokens=4)
    assert res.generated_tokens > 0
    snap = METRICS.snapshot()
    assert snap["histograms"]["engine.generate.step_seconds"]["count"] >= 1
    assert "engine.generate.tokens_per_second" in snap["gauges"]
