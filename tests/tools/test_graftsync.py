"""graftsync self-tests: every rule family proven to fire on a seeded
violation (and stay quiet on the sanctioned shapes), suppressions honored
only with a reason, and THE tier-1 gate — the repo itself must be clean
modulo the checked-in (EMPTY) baseline.

Fixture trees use the real scope suffix (pkg/runtime/...) so the analyzer
treats them exactly like the shipped package: the registry module is any
file ending in runtime/scheduler.py, and taint scope is everything under
a runtime/ segment.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[2]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.graftsync import (  # noqa: E402
    load_project, read_baseline, run_project, split_new,
)
from tools.graftsync import drift, ordering, syncs, taint  # noqa: E402


def _project(tmp_path: Path, files: dict[str, str]):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    return load_project(tmp_path)


def _rules(findings):
    return sorted(f.rule for f in findings)


# The fixture registry: the decision surfaces and sync sites the seeded
# violations play against (same literal-dict shape as the real
# runtime/scheduler.py registries).
REGISTRY_SRC = '''
LOCKSTEP_DECISIONS: dict[str, str] = {
    "Scheduler.admission_order": "queue pick",
    "ContinuousBatcher._shed_expired_queued": "queue-deadline shedding",
}

HOST_SYNC_SITES: dict[str, str] = {
    "ContinuousBatcher._fetch_chunk": "per-chunk D2H",
}
'''


# -- GS1xx lockstep taint ---------------------------------------------------

def test_pr19_wall_clock_shed_is_now_a_gate(tmp_path):
    """The finding this whole tool was born from — ``now =
    time.perf_counter()`` inside the batcher's queue-deadline shed (a
    declared admission decision), reproduced as source, caught by GS1.
    The real batcher now reads the injectable lockstep clock instead."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import time\n"
            "class ContinuousBatcher:\n"
            "    def _shed_expired_queued(self):\n"
            "        now = time.perf_counter()\n"   # the bug, verbatim shape
            "        for req in list(self.queued):\n"
            "            if req.deadline < now:\n"
            "                self.queued.remove(req)\n"
        ),
    }))
    assert _rules(findings) == ["GS101"]
    assert "time.perf_counter" in findings[0].message
    assert "_shed_expired_queued" in findings[0].message
    assert findings[0].line == 4


def test_gs1_taint_through_the_call_graph(tmp_path):
    """The RNG draw hides one hop below the declared decision — only
    interprocedural propagation sees it, and the message names the
    helper the taint flowed through."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "import random\n"
            "class Scheduler:\n"
            "    def admission_order(self, queue):\n"
            "        self._jitter()\n"
            "        return queue[0]\n"
            "    def _jitter(self):\n"
            "        return random.random()\n"
        ),
    }))
    assert _rules(findings) == ["GS101"]
    assert "random.random" in findings[0].message
    assert "via Scheduler._jitter" in findings[0].message


def test_gs1_subclass_override_is_bound_by_the_entry(tmp_path):
    """A registry entry on the base class binds every subclass override
    — hash() (PYTHONHASHSEED-dependent) in a subclass's admission hook
    fires even though only Scheduler.admission_order is declared."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "class Scheduler:\n"
            "    def admission_order(self, queue):\n"
            "        return queue[0]\n"
            "class TenantScheduler(Scheduler):\n"
            "    def admission_order(self, queue):\n"
            "        return max(queue, key=lambda r: hash(r.tenant))\n"
        ),
    }))
    assert _rules(findings) == ["GS101"]
    assert "'hash'" in findings[0].message
    assert "TenantScheduler.admission_order" in findings[0].message


def test_gs1_env_read_fires(tmp_path):
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "import os\n"
            "class Scheduler:\n"
            "    def admission_order(self, queue):\n"
            "        if os.environ[\"DEBUG_PICK\"]:\n"
            "            return queue[-1]\n"
            "        return queue[0]\n"
        ),
    }))
    assert _rules(findings) == ["GS101"]
    assert "os.environ[]" in findings[0].message


def test_gs1_metrics_arguments_are_allowlisted(tmp_path):
    """A clock read that only feeds a metrics/log call's arguments is
    observability plumbing — exempt BY ALLOWLIST (METRICS_BOUNDARY),
    never via suppression comments."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import time\n"
            "class ContinuousBatcher:\n"
            "    def _shed_expired_queued(self):\n"
            "        METRICS.observe(\"batcher.shed_scan_ms\",\n"
            "                        (time.perf_counter() - self._t0) * 1e3)\n"
            "        LOG.debug(\"shed at %s\", time.monotonic())\n"
            "        return None\n"
        ),
    }))
    assert findings == []


def test_gs1_declared_sync_site_is_exempt(tmp_path):
    """Timer reads inside a HOST_SYNC_SITES function are the sanctioned
    place for wall clocks (the host is already serialized against the
    device there) — and the device_get inside it is a declared sync, so
    GS2 stays quiet too."""
    project = _project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import time\n"
            "import jax\n"
            "class ContinuousBatcher:\n"
            "    def _shed_expired_queued(self):\n"
            "        return self._fetch_chunk()\n"
            "    def _fetch_chunk(self):\n"
            "        t0 = time.perf_counter()\n"
            "        out = jax.device_get(self._carry)\n"
            "        self._t_complete = time.perf_counter()\n"
            "        return out\n"
        ),
    })
    assert taint.check(project) == []
    assert syncs.check(project) == []


def test_gs1_source_outside_the_closure_is_clean(tmp_path):
    """Wall clocks in functions no decision reaches (stats endpoints,
    logging helpers) are not lockstep hazards."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import time\n"
            "class ContinuousBatcher:\n"
            "    def _shed_expired_queued(self):\n"
            "        return len(self.queued)\n"
            "    def stats(self):\n"
            "        return {\"now\": time.time()}\n"
        ),
    }))
    assert findings == []


# -- GS2xx undeclared host<->device syncs -----------------------------------

def test_gs2_undeclared_device_get_fires(tmp_path):
    findings = syncs.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import jax\n"
            "class ContinuousBatcher:\n"
            "    def _grow_ahead(self):\n"
            "        flags = jax.device_get(self._flags)\n"  # stray sync
            "        return flags\n"
            "    def _fetch_chunk(self):\n"
            "        return jax.device_get(self._carry)\n"   # declared site
        ),
    }))
    assert _rules(findings) == ["GS201"]
    assert "jax.device_get" in findings[0].message
    assert "ContinuousBatcher._grow_ahead" in findings[0].message


def test_gs2_method_form_and_module_level_fire(tmp_path):
    """.block_until_ready() spelled as a method call is the same sync,
    and import-time device work is attributed to <module> — never a
    sanctioned sync point."""
    findings = syncs.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/engine.py": (
            "import jax\n"
            "_WARM = jax.device_get(_PROBE)\n"               # module level
            "class Engine:\n"
            "    def step(self):\n"
            "        self._carry.block_until_ready()\n"      # method form
        ),
    }))
    assert _rules(findings) == ["GS201", "GS201"]
    assert any("<module>" in f.message for f in findings)
    assert any("<..>.block_until_ready" in f.message for f in findings)


def test_gs2_out_of_scope_files_are_not_checked(tmp_path):
    """The lockstep contract binds runtime/ — a device_get in a bench or
    cluster helper outside the scope segment is not this rule's
    business."""
    findings = syncs.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/cluster/probe.py": (
            "import jax\n"
            "def probe(x):\n"
            "    return jax.device_get(x)\n"
        ),
    }))
    assert findings == []


# -- GS3xx unordered-set iteration ------------------------------------------

def test_gs3_for_over_set_attribute_fires(tmp_path):
    findings = ordering.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "class Scheduler:\n"
            "    def __init__(self):\n"
            "        self._live = set()\n"
            "    def admission_order(self, queue):\n"
            "        for t in self._live:\n"
            "            if t:\n"
            "                return t\n"
            "        return None\n"
        ),
    }))
    assert _rules(findings) == ["GS301"]
    assert "for loop" in findings[0].message
    assert findings[0].line == 5


def test_gs3_sorted_and_set_comprehensions_are_clean(tmp_path):
    """sorted() IS the fix, and a set-producing comprehension over a set
    is order-insensitive — neither may fire or the rule teaches people
    to suppress instead of sort."""
    findings = ordering.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "class Scheduler:\n"
            "    def __init__(self):\n"
            "        self._live = set()\n"
            "    def admission_order(self, queue):\n"
            "        order = sorted(self._live)\n"
            "        still = {t for t in self._live if t}\n"
            "        return order[0] if order else len(still)\n"
        ),
    }))
    assert findings == []


def test_gs3_local_set_materialized_with_list_fires(tmp_path):
    """Set-typedness propagates to locals: a set comprehension assigned
    to a name, then list()-materialized, is the same hazard one
    statement later."""
    findings = ordering.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "class Scheduler:\n"
            "    def admission_order(self, queue):\n"
            "        pending = {r.tenant for r in queue}\n"
            "        names = list(pending)\n"
            "        return names[0] if names else None\n"
        ),
    }))
    assert _rules(findings) == ["GS301"]
    assert "list()" in findings[0].message
    assert findings[0].line == 4


def test_gs3_base_class_set_seen_from_subclass_override(tmp_path):
    """The set lives on the BASE class; the subclass override iterating
    it still fires — attr typing is closed over AST-visible bases."""
    findings = ordering.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/policy.py": (
            "class Scheduler:\n"
            "    def __init__(self):\n"
            "        self._live: set[str] = set()\n"
            "    def admission_order(self, queue):\n"
            "        return queue[0]\n"
            "class TenantScheduler(Scheduler):\n"
            "    def admission_order(self, queue):\n"
            "        return [t for t in self._live]\n"
        ),
    }))
    assert _rules(findings) == ["GS301"]
    assert "comprehension" in findings[0].message


# -- GS4xx registry drift ----------------------------------------------------

def test_gs4_dead_registry_entry_fires(tmp_path):
    findings = drift.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": (
            'LOCKSTEP_DECISIONS: dict[str, str] = {\n'
            '    "Scheduler.admission_order": "real",\n'
            '    "Ghost._vanished": "nothing declares this",\n'
            '}\n'
            'HOST_SYNC_SITES: dict[str, str] = {}\n'
            'class Scheduler:\n'
            '    def admission_order(self, queue):\n'
            '        return queue[0]\n'
        ),
    }))
    assert _rules(findings) == ["GS401"]
    assert "Ghost._vanished" in findings[0].message


def test_gs4_undeclared_hook_fires(tmp_path):
    findings = drift.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": (
            'HOOKS: dict[str, str] = {\n'
            '    "admission_order": "queue pick",\n'
            '    "mystery_hook": "added without a lockstep declaration",\n'
            '}\n'
            'LOCKSTEP_DECISIONS: dict[str, str] = {\n'
            '    "Scheduler.admission_order": "queue pick",\n'
            '}\n'
            'HOST_SYNC_SITES: dict[str, str] = {}\n'
            'class Scheduler:\n'
            '    def admission_order(self, queue):\n'
            '        return queue[0]\n'
            '    def mystery_hook(self):\n'
            '        return None\n'
        ),
    }))
    assert _rules(findings) == ["GS402"]
    assert "mystery_hook" in findings[0].message


def test_gs4_consistent_registries_are_clean(tmp_path):
    findings = drift.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": (
            'HOOKS: dict[str, str] = {"admission_order": "queue pick"}\n'
            'LOCKSTEP_DECISIONS: dict[str, str] = {\n'
            '    "Scheduler.admission_order": "queue pick",\n'
            '}\n'
            'HOST_SYNC_SITES: dict[str, str] = {\n'
            '    "Scheduler.sync_now": "declared",\n'
            '}\n'
            'class Scheduler:\n'
            '    def admission_order(self, queue):\n'
            '        return queue[0]\n'
            '    def sync_now(self):\n'
            '        return None\n'
        ),
    }))
    assert findings == []


# -- suppressions -----------------------------------------------------------

def test_suppressions_require_a_reason(tmp_path):
    """# graftsync: lockstep-ok(<reason>) suppresses on the line; an
    EMPTY reason is inert; rule-scoped ignore[GSxxx] only matches its
    rule — graftlint's escape semantics, verbatim."""
    findings = taint.check(_project(tmp_path, {
        "pkg/runtime/scheduler.py": REGISTRY_SRC,
        "pkg/runtime/batcher.py": (
            "import time\n"
            "class ContinuousBatcher:\n"
            "    def _shed_expired_queued(self):\n"
            "        a = time.perf_counter()  "
            "# graftsync: lockstep-ok(local log only, never compared)\n"
            "        b = time.perf_counter()  # graftsync: lockstep-ok()\n"
            "        c = time.perf_counter()  "
            "# graftsync: ignore[GS101](pre-mesh fast path)\n"
            "        d = time.perf_counter()  "
            "# graftsync: ignore[GS201](wrong rule)\n"
            "        return (a, b, c, d)\n"
        ),
    }))
    assert [f.line for f in findings] == [5, 7]  # b (no reason), d (wrong rule)


# -- THE tier-1 gate --------------------------------------------------------

def test_repo_is_clean():
    """Zero non-baselined findings over the real tree.  A wall clock or
    RNG on a decision path, a stray device_get, a set iteration feeding
    admission, or registry drift fails tier-1 right here."""
    project = load_project(ROOT)
    findings = run_project(project)
    new, _accepted = split_new(findings, read_baseline(ROOT))
    assert not new, "new graftsync findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_cli_exit_codes(tmp_path):
    # Dirty fixture tree -> exit 1 and the finding on stdout ...
    reg = tmp_path / "pkg" / "runtime" / "scheduler.py"
    reg.parent.mkdir(parents=True)
    reg.write_text(REGISTRY_SRC, encoding="utf-8")
    (reg.parent / "batcher.py").write_text(
        "import time\n"
        "class ContinuousBatcher:\n"
        "    def _shed_expired_queued(self):\n"
        "        return time.perf_counter()\n"
        "    def _fetch_chunk(self):\n"
        "        return None\n"
        "class Scheduler:\n"
        "    def admission_order(self, queue):\n"
        "        return queue[0]\n", encoding="utf-8")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftsync", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r.returncode == 1
    assert "GS101" in r.stdout
    # ... --baseline-write accepts the debt, after which the gate passes.
    subprocess.run(
        [sys.executable, "-m", "tools.graftsync", "--root", str(tmp_path),
         "--baseline-write"],
        capture_output=True, text=True, cwd=ROOT, check=True,
    )
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftsync", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # --only scoping rejects unknown families.
    r3 = subprocess.run(
        [sys.executable, "-m", "tools.graftsync", "--root", str(tmp_path),
         "--only", "GS9"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r3.returncode == 2


def test_check_front_door_scopes_across_tools():
    """python -m tools.check --only GS2,GF2 runs exactly the graftflow +
    graftsync families over the real tree (clean), skipping the tools
    with no selected family."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.check", "--root", str(ROOT),
         "--only", "GS2,GF2"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "graftsync" in r.stderr and "graftflow" in r.stderr
    assert "graftcheck" not in r.stderr and "graftlint" not in r.stderr
