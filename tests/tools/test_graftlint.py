"""graftlint self-tests: every rule family proven to fire on a seeded
violation, suppressions honored only with a reason, and THE tier-1 gate —
the repo itself must be clean modulo the checked-in baseline."""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[2]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.graftlint import (  # noqa: E402
    load_project, read_baseline, run_project, split_new, write_baseline,
)
from tools.graftlint import blocking, hotpath, locks, registry, testhygiene  # noqa: E402


def _project(tmp_path: Path, files: dict[str, str]):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    return load_project(tmp_path)


def _rules(findings):
    return sorted(f.rule for f in findings)


# -- GL1xx lock discipline ------------------------------------------------

LOCKED_CLASS = '''
import threading
from collections import deque

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.queue = deque()  # guarded-by: self._lock

    def submit(self, req):
        with self._lock:
            self.queue.append(req)   # guarded: OK

    def scan(self):
        return list(self.queue)      # VIOLATION: no lock

    # graftlint: holds(self._lock)
    def _scan_locked(self):
        return list(self.queue)      # OK: caller holds the lock

    def excused(self):
        return len(self.queue)  # graftlint: unguarded-ok(test-only probe)

    def no_reason(self):
        return len(self.queue)  # graftlint: unguarded-ok()
'''


def test_lock_rule_fires_on_unguarded_access(tmp_path):
    project = _project(tmp_path, {"pkg/mod.py": LOCKED_CLASS})
    findings = locks.check(project)
    lines = {f.line for f in findings if f.rule == "GL101"}
    assert len(lines) == 2  # scan() and the reasonless suppression
    assert all("guarded-by: self._lock" in f.message for f in findings
               if f.rule == "GL101")


def test_lock_rule_event_loop_confinement(tmp_path):
    src = '''
class Coord:
    def __init__(self):
        self.workers = {}  # guarded-by: event-loop

    async def handle(self):
        return len(self.workers)     # OK: coroutine

    def sync_probe(self):
        return len(self.workers)     # VIOLATION: sync, unannotated

    # graftlint: holds(event-loop)
    def status(self):
        return dict(self.workers)    # OK: declared loop-confined
'''
    findings = locks.check(_project(tmp_path, {"pkg/coord.py": src}))
    assert _rules(findings) == ["GL101"]
    assert "event-loop" in findings[0].message


def test_lock_rule_sync_closure_in_coroutine_is_not_confined(tmp_path):
    """A sync def nested inside an async def runs wherever it is CALLED
    (run_in_executor, a thread) — only the innermost function counts for
    event-loop confinement; holds(event-loop) re-admits it."""
    src = '''
class Coord:
    def __init__(self):
        self.workers = {}  # guarded-by: event-loop

    async def handler(self):
        def off_loop_job():
            return dict(self.workers)    # VIOLATION: escapes the loop
        # graftlint: holds(event-loop)
        def on_loop_helper():
            return len(self.workers)     # OK: declared loop-confined
        return off_loop_job, on_loop_helper
'''
    findings = locks.check(_project(tmp_path, {"pkg/coord.py": src}))
    assert _rules(findings) == ["GL101"]
    assert "off_loop" not in findings[0].message  # message names the field
    assert findings[0].line == 8


def test_lock_rule_requires_annotations_in_threaded_modules(tmp_path):
    findings = locks.check(_project(tmp_path, {
        "distributed_llms_tpu/runtime/server.py": "class S:\n    pass\n",
    }))
    assert _rules(findings) == ["GL102"]


# -- GL2xx hot-path hygiene ----------------------------------------------

HOT_SRC = '''
import jax.numpy as jnp
import numpy as np

def bad_item(x):
    return x.item()                      # GL201

def bad_cast(x):
    return float(jnp.sum(x))             # GL202

def bad_np(x):
    return np.asarray(jnp.exp(x))        # GL203

def bad_branch(x):
    if jnp.any(x > 0):                   # GL204
        return x
    return -x

def fine(cfg, x):
    rot = int(cfg.head_dim * cfg.pct)    # static config math: not flagged
    neg = float(jnp.finfo(jnp.float32).min)  # dtype metadata: not flagged
    if cfg.windowed:                     # host flag: not flagged
        return x
    return rot + neg
'''


def test_hotpath_rules_fire_in_scope(tmp_path):
    findings = hotpath.check(_project(tmp_path, {"pkg/ops/kern.py": HOT_SRC}))
    assert _rules(findings) == ["GL201", "GL202", "GL203", "GL204"]


def test_hotpath_ignores_out_of_scope_files(tmp_path):
    findings = hotpath.check(
        _project(tmp_path, {"pkg/runtime/host_side.py": HOT_SRC}))
    assert findings == []


# -- GL3xx registry drift -------------------------------------------------

FAULTS_MOD = '''
FAULT_SITES: dict[str, str] = {
    "engine.step": "per step",
    "engine.never": "declared but never fired",
}

class FaultPlane:
    def fire(self, site, tag=None):
        return None
'''


def test_fault_site_drift(tmp_path):
    project = _project(tmp_path, {
        "pkg/runtime/faults.py": FAULTS_MOD,
        "pkg/engine.py": (
            "def loop(plane):\n"
            "    plane.fire('engine.step')\n"       # registered: OK
            "    plane.fire('engine.stpe')\n"        # typo: GL301
        ),
        "tests/test_x.py": (
            "from pkg.runtime.faults import FaultPlane\n"
            "def test_y(plane):\n"
            "    plane.add('engine.bogus', 'raise')\n"   # dotted: GL301
            "    plane.add('s', 'drop')\n"               # synthetic: OK
        ),
    })
    findings = registry.check_fault_sites(project)
    assert _rules(findings) == ["GL301", "GL301", "GL305"]
    assert any("engine.stpe" in f.message for f in findings)
    assert any("engine.bogus" in f.message for f in findings)
    assert any("engine.never" in f.message for f in findings)


OBS_MOD = '''
METRIC_DOCS: dict[str, str] = {
    "req.count": "requests",
    "req.by_reason.*": "per-reason requests",
    "stale.gauge": "nothing emits this",
}
'''


def test_metric_drift(tmp_path):
    project = _project(tmp_path, {
        "pkg/core/observability.py": OBS_MOD,
        "pkg/srv.py": (
            "from .core.observability import METRICS\n"
            "def f(reason, name):\n"
            "    METRICS.inc('req.count')\n"             # OK
            "    METRICS.inc(f'req.by_reason.{reason}')\n"  # pattern: OK
            "    METRICS.inc('req.cuont')\n"             # typo: GL302
            "    METRICS.set_gauge(name, 1.0)\n"         # dynamic: GL302
        ),
    })
    findings = registry.check_metrics(project)
    assert _rules(findings) == ["GL302", "GL302", "GL305"]
    assert any("req.cuont" in f.message for f in findings)
    assert any("runtime-computed" in f.message for f in findings)
    assert any("stale.gauge" in f.message for f in findings)


def test_cli_flag_short_alias_is_not_invisible(tmp_path):
    """add_argument('-p', '--port', ...) declares --port: the long name
    must be found even when a short alias is the first positional."""
    project = _project(tmp_path, {
        "pkg/core/config.py": (
            "from dataclasses import dataclass\n"
            "@dataclass\nclass RuntimeConfig:\n    port: int = 0\n"
        ),
        "pkg/cli/serve_main.py": (
            "_RUNTIME_FLAGS: dict[str, str] = {'port': 'port'}\n"
            "_SERVER_ONLY_FLAGS = frozenset()\n"
            "def main(ap):\n"
            "    ap.add_argument('-p', '--port', type=int)\n"
        ),
    })
    assert registry.check_cli_flags(project) == []


def test_cli_flag_drift(tmp_path):
    project = _project(tmp_path, {
        "pkg/core/config.py": (
            "from dataclasses import dataclass\n"
            "@dataclass\nclass RuntimeConfig:\n    page_size: int = 64\n"
        ),
        "pkg/cli/serve_main.py": (
            "_RUNTIME_FLAGS: dict[str, str] = {\n"
            "    'page-size': 'page_size',\n"
            "    'paged-pages': 'paged_pages',\n"   # field missing: GL303
            "}\n"
            "_SERVER_ONLY_FLAGS = frozenset({'host', 'ghost'})\n"
            "def main(ap):\n"
            "    ap.add_argument('--page-size', type=int)\n"
            "    ap.add_argument('--paged-pages', type=int)\n"
            "    ap.add_argument('--host')\n"
            "    ap.add_argument('--rogue')\n"      # undeclared: GL303
            # 'ghost' declared but never added: GL305
        ),
    })
    findings = registry.check_cli_flags(project)
    assert _rules(findings) == ["GL303", "GL303", "GL305"]
    assert any("rogue" in f.message for f in findings)
    assert any("paged_pages" in f.message for f in findings)
    assert any("ghost" in f.message for f in findings)


# -- GL401 blocking calls in the engine loop ------------------------------

BATCHER_MOD = '''
import time

class ContinuousBatcher:
    def run(self):
        self._admit()
        helper()

    def _admit(self):
        time.sleep(0.1)          # GL401: reachable via run -> _admit

    def submit(self):
        time.sleep(0.1)          # NOT reachable from run: no finding

def helper():
    open("/tmp/x")               # GL401: reachable via run -> helper
'''


def test_blocking_rule_walks_the_run_call_graph(tmp_path):
    findings = blocking.check(
        _project(tmp_path, {"pkg/runtime/batcher.py": BATCHER_MOD}))
    assert _rules(findings) == ["GL401", "GL401"]
    assert {("_admit" in f.message or "helper" in f.message)
            for f in findings} == {True}
    assert not any("submit" in f.message for f in findings)


# -- GL501 test hygiene ---------------------------------------------------

def test_sleep_in_fast_test_fires(tmp_path):
    findings = testhygiene.check(_project(tmp_path, {"tests/test_t.py": (
        "import time, pytest\n"
        "def test_fast():\n"
        "    time.sleep(0.05)\n"          # GL501
        "def test_yield():\n"
        "    time.sleep(0)\n"             # GIL yield: OK
        "@pytest.mark.slow\n"
        "def test_slow():\n"
        "    time.sleep(1.0)\n"           # slow-marked: OK
    )}))
    assert _rules(findings) == ["GL501"]
    assert findings[0].line == 3


def test_slow_test_under_module_level_if_is_exempt(tmp_path):
    """Decorator-aware handling must survive module-level compound
    statements (a platform-guarded slow test is not a violation)."""
    findings = testhygiene.check(_project(tmp_path, {"tests/test_c.py": (
        "import sys, time, pytest\n"
        "if sys.platform != 'win32':\n"
        "    @pytest.mark.slow\n"
        "    def test_long():\n"
        "        time.sleep(1.0)\n"       # slow-marked: OK
        "    def test_fast():\n"
        "        time.sleep(0.5)\n"       # GL501 even under the if
    )}))
    assert _rules(findings) == ["GL501"]
    assert findings[0].line == 7


def test_slow_module_exempt(tmp_path):
    findings = testhygiene.check(_project(tmp_path, {"tests/test_s.py": (
        "import time, pytest\n"
        "pytestmark = pytest.mark.slow\n"
        "def test_anything():\n    time.sleep(0.5)\n"
    )}))
    assert findings == []


# -- strict fault-spec parsing (the GL301 runtime twin) -------------------

def test_fault_plane_strict_parse_rejects_unknown_sites():
    from distributed_llms_tpu.runtime.faults import FAULT_SITES, FaultPlane

    assert FaultPlane.parse("batcher.decode:raise@1", strict=True).rules
    with pytest.raises(ValueError, match="unknown fault site"):
        # graftlint: ignore[GL301](deliberately typo'd site — the assertion IS that strict parsing rejects it)
        FaultPlane.parse("batcher.decod:raise@1", strict=True)
    # Non-strict keeps the grammar tests' synthetic sites working.
    assert FaultPlane.parse("s:drop@1").rules[0].site == "s"
    assert FAULT_SITES  # the registry itself is populated


def test_write_docs_survives_backslash_in_registry_doc(tmp_path):
    """A backslash in a registry doc string must be written verbatim,
    not read as a re.sub escape (bad-escape crash / group mangling)."""
    (tmp_path / "README.md").write_text(
        "# x\n<!-- graftlint:fault-sites:begin -->\nold\n"
        "<!-- graftlint:fault-sites:end -->\n"
        "<!-- graftlint:metrics:begin -->\nold\n"
        "<!-- graftlint:metrics:end -->\n", encoding="utf-8")
    project = _project(tmp_path, {
        "pkg/runtime/faults.py": (
            "FAULT_SITES: dict[str, str] = "
            r"{'a.b': 'fires on \\x00 frames and \\g<1> groups'}"
            "\n"
        ),
        "pkg/core/observability.py": "METRIC_DOCS: dict[str, str] = {}\n",
    })
    assert set(registry.write_docs(project)) == {"fault-sites", "metrics"}
    text = (tmp_path / "README.md").read_text(encoding="utf-8")
    assert r"fires on \x00 frames and \g<1> groups" in text
    # The written tables satisfy the drift check (round-trip).
    assert registry.check_docs(load_project(tmp_path)) == []


def test_baseline_counts_duplicate_findings(tmp_path):
    """Baselining ONE occurrence of a finding must not absorb a second
    identical-message occurrence added later: the baseline is a multiset
    keyed (path, rule, message) with an [xN] count."""
    one = {"tests/test_d.py": (
        "import time\n"
        "def test_a():\n    time.sleep(0.5)\n"
    )}
    two = {"tests/test_d.py": (
        "import time\n"
        "def test_a():\n    time.sleep(0.5)\n"
        "def test_b():\n    time.sleep(0.5)\n"
    )}
    write_baseline(tmp_path, testhygiene.check(_project(tmp_path, one)))
    baseline = read_baseline(tmp_path)
    findings2 = testhygiene.check(_project(tmp_path, two))
    assert len(findings2) == 2
    new, accepted = split_new(findings2, baseline)
    assert len(accepted) == 1 and len(new) == 1  # the added sleep is NEW
    # Re-accepting both round-trips through the [x2] form.
    write_baseline(tmp_path, findings2)
    assert sum(read_baseline(tmp_path).values()) == 2
    assert split_new(findings2, read_baseline(tmp_path))[0] == []


# -- THE tier-1 gate ------------------------------------------------------

def test_repo_is_clean():
    """Zero non-baselined findings over the real tree.  A new violation
    of any rule family fails tier-1 right here."""
    project = load_project(ROOT)
    findings = run_project(project)
    new, _accepted = split_new(findings, read_baseline(ROOT))
    assert not new, "new graftlint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_cli_exit_codes(tmp_path):
    # Dirty fixture tree -> exit 1 and the finding on stdout ...
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_z.py").write_text(
        "import time\ndef test_a():\n    time.sleep(0.5)\n")
    env_root = str(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", env_root],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r.returncode == 1
    assert "GL501" in r.stdout
    # ... --baseline-write accepts the debt, after which the gate passes.
    subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", env_root,
         "--baseline-write"],
        capture_output=True, text=True, cwd=ROOT, check=True,
    )
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", env_root],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
