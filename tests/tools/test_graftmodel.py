"""Self-tests for tools/graftmodel — the protocol model-checking tier.

Each GM family gets seeded-violation tests against a toy fixture tree
(a registry module, a metrics module, a ``*_MODEL`` literal, and a test
file with drills) plus negatives proving a clean tree stays quiet.  The
toy protocol is a two-slot quota ledger: ``admit`` charges a unit,
``finish``/``drop`` refund it, and conservation (``charged == inflight
+ refunded``) is the GM1 law the mutations break.

Also here: the suppression drill (reasonless escapes are inert), the
CLI exit-code roundtrip (1 -> baseline-write -> 0, unknown family -> 2),
the front-door family scoping, and the tier-1 gate — the REAL repo must
model-check clean against the checked-in (empty) baseline.
"""

from __future__ import annotations

import copy
import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(ROOT))

from tools import graftmodel  # noqa: E402
from tools.graftmodel import load_project, run_project, split_new  # noqa: E402
from tools.graftmodel.core import (discover_models,  # noqa: E402
                                   load_registries)
from tools.graftmodel.docs import check_docs, write_docs  # noqa: E402

REGISTRY_SRC = '''\
ACTIONS = frozenset({"drop", "corrupt", "raise"})

FAULT_SITES = {
    "toy.site": "toy send path",
}

SITE_ACTIONS = {
    "toy.site": "drop, corrupt",
}

PROTOCOL_MODELS = {
    "toy.protocol": "two-slot quota ledger",
}
'''

METRICS_SRC = '''\
METRIC_DOCS = {
    "toy.fallbacks.*": "per-reason toy fallback counters",
}
'''

# Drills for both declared pairs, one per injection idiom the GM601
# scanner understands (plane.add literals, fault-spec strings).
TESTS_SRC = '''\
class _Plane:
    def add(self, *a, **k):
        return None


def test_drop_drill():
    _Plane().add("toy.site", "drop", when="1")


def test_corrupt_drill():
    assert "toy.site/T:corrupt@2"
'''

# The clean toy model: conservation holds on every reachable state, the
# space is 6 states, and every transition fires somewhere.
BASE_MODEL = {
    "name": "toy.protocol",
    "doc": "two-slot quota ledger",
    "params": {"BUDGET": 2},
    "state": {"inflight": 0, "charged": 0, "refunded": 0},
    "actions": [
        {"name": "admit", "guard": "charged < BUDGET",
         "update": {"inflight": "inflight + 1", "charged": "charged + 1"}},
        {"name": "finish", "guard": "inflight > 0",
         "update": {"inflight": "inflight - 1",
                    "refunded": "refunded + 1"}},
    ],
    "faults": [
        {"name": "drop", "site": "toy.site", "action": "drop",
         "guard": "inflight > 0", "metric": "toy.fallbacks.drop",
         "update": {"inflight": "inflight - 1",
                    "refunded": "refunded + 1"}},
    ],
    "invariants": [
        {"rule": "GM1", "name": "ledger-conserved",
         "expr": "charged == inflight + refunded"},
        {"rule": "GM2", "name": "no-negative-parcels",
         "expr": "inflight >= 0"},
        {"rule": "GM3", "name": "refund-at-most-charged",
         "expr": "refunded <= charged"},
        {"rule": "GM4", "name": "bounded-by-budget",
         "expr": "charged <= BUDGET"},
    ],
    "terminal": "inflight == 0",
}


def _toy(mutate=None) -> dict:
    m = copy.deepcopy(BASE_MODEL)
    if mutate:
        mutate(m)
    return m


def _tree(tmp_path, model=None, model_src=None, registry=REGISTRY_SRC,
          metrics=METRICS_SRC, tests=TESTS_SRC, readme=None):
    (tmp_path / "pkg" / "runtime").mkdir(parents=True, exist_ok=True)
    (tmp_path / "pkg" / "core").mkdir(parents=True, exist_ok=True)
    (tmp_path / "tests").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "runtime" / "faults.py").write_text(registry)
    (tmp_path / "pkg" / "core" / "observability.py").write_text(metrics)
    if tests is not None:
        (tmp_path / "tests" / "test_drills.py").write_text(tests)
    if model_src is None:
        model_src = f"TOY_MODEL = {(model or BASE_MODEL)!r}"
    (tmp_path / "pkg" / "proto.py").write_text(model_src + "\n")
    if readme is not None:
        (tmp_path / "README.md").write_text(readme)
    return load_project(tmp_path)


def _rules(findings):
    return sorted(f.rule for f in findings)


def _messages(findings):
    return "\n".join(f.render() for f in findings)


# -- clean tree / exploration stats -----------------------------------------

def test_clean_tree_is_quiet(tmp_path):
    findings = run_project(_tree(tmp_path))
    assert findings == [], _messages(findings)


def test_exploration_stats_are_exact(tmp_path):
    stats = []
    run_project(_tree(tmp_path), only={"GM1"}, stats=stats)
    assert [s["model"] for s in stats] == ["toy.protocol"]
    # 6 reachable ledger states, 9 enabled (state, transition) firings —
    # exact because BFS with fixed transition order is deterministic.
    assert stats[0]["states"] == 6
    assert stats[0]["fired"] == 9


def test_invalid_model_is_excluded_from_exploration(tmp_path):
    # A schema-broken model must surface as GM504, never crash the BFS.
    project = _tree(tmp_path, model=_toy(
        lambda m: m["actions"][0].__setitem__("guard", "inflight +")))
    findings = run_project(project)
    assert "GM504" in _rules(findings)
    assert "does not compile" in _messages(findings)
    assert not [f for f in findings if f.rule.startswith(("GM1", "GM2"))]


# -- GM1: ledger accounting --------------------------------------------------

def test_gm101_lost_refund_reports_shortest_trace(tmp_path):
    def lose_refund(m):
        m["faults"][0]["update"] = {"inflight": "inflight - 1"}
    findings = run_project(_tree(tmp_path, model=_toy(lose_refund)),
                           only={"GM1"})
    assert _rules(findings) == ["GM101"]
    msg = findings[0].message
    assert "ledger-conserved" in msg
    assert "trace: admit -> drop" in msg  # shortest counterexample


def test_gm101_violation_carries_state(tmp_path):
    def lose_refund(m):
        m["faults"][0]["update"] = {"inflight": "inflight - 1"}
    findings = run_project(_tree(tmp_path, model=_toy(lose_refund)),
                           only={"GM1"})
    assert "charged=1" in findings[0].message
    assert "refunded=0" in findings[0].message


def test_gm1_scoped_run_excludes_other_families(tmp_path):
    def break_two(m):
        m["faults"][0]["update"] = {"inflight": "inflight - 1"}  # GM1
        m["invariants"][1]["expr"] = "inflight <= 1"             # GM2
    project = _tree(tmp_path, model=_toy(break_two))
    assert _rules(run_project(project, only={"GM1"})) == ["GM101"]
    assert _rules(run_project(project, only={"GM2"})) == ["GM201"]


# -- GM2: parcel ownership ---------------------------------------------------

def test_gm201_overcommit_violation(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["invariants"][1].update(
            name="parked-at-most-one", expr="inflight <= 1"))),
        only={"GM2"})
    assert _rules(findings) == ["GM201"]
    assert "trace: admit -> admit" in findings[0].message


def test_gm201_initial_state_is_checked(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["invariants"][1].update(expr="inflight > 0"))),
        only={"GM2"})
    assert _rules(findings) == ["GM201"]
    assert "<initial state>" in findings[0].message


def test_gm201_clean_model_quiet(tmp_path):
    assert run_project(_tree(tmp_path), only={"GM2"}) == []


# -- GM3: at-most-once adoption + fallback metrics ---------------------------

def test_gm301_double_count_violation(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["invariants"][2].update(expr="refunded < charged"))),
        only={"GM3"})
    assert _rules(findings) == ["GM301"]


def test_gm302_fault_edge_without_metric(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["faults"][0].pop("metric"))), only={"GM3"})
    assert _rules(findings) == ["GM302"]
    assert "declares no fallback metric" in findings[0].message


def test_gm3_clean_model_quiet(tmp_path):
    assert run_project(_tree(tmp_path), only={"GM3"}) == []


# -- GM4: liveness & boundedness ---------------------------------------------

def test_gm401_deadlock_reported_with_trace(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m.update(terminal="charged == 0"))), only={"GM4"})
    assert _rules(findings) == ["GM401"]
    assert "deadlock" in findings[0].message
    assert "trace:" in findings[0].message


def test_gm402_tagged_invariant(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["invariants"][3].update(expr="charged < BUDGET"))),
        only={"GM4"})
    assert _rules(findings) == ["GM402"]
    assert "bounded-by-budget" in findings[0].message


def test_gm403_dead_transition(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["actions"].append(
            {"name": "never", "guard": "inflight > BUDGET", "update": {}}))),
        only={"GM4"})
    assert _rules(findings) == ["GM403"]
    assert "'never' is never enabled" in findings[0].message


def test_gm404_unbounded_counter_divergence(tmp_path):
    def leak(m):
        m["state"]["leak"] = 9990  # near VAR_BOUND: trips in a few steps
        m["actions"].append({"name": "leak", "guard": "leak >= 0",
                             "update": {"leak": "leak + 1"}})
    findings = run_project(_tree(tmp_path, model=_toy(leak)), only={"GM4"})
    assert _rules(findings) == ["GM404"]
    assert "'leak'" in findings[0].message
    # GM403 is deliberately skipped for a diverged model.


# -- GM5: model <-> code drift -----------------------------------------------

def test_gm501_unknown_site_and_action(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["faults"][0].__setitem__("site", "ghost.site"))),
        only={"GM5"})
    assert _rules(findings) == ["GM501"]
    assert "not declared in FAULT_SITES" in findings[0].message

    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["faults"][0].__setitem__("action", "raise"))),
        only={"GM5"})
    assert _rules(findings) == ["GM501"]
    assert "'toy.site:raise' not declared in SITE_ACTIONS" \
        in findings[0].message


def test_gm502_unknown_metric(tmp_path):
    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["faults"][0].__setitem__("metric", "rogue.counter"))),
        only={"GM5"})
    assert _rules(findings) == ["GM502"]
    assert "not declared in METRIC_DOCS" in findings[0].message


def test_gm503_registry_drift_both_directions(tmp_path):
    dead = REGISTRY_SRC.replace(
        '"toy.protocol": "two-slot quota ledger",',
        '"toy.protocol": "two-slot quota ledger",\n'
        '    "ghost.protocol": "model deleted, entry kept",')
    findings = run_project(_tree(tmp_path, registry=dead), only={"GM5"})
    assert _rules(findings) == ["GM503"]
    assert "dead registry entry" in findings[0].message

    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m.update(name="toy.renamed"))), only={"GM5"})
    assert _rules(findings) == ["GM503", "GM503"]
    msgs = _messages(findings)
    assert "'toy.renamed' is not registered" in msgs
    assert "'toy.protocol' has no *_MODEL declaration" in msgs


def test_gm503_site_actions_vs_fault_sites(tmp_path):
    registry = REGISTRY_SRC.replace(
        '"toy.site": "toy send path",',
        '"toy.site": "toy send path",\n    "lonely.site": "undeclared",')
    registry = registry.replace(
        '"toy.site": "drop, corrupt",',
        '"toy.site": "drop, corrupt",\n    "extra.site": "drop",')
    findings = run_project(_tree(tmp_path, registry=registry), only={"GM5"})
    msgs = _messages(findings)
    assert _rules(findings) == ["GM503", "GM503"]
    assert "SITE_ACTIONS site 'extra.site' is not declared" in msgs
    assert "FAULT_SITES site 'lonely.site' has no SITE_ACTIONS" in msgs


def test_gm503_actions_outside_grammar(tmp_path):
    registry = REGISTRY_SRC.replace('"toy.site": "drop, corrupt",',
                                    '"toy.site": "drop, explode",')
    # The model's corrupt-free fault edge still parses; only the grammar
    # violation and the now-undeclared drill pair change, so scope to GM5.
    findings = run_project(_tree(tmp_path, registry=registry), only={"GM5"})
    assert "GM503" in _rules(findings)
    assert "['explode']" in _messages(findings)


def test_gm504_non_literal_model(tmp_path):
    src = ("def build():\n    return {}\n\n"
           "TOY_MODEL = build()")
    findings = run_project(_tree(tmp_path, model_src=src), only={"GM5"})
    assert "GM504" in _rules(findings)
    assert "not a pure literal" in _messages(findings)


def test_gm504_schema_errors(tmp_path):
    findings = run_project(
        _tree(tmp_path, model_src="TOY_MODEL = {'name': 'toy.protocol'}"),
        only={"GM5"})
    assert "GM504" in _rules(findings)
    assert "missing keys" in _messages(findings)

    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["actions"][0]["update"].__setitem__("ghost", "1"))),
        only={"GM5"})
    assert "updates undeclared variable 'ghost'" in _messages(findings)

    findings = run_project(_tree(tmp_path, model=_toy(
        lambda m: m["invariants"][0].update(rule="GM9"))), only={"GM5"})
    assert "rule tag must be one of" in _messages(findings)


# -- GM6: drill coverage -----------------------------------------------------

def test_gm601_undrilled_pair(tmp_path):
    drop_only = ('class _P:\n    def add(self, *a, **k):\n        pass\n\n'
                 'def test_drop():\n    _P().add("toy.site", "drop")\n')
    findings = run_project(_tree(tmp_path, tests=drop_only), only={"GM6"})
    assert _rules(findings) == ["GM601"]
    assert "'toy.site:corrupt' is never injected" in findings[0].message


def test_gm601_spec_strings_count_as_drills(tmp_path):
    spec_only = ('def test_both():\n'
                 '    assert "toy.site:drop@1, toy.site/T:corrupt@2"\n')
    assert run_project(_tree(tmp_path, tests=spec_only), only={"GM6"}) == []


def test_gm601_synthetic_sites_and_dynamic_args_ignored(tmp_path):
    # Drills of undeclared sites and non-literal plane.add args are not
    # coverage of any declared pair: both toy pairs stay undrilled.
    tests = ('class _P:\n    def add(self, *a, **k):\n        pass\n\n'
             'def test_synthetic(site):\n'
             '    assert "other.site:drop@1"\n'
             '    _P().add(site, "corrupt")\n')
    findings = run_project(_tree(tmp_path, tests=tests), only={"GM6"})
    assert _rules(findings) == ["GM601", "GM601"]


# -- GMD: README table drift -------------------------------------------------

_STALE_README = ("# toy\n\n<!-- graftmodel:models:begin -->\nstale\n"
                 "<!-- graftmodel:models:end -->\n\n"
                 "<!-- graftmodel:rules:begin -->\nstale\n"
                 "<!-- graftmodel:rules:end -->\n")


def test_gmd01_stale_tables(tmp_path):
    findings = run_project(_tree(tmp_path, readme=_STALE_README),
                           only={"GMD"})
    assert _rules(findings) == ["GMD01", "GMD01"]
    assert "is stale" in findings[0].message


def test_gmd01_missing_blocks(tmp_path):
    findings = run_project(_tree(tmp_path, readme="# toy\n"), only={"GMD"})
    assert _rules(findings) == ["GMD01", "GMD01"]
    assert "missing" in findings[0].message


def test_gmd01_write_docs_roundtrip(tmp_path):
    project = _tree(tmp_path, readme=_STALE_README)
    decls, _ = discover_models(project)
    regs = load_registries(project)
    assert len(check_docs(tmp_path, decls, regs)) == 2
    assert write_docs(tmp_path, decls, regs)
    assert check_docs(tmp_path, decls, regs) == []
    text = (tmp_path / "README.md").read_text()
    assert "`toy.protocol`" in text and "pkg/proto.py" in text
    assert "GM601" in text  # rules table rendered from RULE_DOCS


# -- suppressions ------------------------------------------------------------

_SUPP_TEMPLATE = '''\
TOY_MODEL = {
    "name": "toy.protocol",
    "doc": "two-slot quota ledger",
    "params": {"BUDGET": 2},
    "state": {"inflight": 0, "charged": 0, "refunded": 0},
    "actions": [
        {"name": "admit", "guard": "charged < BUDGET",
         "update": {"inflight": "inflight + 1", "charged": "charged + 1"}},
        {"name": "finish", "guard": "inflight > 0",
         "update": {"inflight": "inflight - 1",
                    "refunded": "refunded + 1"}},
    ],
    "faults": [
        __COMMENT__
        {"name": "drop", "site": "toy.site", "action": "drop",
         "guard": "inflight > 0",
         "update": {"inflight": "inflight - 1",
                    "refunded": "refunded + 1"}},
    ],
    "invariants": [
        {"rule": "GM1", "name": "ledger-conserved",
         "expr": "charged == inflight + refunded"},
    ],
    "terminal": "inflight == 0",
}
'''


def _supp_project(tmp_path, comment):
    return _tree(tmp_path,
                 model_src=_SUPP_TEMPLATE.replace("__COMMENT__", comment))


def test_suppression_ok_with_reason(tmp_path):
    findings = run_project(
        _supp_project(tmp_path, "# graftmodel: ok(metric lands in PR 21)"),
        only={"GM3"})
    assert findings == [], _messages(findings)


def test_suppression_without_reason_is_inert(tmp_path):
    findings = run_project(_supp_project(tmp_path, "# graftmodel: ok()"),
                           only={"GM3"})
    assert _rules(findings) == ["GM302"]


def test_suppression_rule_scoped_ignore(tmp_path):
    findings = run_project(
        _supp_project(tmp_path,
                      "# graftmodel: ignore[GM302](accepted toy debt)"),
        only={"GM3"})
    assert findings == [], _messages(findings)
    # A different rule's ignore must not absorb the GM302 finding.
    findings = run_project(
        _supp_project(tmp_path,
                      "# graftmodel: ignore[GM501](wrong rule)"),
        only={"GM3"})
    assert _rules(findings) == ["GM302"]


# -- CLI + front door + the tier-1 gate --------------------------------------

def _cli(args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftmodel", *args],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_cli_exit_codes(tmp_path):
    _tree(tmp_path, model=_toy(lambda m: m["faults"][0].pop("metric")))
    root = ["--root", str(tmp_path)]

    r = _cli(root)
    assert r.returncode == 1, r.stderr
    assert "GM302" in r.stdout
    assert "states," in r.stderr  # per-model exploration counts printed

    r = _cli(root + ["--baseline-write"])
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "graftmodel_baseline.txt").exists()

    r = _cli(root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 baselined" in r.stderr

    r = _cli(root + ["--only", "GM9"])
    assert r.returncode == 2
    assert "unknown families" in r.stderr


def test_check_front_door_scopes_across_tools():
    r = subprocess.run(
        [sys.executable, "-m", "tools.check", "--root", str(ROOT),
         "--only", "GM6,GF2"],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "check: graftmodel:" in r.stderr
    assert "check: graftflow:" in r.stderr
    for skipped in ("graftlint", "graftsync", "graftcheck"):
        assert f"check: {skipped}:" not in r.stderr


def test_repo_is_clean():
    """The tier-1 gate: the real control-plane models must check clean
    against the checked-in (empty) baseline."""
    findings = run_project(load_project(ROOT))
    new, _ = split_new(findings, graftmodel.read_baseline(ROOT))
    assert not new, "\n".join(f.render() for f in new)
