"""graftcheck self-tests: every rule family proven to fire on a seeded
violation, the GC4 gate pinned to the declared bucket ladder, and THE
tier-1 gate — the repo's real contracts must hold modulo the (empty)
checked-in baseline."""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parents[2]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tools.graftcheck import (  # noqa: E402
    read_baseline, run_all, split_new, write_baseline,
)
from tools.graftcheck import (  # noqa: E402
    donation, dtypes, recompile, shapes, sharding,
)
from tools.graftcheck.contracts import (  # noqa: E402
    DonationContract, HotFnContract, OpCase, OpContract, RecompileScenario,
    SpecAudit, CollectiveAudit, fake_mesh, sds,
)
from tools.graftcheck.core import jaxpr_hash  # noqa: E402


def _rules(findings):
    return sorted(f.rule for f in findings)


# -- GC1 shape/dtype contracts --------------------------------------------

def test_gc1_fires_on_shape_and_dtype_drift():
    contract = OpContract(
        "seeded.op", "pkg/op.py", "seeded", lambda: [
            # Declared f32 [2, 4] but the op returns bf16 [2, 4]: dtype drift.
            OpCase("dtype", lambda x: x.astype(jnp.bfloat16),
                   (sds((2, 4), jnp.float32),), (((2, 4), "float32"),)),
            # Declared [2, 4] but the op transposes: shape drift.
            OpCase("shape", lambda x: x.T,
                   (sds((2, 4), jnp.float32),), (((2, 4), "float32"),)),
            # Contract holds: no finding from this case.
            OpCase("ok", lambda x: x + 1,
                   (sds((2, 4), jnp.float32),), (((2, 4), "float32"),)),
        ])
    findings = shapes.check([contract])
    assert _rules(findings) == ["GC101", "GC101"]
    assert all("seeded.op" in f.message for f in findings)


def test_gc1_trace_failure_is_a_finding():
    def boom(x):
        raise ValueError("shapes the op claims to support")

    contract = OpContract(
        "seeded.broken", "pkg/op.py", "seeded", lambda: [
            OpCase("case", boom, (sds((2,), jnp.float32),),
                   (((2,), "float32"),))])
    findings = shapes.check([contract])
    assert _rules(findings) == ["GC102"]


# -- GC2 sharding-spec audit ----------------------------------------------

def _audit(build):
    return SpecAudit("seeded@mesh", "pkg/specs.py", build)


def test_gc2_structure_drift():
    from jax.sharding import PartitionSpec as P

    findings = sharding.check_specs([_audit(lambda: (
        {"a": sds((4, 4), jnp.float32), "b": sds((4,), jnp.float32)},
        {"a": P(None, None)},          # 'b' missing: tree drift
        fake_mesh(model=2),
    ))])
    assert _rules(findings) == ["GC201"]
    assert "'b'" in findings[0].message


def test_gc2_unknown_axis_rank_and_divisibility():
    from jax.sharding import PartitionSpec as P

    findings = sharding.check_specs([_audit(lambda: (
        {"w": sds((5, 4), jnp.float32), "v": sds((4,), jnp.float32),
         "u": sds((8, 4), jnp.float32)},
        {"w": P("model", None),        # 5 % 2 != 0 -> GC204
         "v": P(None, None, "model"),  # rank 3 > rank 1 -> GC203
         "u": P("bogus", None)},       # no such axis -> GC202
        fake_mesh(model=2),
    ))])
    assert _rules(findings) == ["GC202", "GC203", "GC204"]


def test_gc2_catches_the_unguarded_pipe_shard_regression():
    """The in-tree bug this rule forced fixed: param_specs used to shard
    the stacked layer axis over 'pipe' without a divisibility check (3
    neox-tiny layers over pipe=2).  Seed the pre-fix behavior and prove
    the audit fails it; the repo-clean gate proves the fix holds."""
    from jax.sharding import PartitionSpec as P

    findings = sharding.check_specs([_audit(lambda: (
        {"blocks": {"wq": sds((3, 64, 4, 16), jnp.float32)}},
        {"blocks": {"wq": P("pipe", None, None, None)}},  # unguarded
        fake_mesh(pipe=2),
    ))])
    assert _rules(findings) == ["GC204"]
    assert "'pipe'" in findings[0].message


def test_gc2_collective_axis_must_exist_on_mesh():
    from distributed_llms_tpu.core import jaxcompat
    from jax.sharding import PartitionSpec as P

    def build():
        trace_mesh = fake_mesh(seq=2)

        def fn(x):
            return jaxcompat.shard_map(
                lambda x: jax.lax.psum(x, "seq"),
                mesh=trace_mesh, in_specs=P("seq"), out_specs=P(),
                axis_names={"seq"},
            )(x)

        # The audit DECLARES the op runs on a mesh without a 'seq' axis at
        # all: the traced psum's axis is missing there -> GC205.
        from jax.sharding import AbstractMesh

        return fn, (sds((4,), jnp.float32),), AbstractMesh((("model", 2),))

    findings = sharding.check_collectives(
        [CollectiveAudit("seeded.psum", "pkg/op.py", "seeded", build)])
    assert "GC205" in _rules(findings)
    assert any("'seq'" in f.message for f in findings)


# -- GC3 dtype promotion --------------------------------------------------

def test_gc3_unallowlisted_bf16_upcast_fires():
    def sneaky_upcast(x):  # np.float32 scalar promotes bf16 -> f32
        return (x * np.float32(2.0)).sum()

    contract = HotFnContract(
        "seeded.hot", "pkg/hot.py", "seeded",
        lambda: (sneaky_upcast, (sds((8,), jnp.bfloat16),)),
        frozenset())
    findings = dtypes.check([contract])
    assert _rules(findings) == ["GC302"]
    assert "sneaky_upcast" in findings[0].message
    # The same trace passes once the site is allowlisted.
    blessed = HotFnContract(
        "seeded.hot", "pkg/hot.py", "seeded",
        lambda: (sneaky_upcast, (sds((8,), jnp.bfloat16),)),
        frozenset({"sneaky_upcast"}))
    assert dtypes.check([blessed]) == []


def test_gc3_float64_fires_under_x64():
    def widens(x):
        return x.astype("float64").sum()

    contract = HotFnContract(
        "seeded.x64", "pkg/hot.py", "seeded",
        lambda: (widens, (sds((8,), jnp.float32),)), frozenset())
    with jax.experimental.enable_x64():
        findings = dtypes.check([contract])
    assert "GC301" in _rules(findings)
    assert any("widens" in f.message for f in findings)


# -- GC4 recompilation ----------------------------------------------------

def _identity_trace(width: int) -> str:
    return jaxpr_hash(lambda x: x + 1, sds((width,), jnp.float32))


def test_gc4_unbucketed_widths_fire_both_rules():
    """The classic bug seeded verbatim: padding to the RAW request length.
    Off-ladder widths fire GC402 and the (per-width-compiling) trace
    blows the declared key budget -> GC401."""
    sc = RecompileScenario(
        name="seeded.raw-pad", path="pkg/engine.py", doc="seeded",
        ladder=(1, 2, 3, 5, 7, 9, 11),
        width_of=lambda n: n,                 # no bucketing
        allowed_widths=(1, 2, 3, 5, 7, 9, 11),  # ladder "allows" raw widths
        max_keys=2,                           # but declares 2 programs
        trace=_identity_trace,
    )
    findings = recompile.check([sc])
    assert _rules(findings) == ["GC401"]
    sc_off = RecompileScenario(
        name="seeded.off-ladder", path="pkg/engine.py", doc="seeded",
        ladder=(1, 9), width_of=lambda n: n, allowed_widths=(8, 16),
        max_keys=2, trace=_identity_trace,
    )
    findings = recompile.check([sc_off])
    assert set(_rules(findings)) == {"GC402"}


def test_gc4_bucketed_widths_pass():
    from distributed_llms_tpu.runtime import shapes as shapes_lib

    sc = RecompileScenario(
        name="seeded.bucketed", path="pkg/engine.py", doc="seeded",
        ladder=tuple(range(1, 65)),
        width_of=lambda n: shapes_lib.bucket_length(n),
        allowed_widths=tuple(shapes_lib.bucket_ladder(64)),
        max_keys=shapes_lib.bucket_count(64),
        trace=_identity_trace,
    )
    assert recompile.check([sc]) == []


def test_bucket_ladder_is_closed_under_the_policy():
    from distributed_llms_tpu.runtime import shapes as shapes_lib

    cap = 128
    ladder = set(shapes_lib.bucket_ladder(cap))
    for n in range(1, cap + 1):
        assert min(shapes_lib.bucket_length(n), cap) in ladder
        assert shapes_lib.generate_pad_len(n, 8, cap) in (
            ladder | {min(shapes_lib.bucket_length(n), cap - 8),
                      max(cap - 8, n)}
        )
    assert len(ladder) == shapes_lib.bucket_count(cap)


def test_engine_generate_pads_up_the_bucket_ladder():
    """The in-tree GC4 bug this gate forced fixed: generate_text used to
    pad T to the batch's raw max prompt length (one compile per novel
    length).  The engine must route through shapes.generate_pad_len."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    eng = InferenceEngine.from_preset(
        "llama-tiny", vocab_size=512,
        rt=RuntimeConfig(max_decode_steps=8, max_seq_len=128))
    assert eng._bucket_prompt(jnp.zeros((2, 13), jnp.int32), 8).shape[1] == 16
    assert eng._bucket_prompt(jnp.zeros((2, 97), jnp.int32), 8).shape[1] == 120
    # An over-budget prompt keeps its raw width so the sequence-budget
    # check raises exactly as it did before bucketing.
    assert eng._bucket_prompt(jnp.zeros((1, 125), jnp.int32), 8).shape[1] == 125


# -- GC5 donation ---------------------------------------------------------

def test_gc5_missing_donation_fires():
    import functools

    @functools.partial(jax.jit)  # donate_argnames FORGOTTEN
    def step(params, cache, x):
        return x + 1, jax.tree.map(lambda c: c + 1, cache)

    big = sds((1024, 64), jnp.float32)  # 256 KiB leaves

    contract = DonationContract(
        "seeded.step", "pkg/step.py", "seeded",
        lambda: (step, [
            ("params", {"w": sds((8, 8), jnp.float32)}),
            ("cache", {"k": big, "v": big}),
            ("x", sds((4,), jnp.float32)),
        ], {}),
        must_donate=("cache",), may_keep=("params",), static_args=())
    findings = donation.check([contract])
    assert _rules(findings) == ["GC501"]
    assert "cache" in findings[0].message


def test_gc5_large_undeclared_buffer_fires():
    import functools

    @functools.partial(jax.jit, donate_argnames=("cache",))
    def step(params, cache, stash, x):
        return x + stash.sum(), jax.tree.map(lambda c: c + 1, cache)

    big = sds((1024, 64), jnp.float32)
    contract = DonationContract(
        "seeded.step", "pkg/step.py", "seeded",
        lambda: (step, [
            ("params", {"w": sds((8, 8), jnp.float32)}),
            ("cache", {"k": big, "v": big}),
            ("stash", big),                  # large, kept, undeclared
            ("x", sds((4,), jnp.float32)),
        ], {}),
        must_donate=("cache",), may_keep=("params",), static_args=())
    findings = donation.check([contract])
    assert _rules(findings) == ["GC502"]
    assert "stash" in findings[0].message


# -- THE tier-1 gate ------------------------------------------------------

def test_repo_is_clean():
    """Zero non-baselined semantic findings over the real registries: every
    op shape/dtype contract, every preset x mesh spec audit, the dtype
    allowlist, the compile-key budgets, and the donation flags."""
    findings = run_all(root=ROOT)
    new, _accepted = split_new(findings, read_baseline(ROOT))
    assert not new, "new graftcheck findings:\n" + "\n".join(
        f.render() for f in new)


def test_checked_in_baseline_is_empty():
    assert read_baseline(ROOT) == {}, (
        "graftcheck_baseline.txt must stay empty — fix contract violations "
        "instead of baselining them")


def test_gc4_gate_pins_decode_compile_keys():
    """Acceptance pin: the decode-step scenario's measured compile keys
    equal its declared bucket count exactly (1), and the admission ladder
    stays within shapes.bucket_count."""
    from tools.graftcheck.contracts import recompile_scenarios

    by_name = {s.name: s for s in recompile_scenarios()}
    decode = by_name["batcher.decode_chunk"]
    assert len(recompile.measure_keys(decode)) == decode.max_keys == 1
    admit = by_name["batcher.admit_row"]
    measured = recompile.measure_keys(admit)
    assert 1 < len(measured) <= admit.max_keys
    assert set(measured.values()) <= set(admit.allowed_widths)


# -- baseline + CLI -------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    from tools.graftlint.core import Finding

    f1 = Finding("GC101", "pkg/op.py", 0, "seeded contract violation")
    write_baseline(tmp_path, [f1, f1])
    baseline = read_baseline(tmp_path)
    assert sum(baseline.values()) == 2  # [x2] multiset round-trip
    new, accepted = split_new([f1, f1, f1], baseline)
    assert len(accepted) == 2 and len(new) == 1


def test_cli_docs_drift_and_write(tmp_path):
    (tmp_path / "README.md").write_text(
        "# x\n<!-- graftcheck:contracts:begin -->\nstale\n"
        "<!-- graftcheck:contracts:end -->\n", encoding="utf-8")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--root", str(tmp_path),
         "--only", "GCD"],
        capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 1
    assert "GCD01" in r.stdout
    subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--root", str(tmp_path),
         "--write-docs"],
        capture_output=True, text=True, cwd=ROOT, check=True)
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--root", str(tmp_path),
         "--only", "GCD"],
        capture_output=True, text=True, cwd=ROOT)
    assert r2.returncode == 0, r2.stdout + r2.stderr


def test_front_door_escalates_stale_baseline_entries(tmp_path, capsys):
    """python -m tools.check: a baseline entry whose finding no longer
    occurs (fixed debt) is an ERROR at the front door, not a warning —
    the prune must land in the same change."""
    from tools import check as front_door

    (tmp_path / "graftlint_baseline.txt").write_text(
        "ghost.py: GL501 wall-clock sleep that was fixed long ago\n",
        encoding="utf-8")
    rc = front_door.main(["--root", str(tmp_path)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "STALE graftlint baseline entry" in err


@pytest.mark.slow
def test_cli_full_run_is_clean():
    """End-to-end CLI over the real repo (subprocess, fresh jax)."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck"],
        capture_output=True, text=True, cwd=ROOT, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
