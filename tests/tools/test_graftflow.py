"""graftflow self-tests: every rule family proven to fire on a seeded
violation, suppressions honored only with a reason, and THE tier-1 gate —
the repo itself must be clean modulo the checked-in (EMPTY) baseline.

Fixture trees use the real scope suffixes (pkg/runtime/batcher.py,
pkg/cluster/protocol.py, ...) so the analyzers treat them exactly like
the shipped package.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[2]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.graftflow import (  # noqa: E402
    load_project, read_baseline, run_project, split_new,
)
from tools.graftflow import (  # noqa: E402
    eventloop, lockorder, protocolflow, resources,
)


def _project(tmp_path: Path, files: dict[str, str]):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    return load_project(tmp_path)


def _rules(findings):
    return sorted(f.rule for f in findings)


# -- GF1xx lock order -------------------------------------------------------

CYCLE_SRC = '''
import threading

class ContinuousBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = None

    def fwd(self):
        with self._lock:
            with self.pool._lock:
                pass

class PagePool:
    def __init__(self):
        self._lock = threading.Lock()
        self.batcher = None

    def rev(self):
        with self._lock:
            with self.batcher._lock:
                pass
'''


def test_lockorder_cycle_fires(tmp_path):
    findings = lockorder.check(
        _project(tmp_path, {"pkg/runtime/batcher.py": CYCLE_SRC}))
    assert _rules(findings) == ["GF101", "GF101"]
    assert any("PagePool._lock" in f.message for f in findings)


LOCK_REGISTRY = '''
LOCK_ORDER: dict[str, str] = {
    "ContinuousBatcher._lock": "outer",
    "PagePool._lock": "inner leaf",
}
'''

ORDER_VIOLATION_SRC = '''
import threading

class ContinuousBatcher:
    def __init__(self):
        self._lock = threading.Lock()

class PagePool:
    def __init__(self):
        self._lock = threading.Lock()
        self.batcher = None

    def rev(self):
        with self._lock:
            with self.batcher._lock:   # inner acquires the OUTER lock
                pass
'''


def test_lockorder_declared_order_violation(tmp_path):
    findings = lockorder.check(_project(tmp_path, {
        "pkg/runtime/faults.py": LOCK_REGISTRY,
        "pkg/runtime/batcher.py": ORDER_VIOLATION_SRC,
    }))
    assert _rules(findings) == ["GF102"]
    assert "LOCK_ORDER" in findings[0].message


INTERPROC_SRC = '''
import threading

class ContinuousBatcher:
    def __init__(self):
        self._lock = threading.Lock()

class PagePool:
    def __init__(self):
        self._lock = threading.Lock()
        self.batcher = None

    def outer(self):
        with self._lock:
            self._grab()

    def _grab(self):
        with self.batcher._lock:
            pass
'''


def test_lockorder_violation_through_the_call_graph(tmp_path):
    """The bad nesting spans a CALL: outer() holds PagePool._lock and
    _grab() acquires the batcher lock — only held-set propagation over
    the call graph sees the edge."""
    findings = lockorder.check(_project(tmp_path, {
        "pkg/runtime/faults.py": LOCK_REGISTRY,
        "pkg/runtime/batcher.py": INTERPROC_SRC,
    }))
    assert _rules(findings) == ["GF102"]
    assert "_grab" in findings[0].message


def test_lockorder_registry_drift(tmp_path):
    findings = lockorder.check(_project(tmp_path, {
        "pkg/runtime/faults.py": (
            'LOCK_ORDER: dict[str, str] = {\n'
            '    "ContinuousBatcher._lock": "real",\n'
            '    "Ghost._lock": "nothing declares this",\n'
            '}\n'
        ),
        "pkg/runtime/batcher.py": (
            "import threading\n"
            "class ContinuousBatcher:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
        ),
    }))
    assert _rules(findings) == ["GF103"]
    assert "Ghost._lock" in findings[0].message


# -- GF2xx event-loop blocking ----------------------------------------------

def test_eventloop_blocking_direct(tmp_path):
    findings = eventloop.check(_project(tmp_path, {
        "pkg/runtime/server.py": (
            "import time\n"
            "class S:\n"
            "    async def handler(self):\n"
            "        time.sleep(0.1)\n"
        ),
    }))
    assert _rules(findings) == ["GF201"]
    assert "time.sleep" in findings[0].message


def test_eventloop_blocking_transitive(tmp_path):
    """The blocking call hides one sync hop below the coroutine — the
    exact PR-7 shape (zlib inside a helper the send path calls)."""
    findings = eventloop.check(_project(tmp_path, {
        "pkg/cluster/protocol.py": (
            "import zlib\n"
            "def pack(b):\n"
            "    return zlib.compress(b)\n"
            "async def send(w, b):\n"
            "    w.write(pack(b))\n"
        ),
    }))
    assert _rules(findings) == ["GF201"]
    assert "via pack" in findings[0].message


def test_eventloop_to_thread_is_off_loop(tmp_path):
    findings = eventloop.check(_project(tmp_path, {
        "pkg/cluster/protocol.py": (
            "import asyncio, zlib\n"
            "def pack(b):\n"
            "    return zlib.compress(b)\n"
            "async def send(w, b):\n"
            "    w.write(await asyncio.to_thread(pack, b))\n"
        ),
    }))
    assert findings == []


def test_eventloop_fire_requires_defer_stall(tmp_path):
    findings = eventloop.check(_project(tmp_path, {
        "pkg/runtime/server.py": (
            "class S:\n"
            "    def __init__(self):\n"
            "        self.faults = None\n"
            "    async def handler(self):\n"
            "        self.faults.fire('x.y')\n"                  # GF202
            "        self.faults.fire('x.y', defer_stall=True)\n"  # ok
        ),
    }))
    assert _rules(findings) == ["GF202"]
    assert "defer_stall" in findings[0].message


# -- GF3xx resource pairing -------------------------------------------------

def test_pages_leak_on_early_return(tmp_path):
    findings = resources.check(_project(tmp_path, {
        "pkg/runtime/batcher.py": (
            "class B:\n"
            "    def admit(self, n, ok):\n"
            "        pages = self.pool.alloc(n)\n"
            "        if not ok:\n"
            "            return None\n"      # leak: pages forgotten
            "        self.rows[0] = pages\n"
        ),
    }))
    assert _rules(findings) == ["GF301"]
    assert "normal exit" in findings[0].message


def test_pages_leak_on_exception_path_and_finally_is_safe(tmp_path):
    findings = resources.check(_project(tmp_path, {
        "pkg/runtime/batcher.py": (
            "class B:\n"
            "    def grow(self, n):\n"
            "        pages = self.pool.alloc(n)\n"
            "        self.audit()\n"          # raises -> leak path
            "        self.rows[1] = pages\n"
            "    def safe(self, n):\n"
            "        pages = self.pool.alloc(n)\n"
            "        try:\n"
            "            self.audit()\n"
            "        finally:\n"
            "            self.pool.release(pages)\n"
        ),
    }))
    assert _rules(findings) == ["GF301"]
    assert "exception exit" in findings[0].message
    assert findings[0].line == 3  # grow's alloc, not safe's


def test_host_tier_swap_handle_leak(tmp_path):
    """KV tiering (GF301 host-tier leg): a swap handle minted by
    park_swap that an exception path forgets is host RAM nothing will
    ever restore or free — and a handle stored onto the resume request
    before anything can raise is clean."""
    findings = resources.check(_project(tmp_path, {
        "pkg/runtime/batcher.py": (
            "class B:\n"
            "    def swap_out(self, row):\n"
            "        handle = self.host_tier.park_swap(row.payload, 2)\n"
            "        self.audit()\n"          # raises -> stranded parcel
            "        row.req.swap_handle = handle\n"
            "    def safe(self, row, resume):\n"
            "        handle = self.host_tier.park_swap(row.payload, 2)\n"
            "        resume.swap_handle = handle\n"
            "        self.audit()\n"
        ),
    }))
    assert _rules(findings) == ["GF301"]
    assert findings[0].line == 3  # swap_out's park, not safe's
    assert "exception exit" in findings[0].message


def test_bare_acquire_needs_release_on_all_paths(tmp_path):
    findings = resources.check(_project(tmp_path, {
        "pkg/runtime/server.py": (
            "class W:\n"
            "    def bad(self):\n"
            "        self._sem.acquire()\n"
            "        self.work()\n"           # raises past the release
            "        self._sem.release()\n"
            "    def good(self):\n"
            "        self._sem.acquire()\n"
            "        try:\n"
            "            self.work()\n"
            "        finally:\n"
            "            self._sem.release()\n"
        ),
    }))
    assert _rules(findings) == ["GF302"]
    assert findings[0].line == 3


def test_registry_cleanup_required_on_exception_paths(tmp_path):
    findings = resources.check(_project(tmp_path, {
        "pkg/runtime/server.py": (
            "class S:\n"
            "    def __init__(self):\n"
            "        # graftflow: cleanup-required\n"
            "        self.reg = {}\n"
            "    def bad(self, k, v):\n"
            "        self.reg[k] = v\n"
            "        self.submit(v)\n"        # raises -> entry stranded
            "    def good(self, k, v):\n"
            "        self.reg[k] = v\n"
            "        try:\n"
            "            self.submit(v)\n"
            "        except Exception:\n"
            "            self.reg.pop(k)\n"
            "            raise\n"
        ),
    }))
    assert _rules(findings) == ["GF303"]
    assert findings[0].line == 6  # bad's registration, not good's


# -- GF4xx protocol completeness --------------------------------------------

def test_frame_without_handler(tmp_path):
    findings = protocolflow.check_frames(_project(tmp_path, {
        "pkg/cluster/protocol.py": (
            'MESSAGE_TYPES = frozenset({"PING", "PONG"})\n'
            "def message(t, payload=None):\n"
            "    return {'type': t, 'payload': payload}\n"
            "def send(w):\n"
            "    w.write(message('PING'))\n"
            "def pong(w):\n"
            "    w.write(message('PONG'))\n"
            "def handle(msg):\n"
            "    return msg.get('type') == 'PING'\n"
        ),
    }))
    assert _rules(findings) == ["GF401"]
    assert "'PONG' has no handler" in findings[0].message


def test_frame_without_sender_and_undeclared_type(tmp_path):
    findings = protocolflow.check_frames(_project(tmp_path, {
        "pkg/cluster/protocol.py": (
            'MESSAGE_TYPES = frozenset({"PING", "LOST"})\n'
            "def message(t, payload=None):\n"
            "    return {'type': t, 'payload': payload}\n"
            "def send(w):\n"
            "    w.write(message('PING'))\n"
            "    w.write(message('PINGG'))\n"   # typo'd type
            "def handle(msg):\n"
            "    t = msg.get('type')\n"
            "    return t == 'PING' or t == 'LOST'\n"
        ),
    }))
    assert _rules(findings) == ["GF401", "GF401"]
    assert any("'LOST' has no sender" in f.message for f in findings)
    assert any("'PINGG'" in f.message for f in findings)


def test_nack_without_metric(tmp_path):
    findings = protocolflow.check_nacks(_project(tmp_path, {
        "pkg/cluster/kv_transfer.py": (
            "def message(t, p):\n"
            "    return {'type': t, 'payload': p}\n"
            "def refuse(w):\n"
            "    w.write(message('KV_ACK', {'ok': False, 'reason': 'no'}))\n"
            "def refuse_counted(w):\n"
            "    METRICS.inc('xfer.nacks')\n"
            "    w.write(message('KV_ACK', {'ok': False, 'reason': 'no'}))\n"
        ),
    }))
    assert _rules(findings) == ["GF402"]
    assert "refuse" in findings[0].message
    assert "refuse_counted" not in findings[0].message


def test_unbounded_retry_loop(tmp_path):
    findings = protocolflow.check_retries(_project(tmp_path, {
        "pkg/cluster/client.py": (
            "async def pump(reader):\n"
            "    while True:\n"
            "        try:\n"
            "            await read_once(reader)\n"
            "        except ConnectionError:\n"
            "            continue\n"                     # forever
            "async def bounded(reader, n):\n"
            "    while True:\n"
            "        try:\n"
            "            await read_once(reader)\n"
            "        except ConnectionError:\n"
            "            n += 1\n"
            "            if n > 3:\n"
            "                return\n"
            "            continue\n"
        ),
    }))
    assert _rules(findings) == ["GF403"]
    assert "pump" in findings[0].message


def test_fault_site_fired_only_from_dead_code(tmp_path):
    files = {
        "pkg/runtime/faults.py": (
            'FAULT_SITES: dict[str, str] = {"x.y": "a drill"}\n'
        ),
        "pkg/runtime/batcher.py": (
            "def _dead(plane):\n"
            "    plane.fire('x.y')\n"
        ),
    }
    findings = protocolflow.check_fire_liveness(_project(tmp_path, files))
    assert _rules(findings) == ["GF404"]
    assert "x.y" in findings[0].message
    # A single reference anywhere makes the drill live again.
    files["pkg/runtime/batcher.py"] += "def boot(p):\n    _dead(p)\n"
    assert protocolflow.check_fire_liveness(_project(tmp_path, files)) == []


# -- suppressions -----------------------------------------------------------

def test_suppressions_require_a_reason(tmp_path):
    """# graftflow: ok(<reason>) suppresses on the line; an EMPTY reason
    is inert; rule-scoped ignore[GFxxx] only matches its rule —
    graftlint's escape semantics, verbatim."""
    findings = eventloop.check(_project(tmp_path, {
        "pkg/runtime/server.py": (
            "import time\n"
            "class S:\n"
            "    async def a(self):\n"
            "        time.sleep(0)  # graftflow: ok(GIL yield, sub-us)\n"
            "    async def b(self):\n"
            "        time.sleep(0)  # graftflow: ok()\n"
            "    async def c(self):\n"
            "        time.sleep(0)  # graftflow: ignore[GF201](yield)\n"
            "    async def d(self):\n"
            "        time.sleep(0)  # graftflow: ignore[GF202](wrong rule)\n"
        ),
    }))
    assert [f.line for f in findings] == [6, 10]  # b (no reason), d (wrong rule)


# -- THE tier-1 gate --------------------------------------------------------

def test_repo_is_clean():
    """Zero non-baselined findings over the real tree.  A new lock-order
    hazard, event-loop block, leak path, or protocol gap fails tier-1
    right here."""
    project = load_project(ROOT)
    findings = run_project(project)
    new, _accepted = split_new(findings, read_baseline(ROOT))
    assert not new, "new graftflow findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_pr7_bug_is_now_a_gate():
    """The PR-7 review catch — a multi-MB zlib running ON the event loop
    inside the KV send path — reproduced as source and caught by GF2
    (the regression this whole tool exists to make structural)."""
    import tempfile

    src = (
        "import zlib\n"
        "async def send_kv_pages(writer, msg):\n"
        "    frame = zlib.compress(msg)\n"   # the PR-7 bug, verbatim shape
        "    writer.write(frame)\n"
    )
    with tempfile.TemporaryDirectory() as td:
        p = Path(td) / "pkg" / "cluster" / "kv_transfer.py"
        p.parent.mkdir(parents=True)
        p.write_text(src, encoding="utf-8")
        findings = eventloop.check(load_project(td))
    assert _rules(findings) == ["GF201"]
    assert "zlib.compress" in findings[0].message


def test_cli_exit_codes(tmp_path):
    # Dirty fixture tree -> exit 1 and the finding on stdout ...
    mod = tmp_path / "pkg" / "runtime" / "server.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(
        "import time\n"
        "class S:\n"
        "    async def h(self):\n"
        "        time.sleep(1)\n", encoding="utf-8")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftflow", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r.returncode == 1
    assert "GF201" in r.stdout
    # ... --baseline-write accepts the debt, after which the gate passes.
    subprocess.run(
        [sys.executable, "-m", "tools.graftflow", "--root", str(tmp_path),
         "--baseline-write"],
        capture_output=True, text=True, cwd=ROOT, check=True,
    )
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftflow", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # --only scoping rejects unknown families.
    r3 = subprocess.run(
        [sys.executable, "-m", "tools.graftflow", "--root", str(tmp_path),
         "--only", "GF9"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r3.returncode == 2
