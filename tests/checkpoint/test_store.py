"""Shard store + quantization tests (mirrors the reference's
tests/model/test_shard_manager.py strategy — tiny real artifacts on a real
filesystem — plus quantization error-bound tests it never had)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint import quantize as q
from distributed_llms_tpu.checkpoint import store
from distributed_llms_tpu.models import model, presets


def test_int8_roundtrip_error_bound():
    x = jax.random.normal(jax.random.key(0), (64, 256))
    qt = q.quantize(x, bits=8, block=64)
    back = q.dequantize(qt)
    # blockwise absmax int8: error <= absmax/127 per block (half a step)
    err = np.abs(np.asarray(back - x))
    bound = np.asarray(jnp.max(jnp.abs(x))) / 127.0
    assert err.max() <= bound + 1e-6
    assert qt.data.dtype == jnp.int8


def test_int4_pack_unpack_exact():
    """Values already on the int4 grid must round-trip exactly."""
    rng = np.random.default_rng(0)
    vals = rng.integers(-7, 8, size=(8, 32)).astype(np.float32)
    qt = q.quantize(jnp.asarray(vals * 0.5), bits=4, block=32)
    back = np.asarray(q.dequantize(qt))
    assert np.allclose(back / 0.5, vals, atol=1e-5)
    assert qt.data.shape == (4, 32)  # packed pairs along the reduction axis


def test_quantize_tree_policy():
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    qt = q.quantize_tree(params, bits=8)
    # norms stay raw, big matmuls quantized
    assert isinstance(qt["blocks"]["attn"]["wq"], q.QuantizedTensor)
    assert not isinstance(qt["blocks"]["ln1"]["scale"], q.QuantizedTensor)
    assert q.tree_bytes(qt) < q.tree_bytes(params) / 2.5


@pytest.mark.parametrize("quantization", [None, "int8", "int4"])
def test_store_roundtrip(tmp_path, quantization):
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    manifest = store.save_shards(
        params, str(tmp_path), num_shards=3, model_config=cfg, quantization=quantization
    )
    assert manifest["num_shards"] == 3
    back = store.reconstruct(str(tmp_path), dtype=jnp.float32)

    flat_a = store._flatten(params)
    flat_b = store._flatten(back)
    assert set(flat_a) == set(flat_b)
    for name in flat_a:
        a = np.asarray(flat_a[name], dtype=np.float32)
        b = np.asarray(flat_b[name], dtype=np.float32)
        if quantization is None:
            np.testing.assert_array_equal(a, b)
        else:
            tol = 0.02 if quantization == "int8" else 0.35
            assert np.abs(a - b).max() <= max(tol * np.abs(a).max(), 1e-6), name


def test_store_partial_load(tmp_path):
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    store.save_shards(params, str(tmp_path), num_shards=4, model_config=cfg)
    manifest = store.load_manifest(str(tmp_path))
    some = store.load_shards(str(tmp_path), shards=[1])
    names = set(store._flatten(some))
    expected = {n for n, m in manifest["params"].items() if m["shard"] == 1}
    assert names == expected and names  # non-empty strict subset


def test_store_missing_shard_file_errors(tmp_path):
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    store.save_shards(params, str(tmp_path), num_shards=2)
    (tmp_path / "shard_1.bin").unlink()
    with pytest.raises(FileNotFoundError, match="shard 1"):
        store.reconstruct(str(tmp_path))


def test_store_npz_storage_roundtrip(tmp_path):
    """v1 (npz) storage stays readable."""
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    store.save_shards(params, str(tmp_path), num_shards=2, storage="npz")
    assert (tmp_path / "shard_0.npz").exists()
    out = store.reconstruct(str(tmp_path))
    a = jax.tree.leaves(params)
    b = jax.tree.leaves(out)
    assert all((x == y).all() for x, y in zip(a, b))


def test_store_raw_detects_corruption(tmp_path):
    """Native raw storage carries per-tensor CRC32: flipping bytes on disk
    fails the load instead of silently feeding garbage weights."""
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    store.save_shards(params, str(tmp_path), num_shards=1)
    path = tmp_path / "shard_0.bin"
    blob = bytearray(path.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    path.write_bytes(bytes(blob))
    with pytest.raises(IOError, match="checksum mismatch"):
        store.reconstruct(str(tmp_path))


def test_native_io_available_and_matches_python():
    """The C++ tier builds in this image; its reads match the fallback."""
    import zlib

    from distributed_llms_tpu import native

    assert native.available(), "native build failed (g++ is in the image)"
    data = b"x" * 100_001
    assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_store_generation_after_roundtrip(tmp_path):
    """End-to-end: params -> int8 store -> reconstruct -> same greedy tokens."""
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = presets.get_preset("gpt2-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    store.save_shards(params, str(tmp_path), num_shards=2, quantization="int8")
    back = store.reconstruct(str(tmp_path), dtype=jnp.float32)
    prompt = jnp.array([[5, 23, 90, 3]], dtype=jnp.int32)
    lens = jnp.array([4], dtype=jnp.int32)
    a = gen_lib.generate_tokens(params, cfg, prompt, lens, jax.random.key(0), max_new_tokens=4)
    b = gen_lib.generate_tokens(back, cfg, prompt, lens, jax.random.key(0), max_new_tokens=4)
    # int8 is lossy but a tiny random model's greedy path should mostly agree
    assert np.asarray(a).shape == np.asarray(b).shape


def test_fetch_model_local_dir(tmp_path):
    from distributed_llms_tpu.checkpoint.download import fetch_model

    assert fetch_model(str(tmp_path)) == str(tmp_path)


def test_fetch_model_offline_errors():
    from distributed_llms_tpu.checkpoint.download import fetch_model

    with pytest.raises(RuntimeError, match="offline"):
        fetch_model("definitely/not-a-local-path-model")
