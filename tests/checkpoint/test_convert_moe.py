"""Mixtral (MoE) HF checkpoint conversion, golden-tested against the torch
reference (the strategy SURVEY §4 prescribes: tiny-real-artifact fixtures,
no network)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint import convert
from distributed_llms_tpu.models import model as model_lib

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_mixtral():
    cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4, num_experts_per_tok=2,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    return cfg, transformers.MixtralForCausalLM(cfg).eval()


def test_mixtral_config_from_hf():
    hf_cfg, _ = _tiny_mixtral()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.family == "llama"
    assert cfg.num_experts == 4
    assert cfg.num_experts_per_token == 2


def test_mixtral_convert_matches_torch_argmax():
    hf_cfg, model = _tiny_mixtral()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    # Lossless capacity for an exact comparison (HF computes all experts
    # per token with no capacity drops).
    cfg = dataclasses.replace(cfg, dtype="float32", moe_capacity_factor=8.0)
    sd = convert.torch_state_dict_to_numpy(model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    assert params["blocks"]["mlp"]["w_gate"].shape == (2, 4, 32, 56)

    toks = np.array([[3, 17, 9, 41, 2, 77, 5, 11]], dtype=np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(toks.astype(np.int64))).logits.numpy()
    logits, _ = model_lib.forward(params, cfg, jnp.asarray(toks))
    ours = np.asarray(logits)
    assert (ours.argmax(-1) == ref.argmax(-1)).all()
    np.testing.assert_allclose(ours, ref, atol=2e-2, rtol=2e-2)
