"""Model forward tests: shapes, KV-cache consistency, and golden parity
against torch transformers (randomly-initialized tiny models — no downloads,
mirroring the reference's patched-hub test technique, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.models import model, presets
from distributed_llms_tpu.checkpoint import convert


@pytest.mark.parametrize("name", ["gpt2-tiny", "llama-tiny", "opt-tiny", "neox-tiny"])
def test_forward_shapes(name):
    cfg = presets.get_preset(name)
    params = model.init_params(jax.random.key(0), cfg)
    toks = jnp.array([[1, 2, 3, 4, 5], [5, 4, 3, 2, 1]], dtype=jnp.int32)
    logits, cache = model.forward(params, cfg, toks)
    assert logits.shape == (2, 5, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None


@pytest.mark.parametrize("name", ["gpt2-tiny", "llama-tiny", "opt-tiny", "neox-tiny"])
def test_kv_cache_matches_full_forward(name):
    cfg = presets.get_preset(name)
    params = model.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size, dtype=jnp.int32)

    full_logits, _ = model.forward(params, cfg, toks)

    # prefill 6 tokens, then decode 3 incrementally
    cache = model.init_cache(cfg, 2, 16)
    pre_logits, cache = model.forward(params, cfg, toks[:, :6], cache=cache, cache_index=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(full_logits[:, :6]), np.asarray(pre_logits), rtol=1e-4, atol=1e-4)
    for t in range(6, 9):
        step_logits, cache = model.forward(
            params, cfg, toks[:, t : t + 1], cache=cache, cache_index=jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(full_logits[:, t]), np.asarray(step_logits[:, 0]), rtol=1e-3, atol=1e-3
        )


def test_causality():
    """Changing a future token must not affect past logits."""
    cfg = presets.get_preset("llama-tiny")
    params = model.init_params(jax.random.key(0), cfg)
    a = jnp.array([[1, 2, 3, 4, 5, 6]], dtype=jnp.int32)
    b = a.at[0, 5].set(99)
    la, _ = model.forward(params, cfg, a)
    lb, _ = model.forward(params, cfg, b)
    np.testing.assert_allclose(np.asarray(la[:, :5]), np.asarray(lb[:, :5]), atol=1e-5)
    assert np.abs(np.asarray(la[:, 5]) - np.asarray(lb[:, 5])).max() > 1e-3


def _hf_gpt2_pair():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=3, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf_model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_llama_pair():
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=97, hidden_size=32, intermediate_size=88, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rms_norm_eps=1e-5, tie_word_embeddings=False, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_opt_pair():
    import torch
    from transformers import OPTConfig, OPTForCausalLM

    hf_cfg = OPTConfig(
        vocab_size=97, hidden_size=32, ffn_dim=88, num_hidden_layers=3,
        num_attention_heads=4, max_position_embeddings=64,
        activation_function="relu", do_layer_norm_before=True,
        word_embed_proj_dim=32, dropout=0.0, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = OPTForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_qwen2_pair():
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=97, hidden_size=32, intermediate_size=88,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attention_dropout=0.0,
        use_sliding_window=False,
    )
    torch.manual_seed(0)
    hf_model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.qkv_bias  # Qwen2's delta from llama
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    assert "bq" in params["blocks"]["attn"]
    return hf_model, cfg, params


def _hf_gemma_pair():
    import torch
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=97, hidden_size=32, intermediate_size=88,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,  # explicit: heads * head_dim != hidden is Gemma-legal
        max_position_embeddings=64, rms_norm_eps=1e-6,
        hidden_activation="gelu_pytorch_tanh", attention_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = GemmaForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.gate_act == "gelu_tanh" and cfg.norm_plus_one
    assert cfg.head_dim_ == 16 and cfg.embed_scale == 32.0**0.5
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_llama31_pair():
    """Llama-3.1-style rope_scaling (rope_type llama3): the tiny
    original_max_position_embeddings forces several frequencies into the
    scaled and smoothed bands, so the piecewise rescale is live."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=97, hidden_size=32, intermediate_size=88,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attention_dropout=0.0,
        rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16},
    )
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.rope_scaling_factor == 8.0 and cfg.rope_original_max_len == 16
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_phi3_pair():
    import torch
    from transformers import Phi3Config, Phi3ForCausalLM

    hf_cfg = Phi3Config(
        vocab_size=97, hidden_size=32, intermediate_size=88,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attention_dropout=0.0, resid_pdrop=0.0,
        embd_pdrop=0.0, sliding_window=3, attn_implementation="eager",
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
    )
    torch.manual_seed(0)
    hf_model = Phi3ForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    # Phi-3's deltas from llama: fused projections (split at convert) and
    # the sliding window.
    assert cfg.sliding_window == 3 and not cfg.qkv_bias
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_neox_pair(parallel=True):
    """GPT-NeoX/Pythia: interleaved fused qkv, PARTIAL rotary (pct 0.25 of
    head_dim 16 = 4 rotated dims), parallel residual (the NeoX default)."""
    import torch
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=97, hidden_size=64, intermediate_size=176,
        num_hidden_layers=3, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=parallel, hidden_act="gelu",
        layer_norm_eps=1e-5, tie_word_embeddings=False,
        attention_dropout=0.0, hidden_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = GPTNeoXForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.family == "neox" and cfg.rotary_pct == 0.25
    assert cfg.parallel_residual is parallel
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": "float32"})
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    return hf_model, cfg, params


def _hf_neox_seq_pair():
    return _hf_neox_pair(parallel=False)


@pytest.mark.parametrize(
    "maker",
    [_hf_gpt2_pair, _hf_llama_pair, _hf_opt_pair, _hf_qwen2_pair,
     _hf_gemma_pair, _hf_phi3_pair, _hf_llama31_pair, _hf_neox_pair,
     _hf_neox_seq_pair],
    ids=["gpt2", "llama", "opt", "qwen2", "gemma", "phi3", "llama31",
         "neox", "neox-seq"],
)
def test_golden_parity_vs_transformers(maker):
    import torch

    hf_model, cfg, params = maker()
    toks = np.array([[3, 14, 15, 92, 65, 35], [8, 9, 79, 3, 2, 38]], dtype=np.int64)
    with torch.no_grad():
        ref = hf_model(torch.tensor(toks)).logits.float().numpy()
    ours, _ = model.forward(params, cfg, jnp.asarray(toks, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-3)


def test_config_from_hf_rejects_unknown():
    with pytest.raises(ValueError):
        convert.config_from_hf({"model_type": "mamba"})


def test_config_from_hf_neox_rejects_tied_embeddings():
    with pytest.raises(ValueError, match="tied"):
        convert.config_from_hf(dict(
            model_type="gpt_neox", vocab_size=100, hidden_size=64,
            intermediate_size=176, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            tie_word_embeddings=True,
        ))


def test_config_from_hf_rejects_non_llama3_rope_scaling():
    base = dict(
        model_type="llama", vocab_size=100, hidden_size=32,
        intermediate_size=88, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=4096,
    )
    for rtype in ("linear", "dynamic", "yarn"):
        with pytest.raises(ValueError, match="rope_scaling"):
            convert.config_from_hf(
                {**base, "rope_scaling": {"rope_type": rtype, "factor": 2.0}}
            )
    for mt in ("mistral", "qwen2", "gemma"):
        with pytest.raises(ValueError, match="rope_scaling"):
            convert.config_from_hf(
                {**base, "model_type": mt,
                 "rope_scaling": {"rope_type": "yarn", "factor": 2.0}}
            )
    # Malformed llama3 blocks fail loudly too — a zero-width smooth band
    # would serve NaN frequencies, a missing factor a bare KeyError.
    with pytest.raises(ValueError, match="factor"):
        convert.config_from_hf(
            {**base, "rope_scaling": {"rope_type": "llama3"}}
        )
    with pytest.raises(ValueError, match="high_freq_factor"):
        convert.config_from_hf(
            {**base, "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                                      "low_freq_factor": 2.0,
                                      "high_freq_factor": 2.0}}
        )


def test_config_from_hf_phi3_rejects_longrope():
    base = dict(
        model_type="phi3", vocab_size=100, hidden_size=32,
        intermediate_size=88, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=4096,
        sliding_window=2047,
    )
    assert convert.config_from_hf(base).sliding_window == 2047
    with pytest.raises(ValueError, match="rope_scaling"):
        convert.config_from_hf(
            {**base, "rope_scaling": {"type": "longrope",
                                      "short_factor": [1.0]}}
        )
    with pytest.raises(ValueError, match="partial_rotary"):
        convert.config_from_hf({**base, "partial_rotary_factor": 0.5})
