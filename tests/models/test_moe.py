"""Mixture-of-experts layer + expert parallelism (net-new vs the reference:
SURVEY §2.3 lists MoE as absent)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import MeshConfig, ModelConfig
from distributed_llms_tpu.models import layers, model as model_lib
from distributed_llms_tpu.models.presets import get_preset


def _moe_params(rng, d, e, f, dtype=jnp.float32):
    ks = jax.random.split(rng, 4)
    return {
        "router": jax.random.normal(ks[0], (d, e), dtype) * d**-0.5,
        "w_gate": jax.random.normal(ks[1], (e, d, f), dtype) * d**-0.5,
        "w_up": jax.random.normal(ks[2], (e, d, f), dtype) * d**-0.5,
        "w_down": jax.random.normal(ks[3], (e, f, d), dtype) * f**-0.5,
    }


def _reference_moe(x, p, k):
    """Per-token explicit top-k expert mix — no capacity, no dispatch
    tensors.  Ground truth when nothing overflows."""
    b, t, d = x.shape
    xf = np.asarray(x, np.float32).reshape(-1, d)
    logits = xf @ np.asarray(p["router"], np.float32)
    out = np.zeros_like(xf)
    for s in range(xf.shape[0]):
        idx = np.argsort(-logits[s])[:k]
        g = np.exp(logits[s][idx] - logits[s][idx].max())
        g = g / g.sum()
        for w, ei in zip(g, idx):
            gate = xf[s] @ np.asarray(p["w_gate"])[ei]
            up = xf[s] @ np.asarray(p["w_up"])[ei]
            h = (gate / (1 + np.exp(-gate))) * up  # silu(gate) * up
            out[s] += w * (h @ np.asarray(p["w_down"])[ei])
    return out.reshape(b, t, d)


def test_moe_matches_per_token_reference_when_lossless():
    cfg = ModelConfig(
        family="llama", num_experts=4, num_experts_per_token=2,
        moe_capacity_factor=4.0,  # capacity >= all tokens: nothing dropped
    )
    d, e, f = 16, 4, 32
    p = _moe_params(jax.random.key(0), d, e, f)
    x = jax.random.normal(jax.random.key(1), (2, 5, d), jnp.float32)
    out, aux = layers.moe_swiglu(x, p, cfg)
    ref = _reference_moe(x, p, 2)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)
    # balanced-ish random routing with nothing dropped: aux near 1
    assert 0.5 < float(aux) < 2.0


def test_moe_capacity_drops_tokens_to_zero():
    # capacity factor so small every expert holds 1 slot; dropped tokens
    # contribute exactly zero (GShard semantics), output stays finite.
    cfg = ModelConfig(
        family="llama", num_experts=2, num_experts_per_token=1,
        moe_capacity_factor=0.01,
    )
    d, e, f = 8, 2, 16
    p = _moe_params(jax.random.key(0), d, e, f)
    x = jax.random.normal(jax.random.key(1), (1, 16, d), jnp.float32)
    out, _ = layers.moe_swiglu(x, p, cfg)
    assert bool(jnp.all(jnp.isfinite(out)))
    zero_rows = int(jnp.sum(jnp.all(out[0] == 0.0, axis=-1)))
    assert zero_rows >= 14  # 16 tokens, 2 experts x 1 slot

def test_moe_model_forward_and_grad():
    cfg = get_preset("moe-tiny")
    params = model_lib.init_params(jax.random.key(0), cfg)
    assert "router" in params["blocks"]["mlp"]
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size, dtype=jnp.int32)
    logits, _ = model_lib.forward(params, cfg, toks)
    assert logits.shape == (2, 9, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))

    def loss(p):
        lg, _, aux = model_lib.forward(p, cfg, toks, return_aux=True)
        return jnp.mean(lg**2) + cfg.moe_aux_loss_weight * aux

    g = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(g):
        assert bool(jnp.all(jnp.isfinite(leaf)))
    # aux must reach the router weights (load-balance gradient signal)
    assert float(jnp.max(jnp.abs(g["blocks"]["mlp"]["router"]))) > 0


def test_moe_trainer_includes_aux_loss():
    from distributed_llms_tpu.runtime import train

    cfg = get_preset("moe-tiny")
    params = model_lib.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size, dtype=jnp.int32)
    loss_with = train.lm_loss(params, cfg, toks)
    loss_no_aux = train.lm_loss(
        params, dataclasses.replace(cfg, moe_aux_loss_weight=0.0), toks
    )
    assert float(loss_with) != float(loss_no_aux)


def test_moe_rejects_gpt2():
    cfg = ModelConfig(family="gpt2", num_experts=4)
    with pytest.raises(ValueError, match="llama"):
        model_lib.init_params(jax.random.key(0), cfg)


def test_moe_expert_parallel_matches_single_device():
    from distributed_llms_tpu.parallel.api import make_parallel_model

    cfg = get_preset("moe-tiny")
    params = model_lib.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size, dtype=jnp.int32)
    ref, _ = model_lib.forward(params, cfg, toks)

    pm = make_parallel_model(cfg, MeshConfig(data=2, expert=4), devices=jax.devices())
    sp = pm.shard_params(params)
    # expert-stacked weights really live sharded over the expert axis
    spec = sp["blocks"]["mlp"]["w_gate"].sharding.spec
    assert "expert" in str(spec)
    out, _ = pm.forward(sp, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_moe_generate_decodes():
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = get_preset("moe-tiny")
    params = model_lib.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 7), 0, cfg.vocab_size, dtype=jnp.int32)
    lens = jnp.array([4, 7], dtype=jnp.int32)
    out = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(2), max_new_tokens=5
    )
    assert out.shape == (2, 5)
    assert bool(jnp.all(out >= 0)) and bool(jnp.all(out < cfg.vocab_size))
