"""Sliding-window attention (Mistral family).

Reference surface: the upstream framework has no windowed-attention model at
all (its compute is a placeholder matmul, src/worker/node.py:24-32); this
covers the Mistral architecture the way SURVEY §4's golden-parity strategy
covers every family — randomly-initialized tiny HF models, no downloads.

Core invariants:
- a 1-layer windowed model's last-position logits over a long sequence equal
  a run over only the last `window` tokens (RoPE positions preserved) — the
  mask, not the cache size, bounds the span;
- cached decode matches the no-cache forward token-for-token past the window;
- golden parity vs torch transformers' MistralForCausalLM with the window
  active (seq > window);
- the continuous batcher serves windowed models via masks (ragged/paged
  kernels, which read the full prefix, are refused loudly).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint import convert
from distributed_llms_tpu.core.config import ModelConfig
from distributed_llms_tpu.models import model, presets


def _windowed_tiny(window=4, num_layers=4):
    return presets.get_preset("llama-tiny", sliding_window=window,
                              num_layers=num_layers)


def test_window_bounds_attention_span_one_layer():
    """1 layer ⇒ the receptive field IS the window: last-position logits over
    the full sequence must equal a forward over only the last `window` tokens
    at their true RoPE positions."""
    cfg = _windowed_tiny(window=4, num_layers=1)
    params = model.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 10), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    full, _ = model.forward(params, cfg, toks)
    tail = toks[:, 6:10]
    positions = jnp.broadcast_to(jnp.arange(6, 10, dtype=jnp.int32), (2, 4))
    tail_logits, _ = model.forward(params, cfg, tail, positions=positions)
    np.testing.assert_allclose(
        np.asarray(full[:, -1]), np.asarray(tail_logits[:, -1]),
        rtol=1e-4, atol=1e-4,
    )


def test_windowed_differs_from_global():
    cfg = _windowed_tiny(window=3)
    cfg_global = dataclasses.replace(cfg, sliding_window=None)
    params = model.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    lw, _ = model.forward(params, cfg, toks)
    lg, _ = model.forward(params, cfg_global, toks)
    # Positions inside the first window agree; past it they must diverge.
    np.testing.assert_allclose(np.asarray(lw[:, :3]), np.asarray(lg[:, :3]),
                               rtol=1e-4, atol=1e-4)
    assert np.abs(np.asarray(lw[:, -1]) - np.asarray(lg[:, -1])).max() > 1e-3


def test_kv_cache_matches_full_forward_windowed():
    """Prefill + incremental decode through the cache must reproduce the
    no-cache windowed forward even past the window boundary."""
    cfg = _windowed_tiny(window=4)
    params = model.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    full_logits, _ = model.forward(params, cfg, toks)
    cache = model.init_cache(cfg, 2, 16)
    pre, cache = model.forward(params, cfg, toks[:, :6], cache=cache,
                               cache_index=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(full_logits[:, :6]), np.asarray(pre),
                               rtol=1e-4, atol=1e-4)
    for t in range(6, 9):
        step, cache = model.forward(params, cfg, toks[:, t:t + 1], cache=cache,
                                    cache_index=jnp.int32(t))
        np.testing.assert_allclose(np.asarray(full_logits[:, t]),
                                   np.asarray(step[:, 0]), rtol=1e-3, atol=1e-3)


@pytest.mark.fragile_xla_cpu
def test_flash_impl_matches_windowed_dot():
    """attn_impl='flash' on a windowed model rides the kernel's window
    band (ops/flash.py window=) for no-cache forwards AND cached prefill,
    matching the masked dot path exactly; the windowed generate loop stays
    token-identical too (decode steps keep the dense path)."""
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = _windowed_tiny(window=3)
    cfg_flash = dataclasses.replace(cfg, attn_impl="flash")
    params = model.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    a, _ = model.forward(params, cfg, toks)
    b, _ = model.forward(params, cfg_flash, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-5)
    # Ragged generate: windowed flash prefill into the padded cache must
    # emit the same tokens as the dot path (window crossed mid-decode).
    prompt = jnp.asarray([[7, 1, 9, 0, 0, 0], [4] * 6], jnp.int32)
    lens = jnp.asarray([3, 6], jnp.int32)
    ref = gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(2), max_new_tokens=8,
    )
    out = gen_lib.generate_tokens(
        params, cfg_flash, prompt, lens, jax.random.key(2), max_new_tokens=8,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_golden_parity_vs_transformers_mistral():
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=97, hidden_size=32, intermediate_size=88,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attention_dropout=0.0,
        sliding_window=3, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = MistralForCausalLM(hf_cfg).eval()
    cfg = convert.config_from_hf(hf_cfg.to_dict())
    assert cfg.sliding_window == 3  # the Mistral delta from llama
    cfg = dataclasses.replace(cfg, dtype="float32")
    sd = convert.torch_state_dict_to_numpy(hf_model.state_dict())
    params = convert.convert_state_dict(sd, cfg)
    toks = np.array([[3, 14, 15, 92, 65, 35], [8, 9, 79, 3, 2, 38]],
                    dtype=np.int64)
    with torch.no_grad():
        ref = hf_model(torch.tensor(toks)).logits.float().numpy()
    ours, _ = model.forward(params, cfg, jnp.asarray(toks, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-3)


def test_config_from_hf_mistral_window_mapping():
    base = dict(
        model_type="mistral", vocab_size=32000, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=1024,
    )
    cfg = convert.config_from_hf({**base, "sliding_window": 256})
    assert cfg.family == "llama" and cfg.sliding_window == 256
    # v0.2+ style: null window -> global attention.
    assert convert.config_from_hf({**base, "sliding_window": None}).sliding_window is None
    # window >= max_len degenerates to global; keep the cheap mask.
    assert convert.config_from_hf({**base, "sliding_window": 4096}).sliding_window is None


def test_invalid_window_combos_rejected():
    with pytest.raises(ValueError, match="ring"):
        presets.get_preset("llama-tiny", sliding_window=4, attn_impl="ring")
    # ragged_decode + window COMPOSES since the kernel carries the window
    # band (ops/decode_attn.py) — only seq-parallel impls still reject.
    cfg = presets.get_preset("llama-tiny", sliding_window=4,
                             ragged_decode=True)
    assert cfg.sliding_window == 4 and cfg.ragged_decode
    with pytest.raises(ValueError, match="sliding_window must be"):
        ModelConfig(family="llama", sliding_window=0)


def test_batcher_serves_windowed_model_exactly():
    """Mixed budgets through the batcher on a windowed model must match solo
    decodes token-for-token (the window rides the batcher's per-row masks)."""
    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    cfg = presets.get_preset("llama-tiny", vocab_size=512, sliding_window=5)
    params = model.init_params(jax.random.key(0), cfg)
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=64, chunk_steps=4)
    # Off-TPU default is the dense fallback; under kernel/interpret modes
    # windowed models now ride the ragged kernel's window band (exactness
    # under interpret is pinned by tests/ops/test_decode_attn.py).
    reqs = [([7, 1, 9, 4, 2, 8, 3], 8), ([4, 4, 4], 6), ([11, 12], 10)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    for rid, (ids, n) in zip(rids, reqs):
        out = gen_lib.generate_tokens(
            params, cfg, jnp.asarray([ids], jnp.int32),
            jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
            max_new_tokens=n, eos_id=-1, pad_id=0,
        )
        assert res[rid] == np.asarray(out)[0].tolist()


def test_ragged_batch_windowed_decode_matches_solo():
    """REGRESSION (r4 review): the right-padded generate layout puts
    generated slot T+j at position len+j; the window mask must compare
    POSITIONS, not slots, or short rows in a ragged batch attend (T - len)
    positions past the window.  Each padded row must match its own solo
    (pad-free) run exactly."""
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = presets.get_preset("llama-tiny", vocab_size=512, sliding_window=3)
    params = model.init_params(jax.random.key(0), cfg)
    prompts = [[7, 1, 9], [4, 4, 4, 4, 4, 4, 4, 4]]
    t = max(len(p) for p in prompts)
    padded = jnp.asarray([p + [0] * (t - len(p)) for p in prompts], jnp.int32)
    lens = jnp.asarray([len(p) for p in prompts], jnp.int32)
    batch = np.asarray(gen_lib.generate_tokens(
        params, cfg, padded, lens, jax.random.key(1), max_new_tokens=12,
    ))
    for i, p in enumerate(prompts):
        solo = np.asarray(gen_lib.generate_tokens(
            params, cfg, jnp.asarray([p], jnp.int32),
            jnp.asarray([len(p)], jnp.int32), jax.random.key(1),
            max_new_tokens=12,
        ))
        np.testing.assert_array_equal(batch[i], solo[0])


@pytest.mark.fragile_xla_cpu
def test_ragged_windowed_speculative_matches_generate():
    """Same regression through the speculative loop (shares the layout)."""
    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.speculative import (
        speculative_generate_tokens,
    )

    cfg = presets.get_preset("llama-tiny", vocab_size=512, sliding_window=3)
    params = model.init_params(jax.random.key(0), cfg)
    dcfg = presets.get_preset("llama-tiny", vocab_size=512, num_layers=2)
    dparams = model.init_params(jax.random.key(5), dcfg)
    prompt = jnp.asarray([[7, 1, 9, 0, 0, 0, 0, 0], [4] * 8], jnp.int32)
    lens = jnp.asarray([3, 8], jnp.int32)
    want = np.asarray(gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(1), max_new_tokens=12,
    ))
    got = speculative_generate_tokens(
        params, cfg, dparams, dcfg, prompt, lens, k=3, max_new_tokens=12,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_windowed_ragged_session_matches_solo():
    """Multi-turn sessions use the same padded (gapped) layout as generate —
    the per-turn slot->position map is session STATE (slot_positions).  A
    ragged 2-row session must match per-row solo sessions exactly (solo B=1
    has no pad gap, so it is layout-independent ground truth)."""
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    rt = RuntimeConfig(max_decode_steps=6, max_seq_len=128)
    eng = InferenceEngine.from_preset(
        "llama-tiny", rt, vocab_size=512, sliding_window=5
    )
    turn1 = ["hello world", "hi"]
    turn2 = ["more text", "y"]
    sid, r1 = eng.start_session(turn1, max_new_tokens=6)
    r2 = eng.continue_session(sid, turn2, max_new_tokens=6)
    solo = InferenceEngine(eng.cfg, eng.rt, eng.params)
    for i in range(2):
        ssid, s1 = solo.start_session([turn1[i]], max_new_tokens=6)
        s2 = solo.continue_session(ssid, [turn2[i]], max_new_tokens=6)
        np.testing.assert_array_equal(r1.tokens[i], s1.tokens[0])
        np.testing.assert_array_equal(r2.tokens[i], s2.tokens[0])
        solo.end_session(ssid)


# The two mesh-decode tests below compile big pipelined/GSPMD programs —
# fresh-process via tests/runtime/test_isolated.py (shared marker).
@pytest.mark.fragile_xla_cpu
def test_mesh_windowed_decode_matches_single_device():
    """Mesh decode of sliding-window models threads key_positions through
    the adapters (parallel/api.py), so a ragged batch on a dp x tp mesh
    must match single-device tokens exactly — the window must NOT widen by
    each row's pad amount.  Mesh training stays fine too (cache=None
    forward windows in position space)."""
    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model
    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime import train

    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, sliding_window=3, dtype="float32"
    )
    params = model.init_params(jax.random.key(0), cfg)
    # Ragged lengths: row pads differ, so a slot-space window would widen
    # differently per row; 10 new tokens cross the window boundary.
    prompt = jnp.asarray([[7, 1, 9, 0, 0, 0, 0, 0], [4] * 8], jnp.int32)
    lens = jnp.asarray([3, 8], jnp.int32)
    ref = np.asarray(gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(1), max_new_tokens=10,
    ))
    pm = make_parallel_model(cfg, MeshConfig(data=2, model=2),
                             devices=jax.devices()[:4])
    out = gen_lib.generate_tokens(
        pm.shard_params(params), cfg, prompt, lens, jax.random.key(1),
        max_new_tokens=10, forward_fn=pm.as_forward_fn(),
        make_cache=pm.as_make_cache(),
    )
    np.testing.assert_array_equal(np.asarray(out), ref)

    trainer = train.Trainer(cfg, train.default_optimizer(1e-3), parallel=pm)
    step = trainer.make_step()
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    _, _, loss = step(pm.shard_params(params), trainer.init(params), toks,
                      None)
    assert jnp.isfinite(loss)


@pytest.mark.fragile_xla_cpu
def test_pipelined_windowed_decode_matches_single_device():
    """The pipelined paths derive the slot->position map too: per-token
    schedule (pipeline_blocks) and the fused wavefront (pipeline_decode)
    both match single-device windowed decode exactly."""
    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, sliding_window=3, num_layers=4,
        dtype="float32",
    )
    params = model.init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray([[7, 1, 9, 0, 0, 0, 0, 0], [4] * 8], jnp.int32)
    lens = jnp.asarray([3, 8], jnp.int32)
    ref = np.asarray(gen_lib.generate_tokens(
        params, cfg, prompt, lens, jax.random.key(1), max_new_tokens=8,
    ))
    pm = make_parallel_model(cfg, MeshConfig(pipe=2), num_microbatches=2,
                             devices=jax.devices()[:2])
    sharded = pm.shard_params(params)
    out = gen_lib.generate_tokens(
        sharded, cfg, prompt, lens, jax.random.key(1), max_new_tokens=8,
        forward_fn=pm.as_forward_fn(), make_cache=pm.as_make_cache(),
    )
    np.testing.assert_array_equal(np.asarray(out), ref)
    fused = gen_lib.generate_tokens(
        sharded, cfg, prompt, lens, jax.random.key(1), max_new_tokens=8,
        forward_fn=pm.as_forward_fn(), make_cache=pm.as_make_cache(),
        decode_fn=pm.as_decode_fn(),
    )
    np.testing.assert_array_equal(np.asarray(fused), ref)


def test_seq_parallel_windowed_decode_refuses():
    """Ring/Ulysses seq-parallel decode is causal-only (no window bound) —
    the adapters must refuse windowed models loudly."""
    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.parallel.api import make_parallel_model

    cfg = presets.get_preset("llama-tiny", sliding_window=4)
    pm = make_parallel_model(cfg, MeshConfig(seq=2), devices=jax.devices()[:2])
    for entry in (pm.as_forward_fn, pm.as_make_cache, pm.as_decode_fn):
        with pytest.raises(ValueError, match="sequence-parallel"):
            entry()


def test_paged_batcher_refuses_windowed_model():
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    cfg = presets.get_preset("llama-tiny", sliding_window=4)
    params = model.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="sliding-window"):
        ContinuousBatcher(cfg, params, batch_slots=2, max_len=64,
                          paged_pages=5, page_size=16)
