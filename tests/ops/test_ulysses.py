"""Ulysses (all-to-all head-scatter) sequence parallelism on the fake mesh.

Same strategy as the ring tests: exercise the real collective on 8 fake CPU
devices — identical code path to a TPU slice over ICI (SURVEY §4's missing
distributed-test layer)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llms_tpu.core.config import MeshConfig, ModelConfig
from distributed_llms_tpu.core import jaxcompat
from distributed_llms_tpu.core.mesh import mesh_from_devices
from distributed_llms_tpu.models import layers, model as model_lib
from distributed_llms_tpu.ops import ulysses


def _reference(q, k, v, positions, causal, q_per_kv):
    kf = layers.repeat_kv(k, q_per_kv)
    vf = layers.repeat_kv(v, q_per_kv)
    mask = layers.causal_mask(positions, positions) if causal else None
    return layers.dot_product_attention(q, kf, vf, mask)


def _run(mesh, q, k, v, positions, causal=True):
    sh = P(None, "seq", None, None)
    ps = P(None, "seq")
    return jaxcompat.shard_map(
        lambda q, k, v, p: ulysses.ulysses_attention(
            q, k, v, p, axis_name="seq", causal=causal
        ),
        mesh=mesh,
        in_specs=(sh, sh, sh, ps),
        out_specs=sh,
        axis_names={"seq"},
    )(q, k, v, positions)


@pytest.mark.parametrize(
    "seq_devices,heads,kv_heads,causal",
    [
        (4, 8, 4, True),
        (4, 8, 4, False),
        (2, 4, 2, True),
        (8, 8, 8, True),
    ],
)
def test_ulysses_matches_full_attention(seq_devices, heads, kv_heads, causal):
    mesh = mesh_from_devices({"seq": seq_devices}, jax.devices()[:seq_devices])
    b, t, d = 2, 32, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, t, heads, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, kv_heads, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, kv_heads, d)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    out = _run(mesh, q, k, v, positions, causal)
    want = _reference(q, k, v, positions, causal, heads // kv_heads)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = mesh_from_devices({"seq": 4}, jax.devices()[:4])
    b, t, d = 1, 8, 4
    q = jnp.ones((b, t, 8, d), jnp.float32)
    k = jnp.ones((b, t, 2, d), jnp.float32)  # kvh=2 not divisible by seq=4
    v = jnp.ones((b, t, 2, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    with pytest.raises(ValueError, match="ring"):
        _run(mesh, q, k, v, positions)


def test_ulysses_grad():
    mesh = mesh_from_devices({"seq": 4}, jax.devices()[:4])
    b, t, h, d = 1, 16, 4, 8
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    def loss(q, k, v):
        return jnp.sum(_run(mesh, q, k, v, positions) ** 2)

    g = jax.jit(jax.grad(loss))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, positions, True, 1) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=1e-4)


def test_parallel_model_ulysses_forward_matches_single_device():
    from distributed_llms_tpu.parallel.api import make_parallel_model

    cfg = ModelConfig(
        family="llama", vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=4, max_seq_len=64,
        dtype="float32", attn_impl="ulysses",
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128, dtype=jnp.int32)

    ref_cfg = dataclasses.replace(cfg, attn_impl="dot")
    ref, _ = model_lib.forward(params, ref_cfg, tokens)

    pm = make_parallel_model(cfg, MeshConfig(data=2, seq=4), devices=jax.devices())
    sp = pm.shard_params(params)
    out, _ = pm.forward(sp, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)
