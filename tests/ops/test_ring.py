"""Ring attention vs. full attention on the 8-device fake mesh.

The reference has zero distributed tests and zero sequence parallelism
(SURVEY §4, §5.7); this exercises the real ppermute ring on 8 fake CPU
devices — the same code path a TPU slice runs over ICI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core import jaxcompat
from distributed_llms_tpu.core.mesh import mesh_from_devices
from distributed_llms_tpu.models import layers
from distributed_llms_tpu.ops import ring


def _reference(q, k, v, positions, causal, q_per_kv):
    kf = layers.repeat_kv(k, q_per_kv)
    vf = layers.repeat_kv(v, q_per_kv)
    mask = layers.causal_mask(positions, positions) if causal else None
    return layers.dot_product_attention(q, kf, vf, mask)


@pytest.mark.parametrize(
    "seq_devices,heads,kv_heads,causal",
    [
        (8, 4, 4, True),
        (8, 4, 4, False),
        (4, 8, 2, True),  # GQA, seq=4 (other axes trivial)
        (2, 4, 1, True),  # MQA
    ],
)
def test_ring_matches_full_attention(seq_devices, heads, kv_heads, causal):
    mesh = mesh_from_devices({"seq": seq_devices}, jax.devices()[:seq_devices])
    b, t, d = 2, 32, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, t, heads, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, kv_heads, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, kv_heads, d)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    out = ring.ring_self_attention(mesh, q, k, v, positions, causal=causal)
    want = _reference(q, k, v, positions, causal, heads // kv_heads)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_ring_under_jit_and_grad():
    """Ring attention must jit and differentiate (training path)."""
    mesh = mesh_from_devices({"seq": 4}, jax.devices()[:4])
    b, t, h, d = 1, 16, 2, 8
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    def loss(q, k, v):
        return jnp.sum(ring.ring_self_attention(mesh, q, k, v, positions) ** 2)

    g = jax.jit(jax.grad(loss))(q, k, v)
    assert g.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(g)))

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, positions, True, 1) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=1e-4)


def test_ring_fully_masked_rows_are_zero():
    """k_valid=False everywhere -> output 0, no NaNs (online-softmax edge)."""
    mesh = mesh_from_devices({"seq": 2}, jax.devices()[:2])
    b, t, h, d = 1, 8, 2, 4
    q = jnp.ones((b, t, h, d), jnp.float32)
    k = jnp.ones((b, t, h, d), jnp.float32)
    v = jnp.ones((b, t, h, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    from jax.sharding import PartitionSpec as P

    def fn(q, k, v, qp, kp, kv):
        return ring.ring_attention(
            q, k, v, qp, kp, axis_name="seq", causal=True, k_valid=kv
        )
    sh = P(None, "seq", None, None)
    ps = P(None, "seq")
    out = jaxcompat.shard_map(
        fn,
        mesh=mesh,
        in_specs=(sh, sh, sh, ps, ps, ps),
        out_specs=sh,
        axis_names={"seq"},
    )(q, k, v, positions, positions, jnp.zeros((b, t), bool))
    assert bool(jnp.all(out == 0.0))
    assert bool(jnp.all(jnp.isfinite(out)))
