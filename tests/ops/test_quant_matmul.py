"""Interpret-mode parity tests for the fused dequant-matmul Pallas kernel.

The kernel (ops/quant_matmul.py) is the serving hot path for weight-only
quantized models; every quantized-serving test on the CPU backend otherwise
exercises only the dequantize+einsum fallback.  These tests run the kernel's
exact program via Pallas interpret mode and compare against the fallback,
covering the matrix the kernel special-cases: bits {8, 4}, k_lead {1, 2}
(qkv/mlp vs wo), pack_axis {-2, -3}, and M values that exercise the padding
path (decode-shaped M=1, odd M, multi-tile M).

Reference's quantization design: /root/reference/snippets.md:675-833 (absmax
int8 + packed int4, dequantize-before-use); the fused kernel is the
TPU-native replacement for that dequantize step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.checkpoint.quantize import dequantize, quantize
from distributed_llms_tpu.ops import quant_matmul as qm


def _fallback(x, qt, eq):
    w = dequantize(qt, x.dtype)
    return jnp.einsum(eq, x, w)


def _make(shape, bits, pack_axis, seed=0):
    w = jax.random.normal(jax.random.key(seed), shape, jnp.float32)
    return quantize(w, bits=bits, block=128, pack_axis=pack_axis)


@pytest.fixture
def kernel_calls(monkeypatch):
    """Count invocations of the Pallas kernel so parity tests prove the
    kernel path was actually taken (not fallback == fallback)."""
    calls = []
    orig = qm._quant_matmul_2d

    def spy(*args, **kwargs):
        calls.append(kwargs)
        return orig(*args, **kwargs)

    monkeypatch.setattr(qm, "_quant_matmul_2d", spy)
    return calls


@pytest.mark.parametrize("bits", [8, 4])
@pytest.mark.parametrize("m", [1, 7, 16])
def test_parity_2d_klead1(bits, m, kernel_calls):
    """Standard [K, N] weight (w_in/w_gate/w_up/w_down layout), including
    decode-shaped M=1 and odd M=7 (both need M padding to the 16-row tile)."""
    qt = _make((256, 256), bits, pack_axis=-2)
    x = jax.random.normal(jax.random.key(1), (m, 256), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn", interpret=True)
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 1, "kernel path not taken (shapes untileable?)"
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bits,pack_axis", [(8, -2), (4, -3)])
def test_parity_qkv_layout(bits, pack_axis, kernel_calls):
    """wq/wk/wv layout [D, H, hd]: reduction axis is axis 0, so int4 packs
    along -3; output restores the [H, hd] tail."""
    qt = _make((256, 2, 128), bits, pack_axis=pack_axis)
    x = jax.random.normal(jax.random.key(2), (4, 9, 256), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "btd,dhk->bthk", interpret=True)
    want = _fallback(x, qt, "btd,dhk->bthk")
    assert len(kernel_calls) == 1
    assert got.shape == (4, 9, 2, 128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bits", [8, 4])
def test_parity_wo_layout_klead2(bits, kernel_calls):
    """wo layout [H, hd, D] with k_lead=2: both leading axes contract; int4
    packs along -2 (hd — the last K axis)."""
    qt = _make((2, 128, 256), bits, pack_axis=-2)
    x = jax.random.normal(jax.random.key(3), (4, 9, 2, 128), jnp.float32)
    got = qm.quant_contract(x, qt, 2, "bthk,hkd->btd", interpret=True)
    want = _fallback(x, qt, "bthk,hkd->btd")
    assert len(kernel_calls) == 1
    assert got.shape == (4, 9, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_parity_multitile(kernel_calls):
    """M, K, N all larger than one tile (grid > 1 on every axis) so the
    K-accumulator reset/flush logic is exercised across grid steps."""
    qt = _make((512, 384), 8, pack_axis=-2)
    x = jax.random.normal(jax.random.key(4), (300, 512), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn", interpret=True)
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 1
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_parity_bf16_activations(kernel_calls):
    """Serving runs bf16 activations; kernel accumulates f32 like the
    fallback einsum, but tiled K order differs — tolerance is bf16-scale."""
    qt = _make((256, 256), 8, pack_axis=-2)
    x = jax.random.normal(jax.random.key(5), (8, 256), jnp.bfloat16)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn", interpret=True)
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 1
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=2e-2, atol=2e-2
    )


def test_untileable_falls_back(kernel_calls):
    """K not divisible by any tile candidate → clean fallback, same answer."""
    qt = _make((100, 256), 8, pack_axis=-2)
    x = jax.random.normal(jax.random.key(6), (4, 100), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn", interpret=True)
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 0
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_int4_wrong_pack_axis_falls_back(kernel_calls):
    """int4 packed along a non-K axis cannot use the sublane unpack — must
    fall back rather than miscompute."""
    qt = _make((256, 256), 4, pack_axis=-1)  # packed along N, not K
    x = jax.random.normal(jax.random.key(7), (4, 256), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn", interpret=True)
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 0
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_env_interpret_mode(monkeypatch, kernel_calls):
    """DLT_QUANT_MATMUL=interpret (the CI leg) routes through the kernel in
    interpret mode without the caller passing interpret=True."""
    monkeypatch.setenv("DLT_QUANT_MATMUL", "interpret")
    qt = _make((256, 256), 8, pack_axis=-2)
    x = jax.random.normal(jax.random.key(8), (4, 256), jnp.float32)
    got = qm.quant_contract(x, qt, 1, "mk,kn->mn")
    want = _fallback(x, qt, "mk,kn->mn")
    assert len(kernel_calls) == 1
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_env_fallback_mode(monkeypatch, kernel_calls):
    """DLT_QUANT_MATMUL=fallback forces einsum even where tileable."""
    monkeypatch.setenv("DLT_QUANT_MATMUL", "fallback")
    qt = _make((256, 256), 8, pack_axis=-2)
    x = jax.random.normal(jax.random.key(9), (4, 256), jnp.float32)
    qm.quant_contract(x, qt, 1, "mk,kn->mn")
    assert len(kernel_calls) == 0
