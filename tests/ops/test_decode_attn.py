"""Ragged decode attention (ops/decode_attn.py, VERDICT r3 weak #5).

Parity: the kernel program (interpret mode on CPU — same program the TPU
compiles) must match the dense prefix-masked reference, and the batcher's
exact-token invariant must hold end-to-end with the ragged path active.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llms_tpu.ops import decode_attn


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


@pytest.mark.parametrize(
    "b,s,h,kvh,d,lengths",
    [
        (4, 256, 8, 8, 128, [1, 100, 256, 17]),       # MHA, mixed depths
        (2, 512, 8, 2, 128, [512, 300]),              # GQA g=4, partial block
        (3, 256, 4, 4, 128, [1, 1, 1]),               # minimum depth
        (1, 1024, 16, 8, 128, [769]),                 # many blocks, ragged tail
        (2, 384, 4, 4, 128, [129, 384]),              # 128-mult, not 256-mult:
        #   block stepping must keep the kernel (bk=128), not fall back dense
    ],
)
def test_kernel_matches_dense_reference(monkeypatch, b, s, h, kvh, d, lengths):
    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    q = _rand(0, (b, 1, h, d))
    k = _rand(1, (b, s, kvh, d))
    v = _rand(2, (b, s, kvh, d))
    ln = jnp.asarray(lengths, jnp.int32)
    got = decode_attn.ragged_decode_attention(q, k, v, ln)
    want = decode_attn._dense_reference(q, k, v, ln)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


# Round-5 windowed tests run fresh-process via test_isolated.py (shared
# marker — tests/conftest.py).
@pytest.mark.fragile_xla_cpu
@pytest.mark.parametrize(
    "b,s,h,kvh,d,lengths,window",
    [
        (4, 256, 8, 8, 128, [1, 100, 256, 17], 5),    # tiny window, mixed
        (2, 512, 8, 2, 128, [512, 300], 256),         # window == block size
        (1, 1024, 16, 8, 128, [769], 130),            # band crosses blocks
        (2, 256, 4, 4, 128, [200, 9], 1024),          # window > depth: no-op
        (2, 384, 4, 4, 128, [384, 130], 3),           # window inside one blk
    ],
)
def test_windowed_kernel_matches_dense(monkeypatch, b, s, h, kvh, d,
                                       lengths, window):
    """Sliding-window band: the kernel reads only [length - window,
    length) per row (first/last block clamps + in-block mask) and must
    match the dense windowed reference bit-for-tolerance."""
    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    q = _rand(0, (b, 1, h, d))
    k = _rand(1, (b, s, kvh, d))
    v = _rand(2, (b, s, kvh, d))
    ln = jnp.asarray(lengths, jnp.int32)
    got = decode_attn.ragged_decode_attention(q, k, v, ln, window=window)
    want = decode_attn._dense_reference(q, k, v, ln, window=window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_block_stepping_keeps_kernel_at_384(monkeypatch):
    """Cache width 384 (a 128-multiple but not a 256-multiple) must step the
    K block down to 128 and stay on the kernel — not silently serve the
    dense full-width fallback."""
    import jax.experimental.pallas as pl_mod

    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    calls = []
    orig = pl_mod.pallas_call
    monkeypatch.setattr(
        decode_attn.pl, "pallas_call",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw),
    )
    q = _rand(0, (2, 1, 4, 128))
    k = _rand(1, (2, 384, 4, 128))
    v = _rand(2, (2, 384, 4, 128))
    ln = jnp.asarray([129, 384], jnp.int32)
    got = decode_attn.ragged_decode_attention(q, k, v, ln)
    assert calls, "kernel was not used for the 384-wide cache"
    want = decode_attn._dense_reference(q, k, v, ln)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize(
    "b,pool,blk,pages,h,kvh,d,lengths",
    [
        (3, 32, 64, 4, 8, 4, 128, [1, 130, 256]),   # GQA, scattered pages
        (2, 16, 128, 2, 4, 4, 128, [255, 7]),       # page == K block
        (4, 64, 8, 8, 8, 8, 128, [64, 1, 33, 17]),  # tiny 8-slot pages
    ],
)
def test_paged_matches_contiguous(monkeypatch, b, pool, blk, pages, h, kvh, d, lengths):
    """Rows' KV scattered over a shuffled page pool must attend exactly like
    the same data laid out contiguously."""
    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    rng = np.random.RandomState(0)
    # Distinct physical pages per (row, logical page).
    perm = rng.permutation(pool)[: b * pages]
    tables = jnp.asarray(perm.reshape(b, pages), jnp.int32)
    q = _rand(0, (b, 1, h, d))
    k_rows = _rand(1, (b, pages * blk, kvh, d))
    v_rows = _rand(2, (b, pages * blk, kvh, d))
    k_pool = jnp.zeros((pool, blk, kvh, d)).at[tables.reshape(-1)].set(
        k_rows.reshape(b * pages, blk, kvh, d)
    )
    v_pool = jnp.zeros((pool, blk, kvh, d)).at[tables.reshape(-1)].set(
        v_rows.reshape(b * pages, blk, kvh, d)
    )
    ln = jnp.asarray(lengths, jnp.int32)
    got = decode_attn.paged_decode_attention(q, k_pool, v_pool, ln, tables)
    want = decode_attn._dense_reference(q, k_rows, v_rows, ln)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_paged_fallback_matches_reference(monkeypatch):
    """The dense fallback (untileable head_dim) gathers pages correctly."""
    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    b, pool, blk, pages, h, d = 2, 8, 16, 2, 4, 64  # d=64: fallback path
    tables = jnp.asarray([[3, 0], [5, 7]], jnp.int32)
    q = _rand(0, (b, 1, h, d))
    k_rows = _rand(1, (b, pages * blk, h, d))
    v_rows = _rand(2, (b, pages * blk, h, d))
    k_pool = jnp.zeros((pool, blk, h, d)).at[tables.reshape(-1)].set(
        k_rows.reshape(b * pages, blk, h, d)
    )
    v_pool = jnp.zeros((pool, blk, h, d)).at[tables.reshape(-1)].set(
        v_rows.reshape(b * pages, blk, h, d)
    )
    ln = jnp.asarray([17, 32], jnp.int32)
    got = decode_attn.paged_decode_attention(q, k_pool, v_pool, ln, tables)
    want = decode_attn._dense_reference(q, k_rows, v_rows, ln)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_untileable_head_dim_falls_back(monkeypatch):
    """d=64 is not a 128-lane multiple: the dense fallback must serve it."""
    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    q = _rand(0, (2, 1, 4, 64))
    k = _rand(1, (2, 128, 4, 64))
    v = _rand(2, (2, 128, 4, 64))
    ln = jnp.asarray([5, 99], jnp.int32)
    got = decode_attn.ragged_decode_attention(q, k, v, ln)
    want = decode_attn._dense_reference(q, k, v, ln)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_batcher_exact_tokens_with_ragged_decode(monkeypatch):
    """End-to-end: the ContinuousBatcher with the ragged kernel (interpret)
    emits tokens identical to solo generate_tokens — scheduling AND the
    ragged read change nothing about results.  head_dim 128 AND max_len 128
    make the cache kernel-tileable, so the kernel PROGRAM (not the dense
    fallback) is what runs — the spy is on pallas_call itself, which the
    fallback never reaches."""
    from distributed_llms_tpu.models import model as model_lib, presets
    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    calls = []
    orig = decode_attn.pl.pallas_call
    monkeypatch.setattr(
        decode_attn.pl, "pallas_call",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw),
    )
    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, num_heads=2,
        num_kv_heads=2,  # head_dim 128 — kernel-tileable
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=128, chunk_steps=4)
    assert b.cfg_decode.ragged_decode
    reqs = [([7, 1, 9], 6), ([4, 4, 4, 4, 4], 9), ([11, 12], 3)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    assert calls, "ragged decode attention did not run"
    for rid, (ids, n) in zip(rids, reqs):
        solo = gen_lib.generate_tokens(
            params, cfg, jnp.asarray([ids], jnp.int32),
            jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
            max_new_tokens=n,
        )
        assert res[rid] == np.asarray(solo)[0].tolist(), f"req {rid} diverged"


@pytest.mark.fragile_xla_cpu
def test_batcher_windowed_ragged_matches_solo(monkeypatch):
    """Sliding-window model through the batcher's ragged kernel path
    (interpret): mixed budgets crossing the window boundary must match the
    solo dense-windowed decode token-for-token — the kernel's slot-space
    band equals the dense path's position-space window exactly under the
    contiguous layout."""
    from distributed_llms_tpu.models import model as model_lib, presets
    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    monkeypatch.setenv("DLT_RAGGED_DECODE", "interpret")
    calls = []
    orig = decode_attn.pl.pallas_call
    monkeypatch.setattr(
        decode_attn.pl, "pallas_call",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw),
    )
    cfg = presets.get_preset(
        "llama-tiny", vocab_size=512, hidden_size=256, num_heads=2,
        num_kv_heads=2, sliding_window=5,  # head_dim 128 — kernel-tileable
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    b = ContinuousBatcher(cfg, params, batch_slots=2, max_len=128,
                          chunk_steps=4)
    assert b.cfg_decode.ragged_decode and b.cfg_decode.sliding_window == 5
    reqs = [([7, 1, 9, 4, 2, 8, 3], 9), ([4, 4, 4], 7), ([11, 12], 12)]
    rids = [b.submit(ids, max_new_tokens=n) for ids, n in reqs]
    res = b.run()
    assert calls, "ragged decode attention did not run"
    for rid, (ids, n) in zip(rids, reqs):
        solo = gen_lib.generate_tokens(
            params, cfg, jnp.asarray([ids], jnp.int32),
            jnp.asarray([len(ids)], jnp.int32), jax.random.key(9),
            max_new_tokens=n,
        )
        assert res[rid] == np.asarray(solo)[0].tolist(), f"req {rid} diverged"
