"""Flash attention kernel vs the dense reference (CPU interpret mode).

Mirrors the reference's unit-test strategy (SURVEY §4: per-layer tests with
real tensors) for the net-new Pallas kernel: every dispatch mode is checked
against ``layers.dot_product_attention`` with the equivalent mask.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_tpu.core.config import ModelConfig
from distributed_llms_tpu.models import layers, model as model_lib
from distributed_llms_tpu.ops.flash import flash_attention


def _qkv(b=2, t=37, h=4, kvh=2, d=16, s=None, seed=0):
    s = s or t
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kvh, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.float32)
    return q, k, v


def _dense(q, k, v, mask):
    g = q.shape[2] // k.shape[2]
    return layers.dot_product_attention(
        q, layers.repeat_kv(k, g), layers.repeat_kv(v, g), mask
    )


def test_static_causal_matches_dense():
    q, k, v = _qkv()
    b, t = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    ref = _dense(q, k, v, layers.causal_mask(pos, pos))
    out = flash_attention(q, k, v, block_q=16, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_dynamic_positions_match_dense():
    q, k, v = _qkv(seed=1)
    b, t = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    ref = _dense(q, k, v, layers.causal_mask(pos, pos))
    # Passing positions explicitly forces the dynamic kernel.
    out = flash_attention(
        q, k, v, q_positions=pos, k_positions=pos, block_q=16, block_k=128
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cached_prefill_k_valid():
    # Prefill into a longer padded cache: only the first T slots are valid.
    t, s = 23, 64
    q, k, v = _qkv(t=t, s=s, seed=2)
    b = q.shape[0]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    kpos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    k_valid = kpos < t
    ref = _dense(q, k, v, layers.causal_mask(pos, kpos, k_valid))
    out = flash_attention(
        q, k, v, q_positions=pos, k_positions=kpos, k_valid=k_valid,
        block_q=16, block_k=128,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_non_causal():
    q, k, v = _qkv(seed=3)
    ref = _dense(q, k, v, None)
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_mha_no_gqa():
    q, k, v = _qkv(h=4, kvh=4, seed=4)
    b, t = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    ref = _dense(q, k, v, layers.causal_mask(pos, pos))
    out = flash_attention(q, k, v, block_q=16, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_model_forward_flash_matches_dot(family):
    cfg_dot = ModelConfig(
        family=family, vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2 if family == "llama" else 4,
        max_seq_len=64, dtype="float32", attn_impl="dot",
    )
    cfg_flash = ModelConfig(**{**cfg_dot.__dict__, "attn_impl": "flash"})
    params = model_lib.init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(jax.random.key(1), (2, 17), 0, 128, dtype=jnp.int32)
    ref, _ = model_lib.forward(params, cfg_dot, tokens)
    out, _ = model_lib.forward(params, cfg_flash, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


# Round-5 windowed-kernel tests: compile-heavy, so they run fresh-process
# via tests/runtime/test_isolated.py (shared marker — tests/conftest.py).
@pytest.mark.fragile_xla_cpu
@pytest.mark.parametrize("window", [1, 3, 37, 200])
def test_windowed_static_matches_dense(window):
    """Static-causal path with a sliding window: every tile class (fully
    visible, boundary on the diagonal, boundary on the window's lower
    edge, dead above, dead below) vs the dense windowed mask.  t=200 with
    16/128 tiles crosses all of them; window >= t degenerates to plain
    causal."""
    q, k, v = _qkv(t=200, seed=7)
    b, t = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    ref = _dense(q, k, v, layers.causal_mask(pos, pos, window=window))
    out = flash_attention(q, k, v, block_q=16, block_k=128, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.fragile_xla_cpu
def test_windowed_dynamic_matches_dense():
    """Dynamic path (explicit positions + validity) with a window: padded
    cache prefill where only the first T slots are valid."""
    t, s, window = 23, 64, 5
    q, k, v = _qkv(t=t, s=s, seed=8)
    b = q.shape[0]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    kpos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    k_valid = kpos < t
    ref = _dense(q, k, v, layers.causal_mask(pos, kpos, k_valid,
                                             window=window))
    out = flash_attention(
        q, k, v, q_positions=pos, k_positions=kpos, k_valid=k_valid,
        block_q=16, block_k=128, window=window,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_windowed_validation():
    q, k, v = _qkv(seed=9)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=3)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, window=0)


@pytest.mark.fragile_xla_cpu
def test_windowed_grad_matches_dot():
    """Gradients through the windowed flash forward (dense-recompute
    backward must carry the window) vs the windowed dot path."""
    import dataclasses

    cfg = ModelConfig(
        family="llama", vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=32,
        dtype="float32", attn_impl="flash", sliding_window=3,
    )
    cfg_dot = dataclasses.replace(cfg, attn_impl="dot")
    params = model_lib.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, 64, dtype=jnp.int32)

    def loss(p, c):
        lg, _ = model_lib.forward(p, c, toks)
        return jnp.mean(lg**2)

    g1 = jax.grad(lambda p: loss(p, cfg))(params)
    g2 = jax.grad(lambda p: loss(p, cfg_dot))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_grad_through_flash_matches_dot():
    import dataclasses

    cfg = ModelConfig(
        family="llama", vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=32,
        dtype="float32", attn_impl="flash",
    )
    cfg_dot = dataclasses.replace(cfg, attn_impl="dot")
    params = model_lib.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, 64, dtype=jnp.int32)

    def loss(p, c):
        lg, _ = model_lib.forward(p, c, toks)
        return jnp.mean(lg**2)

    g1 = jax.grad(lambda p: loss(p, cfg))(params)
    g2 = jax.grad(lambda p: loss(p, cfg_dot))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_offset_positions_match_dot():
    import dataclasses

    cfg = ModelConfig(
        family="llama", vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64,
        dtype="float32", attn_impl="flash",
    )
    cfg_dot = dataclasses.replace(cfg, attn_impl="dot")
    params = model_lib.init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 9), 0, 64, dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(9, dtype=jnp.int32) + 5, (2, 9))
    l1, _ = model_lib.forward(params, cfg, toks, positions=pos)
    l2, _ = model_lib.forward(params, cfg_dot, toks, positions=pos)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-4)


def test_generate_flash_matches_dot():
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg_dot = ModelConfig(
        family="llama", vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64,
        dtype="float32", attn_impl="dot",
    )
    cfg_flash = ModelConfig(**{**cfg_dot.__dict__, "attn_impl": "flash"})
    params = model_lib.init_params(jax.random.key(0), cfg_dot)
    prompt = jax.random.randint(jax.random.key(1), (2, 9), 0, 128, dtype=jnp.int32)
    lens = jnp.array([5, 9], dtype=jnp.int32)
    rng = jax.random.key(2)
    ref = gen_lib.generate_tokens(params, cfg_dot, prompt, lens, rng, max_new_tokens=6)
    out = gen_lib.generate_tokens(params, cfg_flash, prompt, lens, rng, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
