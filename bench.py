#!/usr/bin/env python
"""Benchmark harness (driver contract: prints ONE JSON line).

Default mode measures the NORTH-STAR metric (BASELINE.json: "tokens/sec/chip
at 7B"): greedy-decode throughput of Llama-2-7B served int8 weight-only on
the available accelerator.  When no accelerator is reachable it degrades to
GPT-2-125M on CPU (marked ``degraded`` in the JSON).  The reference publishes
no numbers (SURVEY §6: README is a title line, no benchmarks/ dir,
placeholder compute), so ``vs_baseline`` is reported against the driver's
north-star target of 1000 tok/s aggregate.

``--ladder`` additionally measures the BASELINE.md ladder configs that fit
the local device (tokens/sec/chip, 2N-approx MFU, achieved weight-stream
bytes/s and HBM utilization — decode is weight-bandwidth-bound, so that is
the honest lens — plus a flash-vs-dot prefill microbenchmark and the
pipeline-hop ppermute latency microbenchmark when >1 device is visible) and
writes the rows to ``--out`` (default BENCH_LADDER.json).  The final stdout
line stays the single north-star JSON object either way.

Usage: python bench.py [--preset llama-2-7b] [--batch 4] [--prompt-len 64]
       [--new-tokens 16] [--dtype bfloat16] [--ladder] [--out FILE]
"""

from __future__ import annotations

import argparse
import functools
import json
import math
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR_TOKS_PER_S = 1000.0  # BASELINE.json: >=1000 tok/s aggregate

# Peak dense bf16 FLOP/s per chip by device_kind substring (public specs);
# MFU is reported only when the device is recognized.
PEAK_FLOPS = {
    "v5 lite": 197e12,  # TPU v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # Trillium
    "v6e": 918e12,
}

# Peak HBM bandwidth per chip (public specs) — decode is weight-bandwidth
# bound, so achieved-bytes/s over this peak is the honest utilization lens
# (VERDICT r2: MFU is the wrong metric for decode).
PEAK_HBM_BW = {
    "v5 lite": 819e9,  # TPU v5e
    "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,  # Trillium
    "v6e": 1640e9,
}

# BASELINE.md ladder (config 5, multi-host 70B, needs hardware this harness
# will never see single-chip; it is covered by the dryrun/multi-host tests).
LADDER = [
    {"config": 1, "preset": "gpt2-125m", "batch": 8, "prompt": 64, "new": 64},
    # Batch-scaling rows: decode reads the same weight bytes per step
    # regardless of batch, so larger batches raise aggregate tok/s toward the
    # same weight-stream ceiling — the lever VERDICT r2 asked the ladder to
    # demonstrate for configs 1-2.
    {"config": "1-b32", "preset": "gpt2-125m", "batch": 32, "prompt": 64, "new": 64},
    {"config": 2, "preset": "tinyllama-1.1b", "batch": 8, "prompt": 64, "new": 32},
    {"config": "2-b32", "preset": "tinyllama-1.1b", "batch": 32, "prompt": 64,
     "new": 32},
    {"config": 3, "preset": "llama-2-7b", "batch": 4, "prompt": 64, "new": 16},
    # int8/int4 weight-only variants: block weights resident quantized and
    # consumed by the fused dequant-matmul kernel, letting 7B (int8) and even
    # 13B (int4, ~7.8 GB weights) fit — and be measured on — one 16 GB chip.
    {"config": "3-int8", "preset": "llama-2-7b", "batch": 4, "prompt": 64,
     "new": 16, "quant": "int8"},
    # Batch sweep for the quantized north star: decode reads the same
    # weight bytes per step regardless of batch, so aggregate tok/s should
    # climb toward the weight-stream ceiling (~480 tok/s at batch 4 rises
    # ~linearly until activations/KV contend) — the next lever after the
    # fused kernel itself (VERDICT r3 next-step 2).
    {"config": "3-int8-b8", "preset": "llama-2-7b", "batch": 8, "prompt": 64,
     "new": 16, "quant": "int8"},
    {"config": "3-int8-b16", "preset": "llama-2-7b", "batch": 16,
     "prompt": 64, "new": 16, "quant": "int8"},
    {"config": "3-int4", "preset": "llama-2-7b", "batch": 4, "prompt": 64,
     "new": 16, "quant": "int4"},
    {"config": 4, "preset": "llama-2-13b", "batch": 2, "prompt": 64, "new": 16},
    {"config": "4-int8", "preset": "llama-2-13b", "batch": 2, "prompt": 64,
     "new": 16, "quant": "int8"},
    {"config": "4-int4", "preset": "llama-2-13b", "batch": 2, "prompt": 64,
     "new": 16, "quant": "int4"},
]

# Default (no --ladder): the north-star config, with a degraded fallback.
NORTH_STAR = {"preset": "llama-2-7b", "batch": 4, "prompt": 64, "new": 16,
              "quant": "int8"}
FALLBACK = {"preset": "gpt2-125m", "batch": 8, "prompt": 64, "new": 64,
            "quant": None}


def _cpu_fallback_line(args) -> dict:
    """Measure the fallback config on CPU in a fresh subprocess (this
    process's JAX backend is pinned to the wedged accelerator).  The child
    pins CPU explicitly (--force-cpu) so a half-alive tunnel cannot lure it
    back onto the TPU, and never arms its own watchdog."""
    try:
        r = subprocess.run(
            [sys.executable, __file__, "--force-cpu", "--iters",
             str(args.iters), "--measure-timeout", "0"],
            capture_output=True, text=True, timeout=1800,
        )
        lines = r.stdout.strip().splitlines()
    except subprocess.TimeoutExpired:
        lines = []
    for line in reversed(lines):
        try:
            out = json.loads(line)
            out["degraded"] = (
                "accelerator hung mid-measurement; cpu fallback via subprocess"
            )
            return out
        except json.JSONDecodeError:
            continue
    return {
        "metric": "decode tokens/sec", "value": 0.0, "unit": "tok/s",
        "vs_baseline": 0.0,
        "degraded": "accelerator hung mid-measurement; fallback failed too",
    }


def _arm_watchdog(seconds: float, args):
    """Watchdog THREAD (not SIGALRM — a signal handler can only run when the
    main thread re-enters Python bytecode, which never happens while it is
    wedged inside the axon plugin's C++ RPC wait): if the measurement has
    not finished after ``seconds``, print the CPU-fallback JSON line and
    hard-exit so the driver always captures one line.  Returns an Event to
    set on completion; seconds<=0 disables."""
    import os
    import threading

    done = threading.Event()
    if seconds <= 0:
        return done

    def fire():
        if not done.wait(seconds):
            try:
                out = _cpu_fallback_line(args)
            except Exception as exc:  # never die silently
                out = {
                    "metric": "decode tokens/sec", "value": 0.0,
                    "unit": "tok/s", "vs_baseline": 0.0,
                    "degraded": f"measurement hung; fallback crashed: {exc}",
                }
            # The fallback subprocess can take many minutes; if the wedged
            # measurement recovered and printed meanwhile (main sets `done`
            # BEFORE printing), drop the fallback line — one JSON line only.
            if done.is_set():
                return
            print(json.dumps(out), flush=True)
            os._exit(0)

    threading.Thread(target=fire, daemon=True).start()
    return done


def _probe_accelerator(timeout_s: float) -> str | None:
    """Check in a subprocess (hard-killed on timeout) whether the default JAX
    backend initializes.  The axon TPU plugin, when its tunnel is down, blocks
    ``jax.devices()`` for ~25 minutes before raising UNAVAILABLE — round 1's
    BENCH artifact died exactly this way.  Returns the platform name or None."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def _init_backend(probe_timeout: float, attempts: int) -> str | None:
    """Retry accelerator init with backoff; fall back to CPU on persistent
    failure.  Returns a degraded-marker string, or None if healthy."""
    for i in range(attempts):
        platform = _probe_accelerator(probe_timeout)
        if platform is not None and platform != "cpu":
            return None  # healthy — main process will init the same backend
        if platform == "cpu":
            # No accelerator configured at all: still a CPU measurement.
            return "no accelerator present; measured on cpu"
        if i + 1 < attempts:
            time.sleep(10.0 * (i + 1))
    # Persistent failure: pin the CPU backend before any jax backend use in
    # this process (the axon plugin ignores the JAX_PLATFORMS env var, so this
    # must go through jax.config).
    jax.config.update("jax_platforms", "cpu")
    return "accelerator-unavailable; measured on cpu fallback"


def _param_count(cfg) -> int:
    """Parameter count from the architecture dims (matches init_params)."""
    d, v, l = cfg.hidden_size, cfg.vocab_size, cfg.num_layers
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    ff = cfg.intermediate_size
    attn = d * h * hd + 2 * d * kvh * hd + h * hd * d
    mlp = 3 * d * ff if cfg.family == "llama" else 2 * d * ff
    if cfg.num_experts:
        mlp = cfg.num_experts * 3 * d * ff + d * cfg.num_experts
    norms = 2 * d * l + d
    embed = v * d + (0 if cfg.tie_embeddings else v * d)
    pos = (cfg.max_seq_len + (2 if cfg.family == "opt" else 0)) * d \
        if cfg.family in ("gpt2", "opt") else 0
    return l * (attn + mlp) + norms + embed + pos


# HBM per chip by device_kind substring — fallback when the plugin exposes
# no memory_stats (the axon TPU plugin doesn't; round 2's first ladder run
# attempted 7B bf16 on a 16 GB chip and died RESOURCE_EXHAUSTED).
HBM_BYTES = {
    "v5 lite": 16e9, "v5e": 16e9, "v4": 32e9, "v5p": 95e9,
    "v6 lite": 32e9, "v6e": 32e9,
}


def _mem_budget_bytes() -> int | None:
    """Usable memory on the target device (HBM) or host (CPU fallback)."""
    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if stats and "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    if dev.platform == "cpu":
        try:
            import os

            return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        except (ValueError, OSError):
            return None
    kind = getattr(dev, "device_kind", "").lower()
    for key, hbm in HBM_BYTES.items():
        if key in kind:
            return int(hbm)
    return None


def _fits(cfg, batch: int, seq: int, dtype: str, quant: str | None = None) -> tuple[bool, str]:
    budget = _mem_budget_bytes()
    if budget is None:
        return True, "unknown memory budget; attempting"
    bytes_per = jnp.dtype(dtype).itemsize
    # int8/int4 weight-only: ~1 byte (0.5) per block weight + scales, with
    # embeddings still at full dtype — folded into an average factor.
    w_bytes = {None: bytes_per, "int8": 1.1, "int4": 0.6}[quant]
    weights = _param_count(cfg) * w_bytes
    kv = 2 * cfg.num_layers * batch * seq * cfg.num_kv_heads * cfg.head_dim_ * bytes_per
    need = int((weights + kv) * 1.25)  # activations + fragmentation headroom
    if need > budget * 0.92:
        return False, (
            f"needs ~{need / 1e9:.1f} GB ({_param_count(cfg) / 1e9:.2f}B params "
            f"@ {quant or dtype}), budget {budget / 1e9:.1f} GB"
        )
    return True, f"~{need / 1e9:.1f} GB of {budget / 1e9:.1f} GB"


# Latest built param tree, keyed by (preset, dtype, quant): consecutive
# ladder rows (3-int8 / 3-int8-b8 / 3-int8-b16, then serving-latency and
# continuous-batching on the same north-star config) differ only in batch —
# rebuilding identical 7B weights for each row is pure setup waste.  One
# entry only, and the old tree is dropped BEFORE the next build so HBM never
# holds two big models.
_PARAMS_CACHE: dict = {}


def _build_params(preset: str, dtype: str, quant: str | None):
    """Random-init params for a preset, optionally weight-only quantized.

    Quantized big models are generated AND quantized directly on the
    accelerator, streamed tensor-by-tensor (layer-chunked so full-precision
    transients stay ~2 GB): the previous host-side path random-inited 7B
    f32 on one CPU core and shipped ~7 GB over the tunnel — ~25 minutes of
    setup per ladder row, which is how round 4's first ladder run ran into
    its own watchdog.  Only the int8/int4 blocks (plus full-dtype
    embeddings) are ever resident on device."""
    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset

    key = (preset, dtype, quant)
    if key in _PARAMS_CACHE:
        return _PARAMS_CACHE[key]
    _PARAMS_CACHE.clear()  # free the previous model before building the next

    cfg = get_preset(preset, dtype=dtype)
    if not quant:
        out = cfg, model_lib.init_params(jax.random.key(0), cfg)
    elif jax.devices()[0].platform == "cpu":
        # Host fallback: quantize host-side (same numerics as the store path).
        from distributed_llms_tpu.checkpoint import quantize as quant_lib

        bits = {"int8": 8, "int4": 4}[quant]
        params = model_lib.init_params(jax.random.key(0), cfg)
        params["blocks"] = quant_lib.quantize_tree(params["blocks"], bits=bits)
        out = cfg, params
    else:
        out = cfg, _gen_quantized_on_device(cfg, quant)
    _PARAMS_CACHE[key] = out
    return out


def _gen_quantized_on_device(cfg, quant: str):
    """Random weights for benchmarking, generated on the accelerator.

    Walks init_params' tree structure via eval_shape (never materializing
    it), generating each leaf on-device: matmul block weights are generated
    in <=2 GB f32 layer-chunks and quantized immediately, so peak HBM is
    the quantized model plus one chunk.  Values are NOT bit-identical to
    init_params (per-leaf fold_in keys, approximate fan-in) — irrelevant
    for throughput rows, which only need finite bf16 activations."""
    from distributed_llms_tpu.checkpoint import quantize as quant_lib
    from distributed_llms_tpu.models import model as model_lib

    bits = {"int8": 8, "int4": 4}[quant]
    shapes = jax.eval_shape(
        lambda k: model_lib.init_params(k, cfg), jax.random.key(0)
    )
    base = jax.random.key(0)
    counter = iter(range(1 << 20))

    def gen_dense(leaf_key, shape, dtype, fan_in):
        x = jax.random.normal(leaf_key, shape, jnp.float32)
        return (x * fan_in**-0.5).astype(dtype)

    def visit(path, sd):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        leaf = name.split("/")[-1]
        leaf_key = jax.random.fold_in(base, next(counter))
        if leaf.startswith("scale") or leaf == "g":
            return jnp.ones(sd.shape, sd.dtype)
        if leaf.startswith("bias") or leaf.startswith("b"):
            return jnp.zeros(sd.shape, sd.dtype)
        # fan-in approximation: D sits at axis 1 for stacked [L, D, ...]
        # weights, at the last axis for 2-D embeddings.
        fan_in = sd.shape[1] if len(sd.shape) >= 3 else sd.shape[-1]
        # leaf_plan is the serving path's own selection logic — the rows
        # must measure exactly the quantization the engine serves.
        should, pack_axis = quant_lib.leaf_plan(name, sd)
        if not (name.startswith("blocks/") and should):
            return gen_dense(leaf_key, sd.shape, sd.dtype, fan_in)
        layers = sd.shape[0]
        per_layer = int(np.prod(sd.shape[1:]))
        chunk = max(1, min(layers, int(2e9 // (per_layer * 4))))
        datas, scales = [], []
        for lo in range(0, layers, chunk):
            n = min(chunk, layers - lo)
            x = gen_dense(
                jax.random.fold_in(leaf_key, lo), (n, *sd.shape[1:]),
                jnp.float32, fan_in,
            )
            qt = quant_lib.quantize(x, bits=bits, pack_axis=pack_axis)
            datas.append(qt.data)
            scales.append(qt.scale)
            del x, qt
        return quant_lib.QuantizedTensor(
            data=jnp.concatenate(datas, 0) if len(datas) > 1 else datas[0],
            scale=jnp.concatenate(scales, 0) if len(scales) > 1 else scales[0],
            bits=bits, orig_shape=tuple(sd.shape), pack_axis=pack_axis,
        )

    with jax.default_device(jax.devices()[0]):
        return jax.tree_util.tree_map_with_path(visit, shapes)


def _measure_decode(preset: str, batch: int, prompt_len: int, new_tokens: int,
                    dtype: str, iters: int, quant: str | None = None) -> dict:
    """Two-point greedy-decode throughput at true model shapes (random
    weights — no network in this environment; decode FLOPs are identical).
    ``quant``: int8/int4 weight-only serving (block weights resident
    quantized; dequant fused per layer)."""
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import generate as gen_lib

    import numpy as np

    cfg, params = _build_params(preset, dtype, quant)
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )
    lens = jnp.full((batch,), prompt_len, dtype=jnp.int32)
    rng = jax.random.key(2)

    # The axon-tunneled TPU has ~80ms constant dispatch/transfer overhead and
    # a block_until_ready that does NOT actually block, so (a) force a host
    # transfer with np.asarray and (b) use a two-point measurement — time
    # decode at N and 2N tokens and take the delta — which cancels the
    # constant overhead and the (shared) prefill cost.
    def timed(n_new: int) -> float:
        np.asarray(
            gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
        )
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(
                gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
            )
            times.append(time.perf_counter() - t0)
        return min(times)

    n1, n2 = new_tokens, 2 * new_tokens
    t1, t2 = timed(n1), timed(n2)
    overhead_dominated = t2 <= t1
    if overhead_dominated:
        # The two-point delta collapsed into dispatch noise; the single-shot
        # number still folds prefill + ~80ms tunnel overhead into tok/s, so
        # mark the row — otherwise a deflated batch-scaling row reads as
        # batching regressing throughput.
        tps = batch * n2 / t2
    else:
        tps = batch * (n2 - n1) / (t2 - t1)

    from distributed_llms_tpu.checkpoint.quantize import tree_bytes

    weight_bytes = tree_bytes(params)  # actual resident bytes (quant-aware)
    n_chips = jax.device_count()
    out = {
        "preset": preset,
        **({"quant": quant} if quant else {}),
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "n_chips": n_chips,
        "tok_per_s": round(tps, 2),
        "tok_per_s_per_chip": round(tps / n_chips, 2),
        "params_b": round(_param_count(get_preset(preset)) / 1e9, 3),
        "weight_gb": round(weight_bytes / 1e9, 3),
        **({"note": "overhead-dominated: two-point delta collapsed; "
                    "single-shot number includes prefill + dispatch"}
           if overhead_dominated else {}),
    }
    mfu = _mfu(tps / n_chips, _param_count(get_preset(preset)))
    if mfu is not None:
        out["mfu_2N"] = mfu
    # Weight-stream bandwidth: every decode step reads all resident weights
    # once, so achieved bytes/s = weight_bytes * steps/s.  Utilization over
    # peak HBM bandwidth is the decode-honest metric (KV reads add a little
    # more traffic; this is a lower bound on achieved BW).  This measurement
    # path runs the whole forward on ONE device (no mesh/forward_fn), so all
    # weight bytes stream from that chip — no per-chip division.
    steps_per_s = tps / batch
    bw = weight_bytes * steps_per_s
    out["weight_stream_gb_per_s"] = round(bw / 1e9, 2)
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in PEAK_HBM_BW.items():
        if key in kind:
            out["hbm_util"] = round(bw / peak, 4)
            break
    return out


def _mfu(tps_per_chip: float, n_params: int) -> float | None:
    """Model FLOPs utilization with the standard 2N FLOPs/token estimate."""
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return round(tps_per_chip * 2.0 * n_params / peak, 5)
    return None


def _measure_serving_latency(
    preset: str, batch: int, prompt_len: int, dtype: str,
    quant: str | None = None, requests: int = 8, new_tokens: int = 16,
) -> dict:
    """Serving-latency percentiles through the PRODUCT path (InferenceEngine
    + tokenizer), not raw generate_tokens: TTFT (prefill + first token) and
    TPOT (steady-state per-token decode) — the p50/p95 latency metrics
    SURVEY §5.5 calls for next to throughput.

    TTFT = latency of a 1-token generate; TPOT = (t(N) - t(1)) / (N - 1),
    which cancels prefill and the constant dispatch overhead.
    """
    from distributed_llms_tpu.core.config import RuntimeConfig
    from distributed_llms_tpu.runtime.engine import InferenceEngine

    if new_tokens < 2:
        raise ValueError("TPOT needs new_tokens >= 2")
    rt = RuntimeConfig(max_decode_steps=new_tokens)
    # Rebuilds params even when a decode row just built the same ones — on
    # purpose: caching jax arrays across rows would pin this config's HBM
    # while later (bigger) configs run, breaking the crash-isolated ladder.
    cfg, params = _build_params(preset, dtype, quant)
    eng = InferenceEngine(cfg, rt, params)
    prompts = ["benchmark " * max(1, prompt_len // 10)] * batch

    # Warm both compilation caches (1-token and N-token loops).
    eng.generate_text(prompts, max_new_tokens=1)
    eng.generate_text(prompts, max_new_tokens=new_tokens)

    ttfts, fulls = [], []
    for _ in range(requests):
        t0 = time.perf_counter()
        eng.generate_text(prompts, max_new_tokens=1)
        ttfts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        eng.generate_text(prompts, max_new_tokens=new_tokens)
        fulls.append(time.perf_counter() - t0)
    # Interpolated percentiles: with the default requests=8, a positional
    # index at 0.95 would be the sample MAX — one outlier would fully
    # determine the reported p95 (ADVICE r3).
    p50, p95 = np.percentile(np.asarray(ttfts), [50.0, 95.0])
    out = {
        "preset": preset,
        **({"quant": quant} if quant else {}),
        "batch": batch,
        "new_tokens": new_tokens,
        "requests": requests,
        "platform": jax.devices()[0].platform,
        "ttft_p50_ms": round(float(p50) * 1e3, 1),
        "ttft_p95_ms": round(float(p95) * 1e3, 1),
    }
    tpot = (min(fulls) - min(ttfts)) / (new_tokens - 1)
    if tpot <= 0:
        # Overhead-dominated (constant dispatch ~ decode time, cf. the
        # t2<=t1 guard in _measure_decode): the subtraction is noise.
        out["tpot_ms"] = None
        out["note"] = "overhead-dominated: full-decode time within noise of TTFT"
    else:
        out["tpot_ms"] = round(tpot * 1e3, 2)
        out["tok_per_s_steady"] = round(batch / tpot, 1)
    return out


def _measure_speculative(
    preset: str, dtype: str, target_quant: str | None = None,
    k: int = 4, batch: int = 4, prompt_len: int = 64, new_tokens: int = 32,
    iters: int = 3,
) -> dict:
    """Speculative vs plain greedy decode (runtime/speculative.py): target =
    ``preset`` (optionally weight-only quantized), draft = the same weights
    at int4 — the self-speculation recipe, whose draft steps read a fraction
    of the target's weight bytes.  Reports both throughputs, the speedup,
    and the measured acceptance rate.  Exactness is asserted on-device
    (speculative tokens must equal plain greedy bit-for-bit) so this row is
    also a hardware parity check of the whole loop.

    With random weights the acceptance rate measures how often int4
    quantization preserves the argmax of an essentially flat logit
    landscape — a PESSIMISTIC bound; real checkpoints' peaked logits accept
    far more.  The row records it honestly either way."""
    import numpy as np

    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.speculative import (
        speculative_generate_tokens,
    )

    cfg, tparams = _build_params(preset, dtype, target_quant)
    _, dparams = _build_params(preset, dtype, "int4")
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size,
        dtype=jnp.int32,
    )
    lens = jnp.full((batch,), prompt_len, dtype=jnp.int32)
    rng = jax.random.key(2)

    def timed(fn) -> float:
        np.asarray(fn())  # warm compile + force transfer (tunnel overhead)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(fn())
            times.append(time.perf_counter() - t0)
        return min(times)

    def plain(n):
        return gen_lib.generate_tokens(
            tparams, cfg, prompt, lens, rng, max_new_tokens=n)

    def spec(n):
        # Stats ride the while_loop carry either way, so timing the
        # return_stats variant costs nothing — and reusing it for the
        # exactness/acceptance reads below avoids compiling a second
        # (stats-free) n2 program inside the TPU availability window.
        toks, _ = speculative_generate_tokens(
            tparams, cfg, dparams, cfg, prompt, lens, k=k, max_new_tokens=n,
            return_stats=True,
        )
        return toks

    n1, n2 = new_tokens, 2 * new_tokens
    # On-device exactness: the whole speculative loop (draft scan, per-row
    # verify write, rollback masks, backfill) against the plain scan loop.
    spec_toks, stats = speculative_generate_tokens(
        tparams, cfg, dparams, cfg, prompt, lens, k=k, max_new_tokens=n2,
        return_stats=True,
    )
    exact = bool(np.array_equal(np.asarray(spec_toks), np.asarray(plain(n2))))
    drafted = max(int(stats["drafted"]), 1)
    acceptance = int(stats["accepted"]) / drafted

    tp1, tp2 = timed(lambda: plain(n1)), timed(lambda: plain(n2))
    ts1, ts2 = timed(lambda: spec(n1)), timed(lambda: spec(n2))
    out = {
        "preset": preset,
        **({"quant": target_quant} if target_quant else {}),
        "draft": "self-int4",
        "k": k,
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "exact_vs_greedy": exact,
        "acceptance": round(acceptance, 4),
    }
    if tp2 > tp1 and ts2 > ts1:
        plain_tps = batch * (n2 - n1) / (tp2 - tp1)
        spec_tps = batch * (n2 - n1) / (ts2 - ts1)
        out["tok_per_s_plain"] = round(plain_tps, 2)
        out["tok_per_s_spec"] = round(spec_tps, 2)
        out["speedup"] = round(spec_tps / plain_tps, 3)
    else:
        out["note"] = ("overhead-dominated: two-point deltas collapsed; "
                       "throughputs unreliable at these shapes")
    if not exact:
        out["note"] = (out.get("note", "") +
                       " EXACTNESS FAILED: speculative != greedy").strip()
    return out


def _measure_spec_batching(
    preset: str = "tinyllama-1.1b", dtype: str = "bfloat16",
    target_quant: str = "int8", slots: int = 4, requests: int = 12,
    k: int = 4,
) -> dict:
    """Speculative vs plain continuous batching on mixed-length traffic:
    same requests, same (quantized) target, same scheduler — the spec
    variant drafts with the int4 self-draft and verifies k+1 tokens per
    target forward.  Results are asserted bit-identical; only rounds-per-
    token changes.  Quantized target so target and draft share the same
    on-device-generated base weights (cf. the spec-decode rows)."""
    import numpy as np

    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    cfg, tparams = _build_params(preset, dtype, target_quant)
    _, dparams = _build_params(preset, dtype, "int4")
    rng = np.random.RandomState(0)
    lens = rng.randint(8, 65, size=requests)
    budgets = rng.choice([8, 8, 12, 16, 16, 24, 32], size=requests).astype(
        np.int64
    )
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist() for n in lens]
    total_new = int(budgets.sum())

    def run(spec: bool):
        b = ContinuousBatcher(
            cfg, tparams, batch_slots=slots, max_len=128, chunk_steps=8,
            **(dict(draft_params=dparams, draft_cfg=cfg, spec_k=k)
               if spec else {}),
        )
        rids = [b.submit(p, max_new_tokens=int(n))
                for p, n in zip(prompts, budgets)]
        t0 = time.perf_counter()
        res = b.run()
        return time.perf_counter() - t0, [res[r] for r in rids]

    # Warm compiles outside the timed runs.
    run(False)
    run(True)
    t_plain, out_plain = run(False)
    t_spec, out_spec = run(True)
    exact = out_plain == out_spec
    out = {
        "preset": preset,
        "quant": target_quant,
        "draft": "self-int4",
        "k": k,
        "slots": slots,
        "requests": requests,
        "platform": jax.devices()[0].platform,
        "exact_vs_plain": bool(exact),
        "tok_per_s_plain": round(total_new / t_plain, 2),
        "tok_per_s_spec": round(total_new / t_spec, 2),
        "speedup": round(t_plain / t_spec, 3),
    }
    if not exact:
        out["note"] = "EXACTNESS FAILED: speculative batcher != plain"
    return out


def _measure_spec_paged(dtype: str = "bfloat16") -> dict:
    """Paged speculative serving (round 17): spec-on vs spec-off at EQUAL
    pool budget — same requests, same paged pool, same scheduler; the
    spec leg drafts with the int4 self-draft and verifies through the
    page tables (scratch-tail pages instead of the contiguous engine's
    max_len+spec_k+1 slot reservation).  Stamps steady tok/s + delivery
    ITL p50 for both legs, the acceptance fraction and downshift count,
    byte-exactness spec-on vs spec-off, and the CAPACITY arithmetic: rows
    per pool byte for contiguous-spec (which must reserve
    max_len+spec_k+1 slots per row up front) vs paged-spec (prompt +
    budget + scratch-tail pages, allocated on demand).  The capacity and
    exactness results are platform-independent; CPU tok/s is honest but
    degraded (the draft's weight-bandwidth advantage needs real chips —
    XLA:CPU dequantizes the int4 draft into the same dense flops as the
    target)."""
    from distributed_llms_tpu.runtime.batcher import (ContinuousBatcher,
                                                      pool_page_bytes)

    preset = ("gpt2-125m" if jax.devices()[0].platform == "cpu"
              else "tinyllama-1.1b")
    cfg, tparams = _build_params(preset, dtype, "int8")
    _, dparams = _build_params(preset, dtype, "int4")
    max_len, blk, pages, k, slots = 256, 16, 33, 4, 6
    rng = np.random.RandomState(0)
    lens = rng.randint(12, 41, size=8)
    budget = 40
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist() for n in lens]
    total_new = budget * len(prompts)

    def leg(spec: bool):
        b = ContinuousBatcher(
            cfg, tparams, batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=pages, page_size=blk,
            **(dict(draft_params=dparams, draft_cfg=cfg, spec_k=k)
               if spec else {}),
        )
        last: dict[int, float] = {}
        gaps: list[float] = []

        def cb(rid, new, done, lps):
            t = time.perf_counter()
            prev = last.get(rid)
            if prev is not None and new:
                gaps.append((t - prev) / len(new))
            last[rid] = t

        rids = [b.submit(p, max_new_tokens=budget) for p in prompts]
        t0 = time.perf_counter()
        res = b.run(on_tokens=cb)
        wall = time.perf_counter() - t0
        b.assert_pool_consistent()
        return wall, [res[r] for r in rids], gaps, b

    leg(False)  # warm compiles outside the timed runs
    leg(True)
    t_plain, out_plain, gaps_plain, _ = leg(False)
    t_spec, out_spec, gaps_spec, bs = leg(True)
    exact = out_plain == out_spec
    stats = bs.spec_stats
    drafted = stats["accepted"] + stats["rejected"]
    # Capacity at the SAME pool byte budget: contiguous spec reserves
    # max_len+k+1 slots per row up front; paged spec holds the workload's
    # actual footprint (prompt + budget + the k+1-slot scratch tail).
    usable = pages - 1  # page 0 is scratch
    pool_kib = usable * pool_page_bytes(cfg, blk, 16, dtype) / 1024
    rows_contig = int(usable * blk // (max_len + k + 1))
    mean_pages = -(-int(np.mean(lens) + budget + k + 1) // blk)
    rows_paged = usable // mean_pages
    out = {
        "preset": preset,
        "quant": "int8 target, int4 self-draft",
        "k": k,
        "slots": slots,
        "pool_pages": pages,
        "page_size": blk,
        "pool_kib": round(pool_kib, 1),
        "platform": jax.devices()[0].platform,
        "exact_spec_vs_plain": bool(exact),
        "tok_per_s_plain": round(total_new / t_plain, 2),
        "tok_per_s_spec": round(total_new / t_spec, 2),
        "speedup": round(t_plain / t_spec, 3),
        "itl_p50_ms_plain": round(
            float(np.percentile(gaps_plain, 50)) * 1e3, 2),
        "itl_p50_ms_spec": round(
            float(np.percentile(gaps_spec, 50)) * 1e3, 2),
        "acceptance_frac": round(stats["accepted"] / max(drafted, 1), 3),
        "spec_rounds": stats["rounds"],
        "k_downshifts": stats["downshifts"],
        "rows_contig_spec": rows_contig,
        "rows_paged_spec": rows_paged,
        "capacity_factor": round(rows_paged / max(rows_contig, 1), 2),
    }
    if not exact:
        out["note"] = "EXACTNESS FAILED: paged speculative != paged plain"
    elif out["platform"] == "cpu":
        out["note"] = (
            "CPU: the int4 draft dequantizes to FULL dense flops per step "
            "(no weight-bandwidth advantage), so spec-on tok/s needs a TPU "
            "re-stamp; exactness, capacity factor, acceptance, and the "
            "downshift count are platform-independent"
        )
    return out


def _measure_ragged_decode(
    preset: str = "tinyllama-1.1b", dtype: str = "bfloat16",
    max_len: int = 8192, slots: int = 8, iters: int = 5,
    window: int | None = None,
) -> dict:
    """Long-context decode-chunk latency: dense full-width attention vs the
    ragged decode kernel (ops/decode_attn.py) on a batch whose rows sit at
    very different cache depths — the continuous-batcher traffic shape.  The
    dense path reads all B*S KV slots per step; the ragged kernel reads only
    sum(lengths) — or, with ``window`` (Mistral-style sliding window), only
    sum(min(length, window)) per step.  Real kernels only (TPU) — interpret
    mode would time the emulator."""
    import dataclasses
    import os

    import numpy as np

    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import batcher as batcher_lib

    # Extend max_seq_len to the measured width (RoPE is computed, not a
    # table — positions past the trained range are numerically fine for a
    # throughput measurement); without this the tinyllama preset's 2048 cap
    # would silently shrink the "8k" row to a 2k measurement.
    cfg = get_preset(preset, dtype=dtype, max_seq_len=max_len,
                     sliding_window=window)
    params = model_lib.init_params(jax.random.key(0), cfg)
    rng = np.random.RandomState(0)
    # Mixed depths: a few deep rows, mostly shallow — mean fill ~35%.
    # Ranges clamp so tiny max_len (CPU smoke) stays valid.
    n_deep = max(1, slots // 4)
    deep_lo, deep_hi = max_len // 2, max(max_len // 2 + 1, max_len - 64)
    shal_lo, shal_hi = min(64, max(1, max_len // 8)), max_len // 4
    shal_hi = max(shal_hi, shal_lo + 1)
    lens = np.concatenate([
        rng.randint(deep_lo, deep_hi, size=n_deep),
        rng.randint(shal_lo, shal_hi, size=slots - n_deep),
    ]).astype(np.int32)
    cache = model_lib.init_cache(cfg, slots, max_len)
    last_tok = np.ones((slots,), np.int32)
    valid = (np.arange(max_len)[None, :] < lens[:, None])
    active = np.ones((slots,), bool)
    budget = np.full((slots,), 1 << 20, np.int32)

    def time_mode(ragged: bool) -> float:
        c = dataclasses.replace(cfg, ragged_decode=ragged)
        # Fresh donate-able cache per timing (decode_chunk donates).
        args = (params, c, jax.tree.map(jnp.copy, cache), jnp.asarray(last_tok),
                jnp.asarray(lens), jnp.asarray(valid), jnp.asarray(active),
                jnp.asarray(budget), jax.random.key(0))
        out = batcher_lib.decode_chunk(*args, 8)  # warm compile
        jax.block_until_ready(out[1].k)
        best = float("inf")
        for _ in range(iters):
            args = (params, c, jax.tree.map(jnp.copy, cache),
                    jnp.asarray(last_tok), jnp.asarray(lens),
                    jnp.asarray(valid), jnp.asarray(active),
                    jnp.asarray(budget), jax.random.key(0))
            t0 = time.perf_counter()
            out = batcher_lib.decode_chunk(*args, 8)
            jax.block_until_ready(out[1].k)
            best = min(best, time.perf_counter() - t0)
        return best

    os.environ.setdefault("DLT_RAGGED_DECODE", "auto")
    t_dense = time_mode(False)
    t_ragged = time_mode(True)
    return {
        "preset": preset,
        "max_len": max_len,
        "slots": slots,
        **({"window": window} if window is not None else {}),
        "mean_fill": round(float(lens.mean()) / max_len, 3),
        "platform": jax.devices()[0].platform,
        "dense_chunk_ms": round(t_dense * 1e3, 1),
        "ragged_chunk_ms": round(t_ragged * 1e3, 1),
        "speedup": round(t_dense / t_ragged, 3),
    }


def _measure_paged_batching(
    preset: str = "tinyllama-1.1b", dtype: str = "bfloat16",
    max_len: int = 2048, slots: int = 8, requests: int = 16,
    page_size: int = 128, pool_frac: float = 0.45,
) -> dict:
    """Paged vs contiguous continuous batching on the same mixed workload:
    the paged pool holds ``pool_frac`` of the contiguous cache's slots yet
    serves identical tokens — the memory headroom is the point; throughput
    should hold (the paged kernel reads only real depths).  TPU-only in the
    ladder (real kernels)."""
    import numpy as np

    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    cfg, params = _build_params(preset, dtype, None)
    if max_len > cfg.max_seq_len:
        max_len = cfg.max_seq_len
    rng = np.random.RandomState(0)
    # Prompt/budget ranges scale with max_len so small CPU-smoke shapes
    # stay admissible: longest prompt + longest budget <= max_len / 2.
    lens = rng.randint(max(4, max_len // 128), max(8, max_len // 8) + 1,
                       size=requests)
    base = max(2, max_len // 128)
    budgets = rng.choice(
        [base, base, 2 * base, 4 * base, 4 * base, 8 * base, 16 * base],
        size=requests,
    )
    budgets = np.minimum(budgets, max_len // 4)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist() for n in lens]
    n_pages = max(
        int(pool_frac * slots * max_len / page_size),
        max_len // page_size + 1,
    )

    def run(paged: bool) -> tuple[float, dict, int]:
        b = ContinuousBatcher(
            cfg, params, batch_slots=slots, max_len=max_len, chunk_steps=8,
            paged_pages=n_pages if paged else None, page_size=page_size,
        )
        kv_bytes = int(
            b.cache.k.size * b.cache.k.dtype.itemsize
            + b.cache.v.size * b.cache.v.dtype.itemsize
        )
        rids = [
            b.submit(p, max_new_tokens=int(n))
            for p, n in zip(prompts, budgets)
        ]
        t0 = time.perf_counter()
        res = b.run()
        return time.perf_counter() - t0, {r: res[r] for r in rids}, kv_bytes

    run(False), run(True)  # warm compiles
    t_dense, out_dense, bytes_dense = run(False)
    t_paged, out_paged, bytes_paged = run(True)
    # min-of-2 like the sibling measures: this row's claim is the
    # throughput RATIO at reduced memory — one host stall must not skew it.
    t_dense = min(t_dense, run(False)[0])
    t_paged = min(t_paged, run(True)[0])
    total_new = int(sum(len(v) for v in out_dense.values()))
    if list(out_dense.values()) != list(out_paged.values()):
        raise AssertionError("paged tokens diverge from contiguous tokens")
    return {
        "preset": preset,
        "max_len": max_len,
        "slots": slots,
        "requests": requests,
        "platform": jax.devices()[0].platform,
        "kv_bytes_contiguous": bytes_dense,
        "kv_bytes_paged": bytes_paged,
        "kv_memory_ratio": round(bytes_paged / bytes_dense, 3),
        "tok_per_s_contiguous": round(total_new / t_dense, 1),
        "tok_per_s_paged": round(total_new / t_paged, 1),
    }


def _measure_continuous_batching(
    preset: str, dtype: str, quant: str | None = None,
    slots: int = 4, requests: int = 16, chunk_steps: int = 8,
) -> dict:
    """Continuous batching vs grouped batching on a mixed-length workload.

    Grouped (the reference's model and round-2's engine): requests enter in
    batches of ``slots``; every batch decodes until its LONGEST budget, so
    short rows pad along and the batch drains before the next one starts.
    Continuous: finished rows are refilled from the queue between decode
    chunks.  Same requests, same model — the speedup is pure scheduling.
    """
    import numpy as np

    from distributed_llms_tpu.runtime import generate as gen_lib
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    cfg, params = _build_params(preset, dtype, quant)
    rng = np.random.RandomState(0)
    lens = rng.randint(8, 65, size=requests)
    # Long-tailed budgets (mostly short replies, occasional long ones) — the
    # traffic shape that causes head-of-line blocking in grouped serving.
    budgets = rng.choice(
        [8, 8, 12, 16, 16, 24, 32, 64], size=requests
    ).astype(np.int64)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist() for n in lens]
    total_new = int(budgets.sum())

    def run_continuous() -> float:
        b = ContinuousBatcher(
            cfg, params, batch_slots=slots, max_len=128, chunk_steps=chunk_steps,
        )
        rids = [
            b.submit(p, max_new_tokens=int(n)) for p, n in zip(prompts, budgets)
        ]
        t0 = time.perf_counter()
        res = b.run()
        dt = time.perf_counter() - t0
        assert all(len(res[r]) for r in rids)
        return dt

    def run_grouped() -> float:
        t0 = time.perf_counter()
        for i in range(0, requests, slots):
            grp = list(range(i, min(i + slots, requests)))
            t = max(lens[g] for g in grp)
            arr = np.zeros((len(grp), int(t)), np.int32)
            for j, g in enumerate(grp):
                arr[j, : lens[g]] = prompts[g]
            out = gen_lib.generate_tokens(
                params, cfg, jnp.asarray(arr),
                jnp.asarray([int(lens[g]) for g in grp], jnp.int32),
                jax.random.key(0),
                max_new_tokens=int(max(budgets[g] for g in grp)),
            )
            np.asarray(out)
        return time.perf_counter() - t0

    # Warm compilation caches for both paths, then time.
    run_continuous()
    run_grouped()
    t_cb = min(run_continuous(), run_continuous())
    t_grp = min(run_grouped(), run_grouped())
    return {
        "preset": preset,
        **({"quant": quant} if quant else {}),
        "slots": slots,
        "requests": requests,
        "platform": jax.devices()[0].platform,
        "useful_tokens": total_new,
        "tok_per_s_continuous": round(total_new / t_cb, 1),
        "tok_per_s_grouped": round(total_new / t_grp, 1),
        "speedup": round(t_grp / t_cb, 3),
    }


def _measure_local_proc_batching(
    dtype: str = "bfloat16", requests: int = 12, workers: int = 2,
) -> dict:
    """End-to-end cluster serving with true process isolation, measured
    honestly on CPU: an in-bench Coordinator + N ``cli.host_main`` worker
    SUBPROCESSES (the reference's planned multiprocessing local simulation,
    plan.md:225-233), shards placed from a store, then mixed-budget batches
    served concurrently — one per worker — through each worker's continuous
    batcher (VERDICT r4 item 9: the provable-without-hardware serving row).

    Metrics: end-to-end tok/s through the control plane + wire protocol vs
    the workers' own in-engine tok/s (their delta is the cluster-path
    overhead), plus the p50 round trip of a single 1-token request (the
    serving-latency floor of the coordinator path).  Workers pin
    ``--platform cpu`` so this row never touches (or contends for) a TPU.
    """
    import asyncio
    import subprocess
    import sys
    import tempfile

    import numpy as np

    from distributed_llms_tpu.checkpoint import store as store_lib
    from distributed_llms_tpu.cluster.coordinator import Coordinator
    from distributed_llms_tpu.core.config import ClusterConfig
    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset

    preset = FALLBACK["preset"]
    cfg = get_preset(preset, dtype=dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    rng = np.random.RandomState(0)
    budgets = rng.choice([8, 8, 12, 16, 16, 24, 32, 64], size=requests)
    texts = ["bench prompt " + "x" * int(n) for n in rng.randint(4, 40, requests)]
    reqs = [
        {"prompt": p, "max_new_tokens": int(n)} for p, n in zip(texts, budgets)
    ]
    half = (len(reqs) + workers - 1) // workers
    batches = [reqs[i: i + half] for i in range(0, len(reqs), half)]

    async def drive(store_dir: str) -> dict:
        ccfg = ClusterConfig(
            coordinator_host="127.0.0.1", coordinator_port=0,
            task_timeout_s=1200.0, heartbeat_timeout_s=1200.0,
        )
        coord = Coordinator(ccfg)
        await coord.start()
        procs: list[subprocess.Popen] = []
        try:
            # Spawn INSIDE the try: a failed later Popen must still tear
            # down earlier workers and the coordinator via the finally.
            for i in range(workers):
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "distributed_llms_tpu.cli.host_main",
                     "--host", "127.0.0.1", "--port", str(coord.port),
                     "--platform", "cpu", "--worker-id", f"bench-w{i}"],
                ))
            for _ in range(1200):  # jax import in children takes seconds
                if len(coord.workers) >= workers:
                    break
                await asyncio.sleep(0.1)
            if len(coord.workers) < workers:
                raise RuntimeError(
                    f"only {len(coord.workers)}/{workers} workers registered"
                )
            coord.plan_shards(workers, store_dir=store_dir)
            await coord.place_shards(timeout=600.0)

            # Warmup: compile each worker's batcher path (tiny budgets).
            warm = [{"prompt": "warm", "max_new_tokens": 2}]
            await asyncio.gather(*(
                coord.generate_requests(warm, timeout=1200.0)
                for _ in range(workers)
            ))

            t0 = time.perf_counter()
            outs = await asyncio.gather(*(
                coord.generate_requests(b, timeout=1200.0) for b in batches
            ))
            wall = time.perf_counter() - t0

            # Serving-latency floor: 1-token single-request round trips.
            rtts = []
            one = [{"prompt": "ping", "max_new_tokens": 1}]
            for _ in range(10):
                t1 = time.perf_counter()
                await coord.generate_requests(one, timeout=1200.0)
                rtts.append(time.perf_counter() - t1)
            rtts.sort()
            return {
                "outs": outs, "wall": wall,
                "rtt_p50_ms": round(1e3 * rtts[len(rtts) // 2], 1),
            }
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
            await coord.stop()

    with tempfile.TemporaryDirectory() as store_dir:
        store_lib.save_shards(
            params, store_dir, num_shards=workers, model_config=cfg
        )
        del params  # children load from the store; no need to hold a copy
        res = asyncio.run(drive(store_dir))
    total = sum(o["generated_tokens"] for o in res["outs"])
    engine_rate = sum(o["tokens_per_second"] for o in res["outs"])
    e2e = total / max(res["wall"], 1e-9)
    return {
        "preset": preset, "workers": workers, "requests": requests,
        "platform": "cpu (coordinator + worker subprocesses)",
        "useful_tokens": int(total),
        "tok_per_s_end_to_end": round(e2e, 1),
        "tok_per_s_in_engine": round(engine_rate, 1),
        "cluster_overhead_pct": round(100 * (1 - e2e / max(engine_rate, 1e-9)), 1),
        "rtt_1tok_p50_ms": res["rtt_p50_ms"],
    }


def _measure_chunked_prefill(
    preset: str | None = None, dtype: str = "bfloat16",
    chunk: int = 64, long_len: int = 1024, iters: int = 3,
) -> dict:
    """Chunked-prefill QoS: a SHORT request arrives while a LONG prompt is
    being admitted.  Monolithic admission runs the whole long prefill
    before the short request can admit or decode; chunked admission
    interleaves, so the short request finishes while the long prompt is
    still chunking.  The metric is the short request's completion latency
    under long-prompt interference — a pure scheduling effect, honestly
    measurable on any platform (the long row's own throughput is
    unchanged; tokens are identical either way)."""
    import numpy as np

    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    max_len = min(long_len + 64, cfg.max_seq_len)
    long_ids = np.random.RandomState(0).randint(
        1, cfg.vocab_size, size=max_len - 40
    ).tolist()
    short_ids = [7, 1, 9]

    def short_latency(prefill_chunk, n=iters) -> float:
        best = float("inf")
        for _ in range(n):
            b = ContinuousBatcher(
                cfg, params, batch_slots=2, max_len=max_len, chunk_steps=4,
                prefill_chunk=prefill_chunk,
            )
            b.submit(long_ids, max_new_tokens=8)
            rid_s = b.submit(short_ids, max_new_tokens=8)
            done_at = {}
            t0 = time.perf_counter()

            def cb(rid, new, done, lps):
                if done:
                    done_at[rid] = time.perf_counter() - t0

            b.run(on_tokens=cb)
            best = min(best, done_at[rid_s])
        return best

    # Warm compiles for both modes before timing (one run each suffices).
    short_latency(None, n=1)
    short_latency(chunk, n=1)
    t_mono = short_latency(None)
    t_chunk = short_latency(chunk)
    return {
        "preset": preset,
        "long_prompt": len(long_ids),
        "prefill_chunk": chunk,
        "platform": jax.devices()[0].platform,
        "short_done_ms_monolithic": round(t_mono * 1e3, 1),
        "short_done_ms_chunked": round(t_chunk * 1e3, 1),
        "speedup": round(t_mono / t_chunk, 3),
    }


def _measure_prefix_cache_ttft(
    preset: str | None = None, dtype: str = "bfloat16",
    prefix_len: int = 384, suffix_len: int = 16, requests: int = 8,
    page_size: int = 64, new_tokens: int = 4, iters: int = 2,
    shared_frac: float = 0.75,
) -> dict:
    """Automatic prefix caching (hash-block KV reuse in the paged pool):
    TTFT on a ``shared_frac`` shared-prefix workload — the chat-traffic
    shape (system prompts, few-shot templates; production chat traffic
    shares far more than half its prefix tokens) — with the cache ON vs
    OFF.  Requests are
    served one at a time so each TTFT isolates its own admission prefill;
    with the cache ON, shared-prefix requests prefill only their un-cached
    suffix (a page-table gather replaces the prefix prefill).  The ratio is
    a compute effect (prefill tokens skipped), honestly measurable on any
    platform; prefill-tokens-saved and the cache hit rate come from the
    batcher's own PrefixCache counters, warm-up excluded.  Per-request
    TTFTs take the min over ``iters`` passes with a FRESH batcher+cache
    per pass (so a later pass never turns the unique prompts into hits) —
    the same host-stall defense as the sibling min-of-2 rows."""
    import numpy as np

    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    total = prefix_len + suffix_len + new_tokens
    max_len = min(-(-total // page_size) * page_size,
                  cfg.max_seq_len // page_size * page_size)
    if max_len < total:  # tiny-preset guard: shrink the prefix to fit
        prefix_len = max_len - suffix_len - new_tokens
    pool = 3 * (max_len // page_size) + 1
    rng = np.random.RandomState(0)
    shared = rng.randint(1, cfg.vocab_size, size=prefix_len).tolist()
    # Interleave shared and unique requests (no ordering artifact): the
    # first shared_frac of each position-modulo stripe shares the prefix.
    n_unique = max(1, round(requests * (1.0 - shared_frac)))
    stride = requests // n_unique
    is_shared = [(i % stride) != stride - 1 for i in range(requests)]
    workload = []
    for i in range(requests):
        if is_shared[i]:
            ids = shared + rng.randint(1, cfg.vocab_size,
                                       size=suffix_len).tolist()
        else:
            ids = rng.randint(1, cfg.vocab_size,
                              size=prefix_len + suffix_len).tolist()
        workload.append(ids)

    def run(auto: bool):
        b = ContinuousBatcher(
            cfg, params, batch_slots=2, max_len=max_len, chunk_steps=4,
            paged_pages=pool, page_size=page_size, prefix_cache=auto,
        )
        # Warm: two shared-prefix requests compile both admission programs
        # (full-prompt miss and suffix-continuation hit) and, cache-on,
        # seed the pages the measured requests will hit.
        for _ in range(2):
            b.submit(shared + rng.randint(1, cfg.vocab_size,
                                          size=suffix_len).tolist(),
                     max_new_tokens=new_tokens)
            b.run()
        # Snapshot after warm-up so the reported savings and hit rate
        # describe ONLY the measured workload.
        warm = ((b.prefix_cache.hit_tokens, b.prefix_cache.miss_tokens)
                if auto else (0, 0))
        ttfts = []
        for ids in workload:
            seen = {}

            def cb(rid, new, done, lps):
                seen.setdefault("t", time.perf_counter())

            t0 = time.perf_counter()
            b.submit(ids, max_new_tokens=new_tokens)
            b.run(on_tokens=cb)
            ttfts.append(seen["t"] - t0)
        return ttfts, b, warm

    def measure(auto: bool):
        best, b, warm = run(auto)
        for _ in range(iters - 1):
            ttfts, b, warm = run(auto)
            best = [min(a, c) for a, c in zip(best, ttfts)]
        return best, b, warm

    ttfts_off, _b, _w = measure(False)
    ttfts_on, b_on, (warm_hits, warm_misses) = measure(True)
    pc = b_on.prefix_cache
    hits = pc.hit_tokens - warm_hits
    misses = pc.miss_tokens - warm_misses
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
    shared_off = [t for t, s in zip(ttfts_off, is_shared) if s]
    shared_on = [t for t, s in zip(ttfts_on, is_shared) if s]
    return {
        "preset": preset,
        "prefix_len": prefix_len,
        "suffix_len": suffix_len,
        "requests": requests,
        "shared_prefix_frac": round(sum(is_shared) / requests, 3),
        "page_size": page_size,
        "platform": jax.devices()[0].platform,
        "ttft_ms_cache_off": round(mean(ttfts_off) * 1e3, 1),
        "ttft_ms_cache_on": round(mean(ttfts_on) * 1e3, 1),
        "ttft_ms_shared_off": round(mean(shared_off) * 1e3, 1),
        "ttft_ms_shared_on": round(mean(shared_on) * 1e3, 1),
        "speedup": round(mean(ttfts_off) / mean(ttfts_on), 3),
        "prefill_tokens_saved": hits,
        "hit_rate": round(hits / max(hits + misses, 1), 3),
    }


async def _serving_post(host: str, port: int, req: dict):
    """One raw POST /v1/completions against a serving replica/router —
    the ONE mini-client every serving bench shares (status parse, header
    skip, read-to-EOF body).  Returns (status, parsed JSON body)."""
    import asyncio
    import json as _json

    reader, writer = await asyncio.open_connection(host, port)
    body = _json.dumps(req).encode()
    writer.write(
        f"POST /v1/completions HTTP/1.1\r\nHost: b\r\n"
        f"Content-Length: {len(body)}\r\n\r\n".encode() + body
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b"\n", b""):
        pass
    out = _json.loads(await reader.read())
    writer.close()
    return status, out


def _measure_fault_recovery(
    preset: str | None = None, dtype: str = "bfloat16",
    requests: int = 8, new_tokens: int = 24, page_size: int = 16,
) -> dict:
    """Crash-safe serving (runtime/server.py supervisor): inject a
    decode-step crash under concurrent load and measure (a) supervisor
    recovery latency — crash to the first post-restart token delivery —
    and (b) the fraction of requests that still complete.  Zero-streamed
    requests re-admit (temp-0 exact); requests that had streamed before the
    crash fail with a structured error, so the completed fraction is
    (requests - rows_in_flight_at_crash) / requests by design.  A pure
    host-scheduling effect, honestly measurable on any platform."""
    import asyncio
    import json as _json

    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.faults import FaultPlane
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    max_len = 8 * page_size
    slots = 2

    def make_batcher(faults=None):
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=2 * slots * (max_len // page_size) + 1,
            page_size=page_size, faults=faults,
        )

    # Warm both compiled programs (admission + decode) outside the timing.
    warm = make_batcher()
    warm.submit("warm me up", max_new_tokens=new_tokens)
    warm.run()

    async def one_request(host, port, i):
        return await _serving_post(host, port, {
            "prompt": f"request number {i}", "max_tokens": new_tokens,
        })

    async def drive() -> dict:
        plane = FaultPlane.parse("batcher.decode:raise@2")
        srv = InferenceServer(make_batcher(plane), model_name="bench",
                              host="127.0.0.1", port=0)
        host, port = await srv.start()
        restarts0 = METRICS.get_counter("server.engine_restarts")
        retried0 = METRICS.get_counter("server.requests_retried")
        rec0 = METRICS.snapshot()["histograms"].get(
            "server.recovery_seconds", {}
        ).get("count", 0)
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            one_request(host, port, i) for i in range(requests)
        ])
        wall = time.perf_counter() - t0
        await srv.stop()
        completed = sum(
            1 for status, out in outs
            if status == 200
            and out["usage"]["completion_tokens"] == new_tokens
        )
        rec = METRICS.snapshot()["histograms"].get(
            "server.recovery_seconds", {}
        )
        assert rec.get("count", 0) > rec0, "supervisor never recovered"
        return {
            "requests": requests,
            "new_tokens": new_tokens,
            "completed": completed,
            "completed_frac": round(completed / requests, 3),
            "engine_restarts": int(
                METRICS.get_counter("server.engine_restarts") - restarts0
            ),
            "requests_retried": int(
                METRICS.get_counter("server.requests_retried") - retried0
            ),
            "recovery_ms": round(rec["max"] * 1e3, 1),
            "wall_ms": round(wall * 1e3, 1),
        }

    out = asyncio.run(drive())
    out.update({"preset": preset, "platform": jax.devices()[0].platform})
    return out


def _measure_replica_failover(
    preset: str | None = None, dtype: str = "bfloat16",
    replicas: int = 3, requests: int = 12, new_tokens: int = 24,
    page_size: int = 16,
) -> dict:
    """Replica-fleet serving (runtime/router.py + cluster/fleet.py): N
    full server/batcher replicas behind the health-aware router; one
    replica is KILLED abruptly mid-storm.  Measured: failover recovery
    latency (failure observed -> the re-placed request answered), goodput
    through the storm, and the exactness count — every 200 is compared
    byte-for-byte against an un-faulted reference run (temp-0 exact
    failover is the contract, not best-effort).  A host-scheduling
    effect, honestly measurable on any platform."""
    import asyncio
    import json as _json

    from distributed_llms_tpu.cluster.fleet import ReplicaFleet
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.router import ReplicaRouter
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    max_len = 8 * page_size
    slots = 2

    def make_batcher():
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=2 * slots * (max_len // page_size) + 1,
            page_size=page_size, prefix_cache=True,
        )

    def make_server():
        return InferenceServer(
            make_batcher(), model_name="bench", host="127.0.0.1", port=0,
            batcher_factory=make_batcher, watchdog_timeout_s=2.0,
        )

    prompts = [f"replica storm request {i:02d}" for i in range(requests)]
    # Reference texts + jit warm-up in one go (the replicas share the
    # compiled programs process-wide).
    ref = make_batcher()
    rids = [ref.submit(p, max_new_tokens=new_tokens) for p in prompts]
    ref_res = ref.run()
    wants = {p: tok.decode(ref_res[r]) for p, r in zip(prompts, rids)}

    async def one_request(host, port, p):
        return await _serving_post(
            host, port, {"prompt": p, "max_tokens": new_tokens}
        )

    async def drive() -> dict:
        fleet = ReplicaFleet([make_server] * replicas,
                             probe_interval_s=0.05)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=tok, page_size=page_size)
        await fleet.start()
        host, port = await router.start()
        assert await fleet.wait_healthy(timeout_s=60.0)
        fo0 = METRICS.get_counter("router.failovers")
        rec0 = METRICS.snapshot()["histograms"].get(
            "router.failover_seconds", {}
        ).get("count", 0)

        async def staggered(i, p):
            await asyncio.sleep(i * 0.05)
            return await one_request(host, port, p)

        t0 = time.perf_counter()
        tasks = [asyncio.create_task(staggered(i, p))
                 for i, p in enumerate(prompts)]
        for _ in range(2000):  # kill r0 once real work is in flight on it
            if fleet["r0"].inflight:
                break
            await asyncio.sleep(0.005)
        await fleet.kill("r0")
        outs = await asyncio.gather(*tasks)
        wall = time.perf_counter() - t0
        await router.stop()
        await fleet.stop()
        completed = [(p, out) for (status, out), p in zip(outs, prompts)
                     if status == 200]
        exact = sum(
            1 for p, out in completed
            if out["choices"][0]["text"] == wants[p]
        )
        good_tokens = sum(
            out["usage"]["completion_tokens"] for _p, out in completed
        )
        hist = METRICS.snapshot()["histograms"].get(
            "router.failover_seconds", {}
        )
        assert hist.get("count", 0) > rec0, "no failover was ever taken"
        return {
            "replicas": replicas,
            "requests": requests,
            "new_tokens": new_tokens,
            "completed": len(completed),
            "exact": exact,
            "completed_frac": round(len(completed) / requests, 3),
            "failovers": int(
                METRICS.get_counter("router.failovers") - fo0
            ),
            "recovery_ms": round(hist["max"] * 1e3, 1),
            "goodput_tok_per_s": round(good_tokens / wall, 1),
            "wall_ms": round(wall * 1e3, 1),
        }

    out = asyncio.run(drive())
    out.update({"preset": preset, "platform": jax.devices()[0].platform})
    return out


def _measure_disagg_handoff(
    preset: str | None = None, dtype: str = "bfloat16",
    shorts: int = 2, longs: int = 2, new_tokens: int = 48,
    page_size: int = 16,
) -> dict:
    """Disaggregated prefill/decode (runtime/router.py handoff plane +
    cluster/kv_transfer.py): short requests are mid-decode when LONG
    prompts arrive — colocated, each long's monolithic prefill runs ON
    the decoding engine and stalls every in-flight stream for its whole
    forward; disaggregated, the prefill tier absorbs it and the decode
    engine admits only a < 1-page suffix.  Stamped: the shorts'
    completion time under that interference in both topologies (the
    decode-tok/s interference the handoff exists to remove), the
    verified handoff's latency (prefill + transfer + import), and the
    fallback recovery time when the prefill tier is KILLED (the next
    long request degrades to colocated prefill — byte-exact, just
    slower).  Every 200 is byte-compared against an un-faulted
    colocated reference.  A host-scheduling effect, honestly measurable
    on any platform."""
    import asyncio
    import json as _json

    from distributed_llms_tpu.cluster.fleet import ReplicaFleet
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.router import ReplicaRouter
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    max_len = 16 * page_size  # long prompts span ~14 full pages
    slots = 4  # shorts keep decoding while longs admit beside them

    def make_batcher():
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=slots * (max_len // page_size) + 9,
            page_size=page_size, prefix_cache=True,
        )

    def make_server(role):
        return InferenceServer(
            make_batcher(), model_name="bench", host="127.0.0.1", port=0,
            batcher_factory=make_batcher, watchdog_timeout_s=10.0, role=role,
        )

    long_prompts = [
        f"disaggregation long prompt {i:02d} " * 8 for i in range(longs)
    ]
    short_prompts = [f"short request {i:02d}" for i in range(shorts)]
    # The fallback-recovery probe must be a FRESH prompt: a re-sent one
    # would be affinity-warm on the decode replica and the router would
    # (correctly) skip the handoff instead of degrading it.
    fallback_prompt = "fallback recovery probe xx " * 8
    reqs = [(p, 4) for p in long_prompts + [fallback_prompt]] \
        + [(p, new_tokens) for p in short_prompts]
    ref = make_batcher()
    rids = [ref.submit(p, max_new_tokens=n) for p, n in reqs]
    ref_res = ref.run()
    wants = {p: tok.decode(ref_res[r]) for r, (p, n) in zip(rids, reqs)}
    # Warm the CACHE-HIT admission program (admit_row_auto_paged at the
    # long prompts' exact bucket shapes): a handed-off request admits
    # through it on the decode tier, and only the disaggregated leg would
    # otherwise pay its compile — which would bill XLA compile time as
    # "interference" against exactly one leg of the comparison.
    for p in long_prompts:
        ref.submit(p, max_new_tokens=2)
    ref.run()

    async def one_request(host, port, p, n):
        t0 = time.perf_counter()
        status, out = await _serving_post(
            host, port, {"prompt": p, "max_tokens": n}
        )
        return status, out, (time.perf_counter() - t0) * 1e3

    async def drive_leg(roles, names, handoff):
        fleet = ReplicaFleet(
            [(lambda r: (lambda: make_server(r)))(r) for r in roles],
            names=names, probe_interval_s=0.05,
        )
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=tok, page_size=page_size,
                               handoff=handoff)
        await fleet.start()
        host, port = await router.start()
        assert await fleet.wait_healthy(timeout_s=120.0)
        t0 = time.perf_counter()
        # Shorts first; the longs land once the shorts are decoding, so
        # their prefills interfere (or, disaggregated, don't).
        short_tasks = [
            asyncio.create_task(one_request(host, port, p, new_tokens))
            for p in short_prompts
        ]
        await asyncio.sleep(0.4)
        long_tasks = [
            asyncio.create_task(one_request(host, port, p, 4))
            for p in long_prompts
        ]
        outs = await asyncio.gather(*short_tasks, *long_tasks)
        wall = time.perf_counter() - t0
        prompts = short_prompts + long_prompts
        exact = completed = good_tokens = 0
        short_ms = []
        for p, (status, out, ms) in zip(prompts, outs):
            if status != 200:
                continue
            completed += 1
            exact += out["choices"][0]["text"] == wants[p]
            good_tokens += out["usage"]["completion_tokens"]
            if p in short_prompts:
                short_ms.append(ms)
        extra = {}
        if handoff:
            # Fallback recovery: kill the prefill tier, then time one
            # more long request end to end — it degrades to colocated
            # prefill on a decode replica, byte-exact.
            fb0 = METRICS.get_counter("router.handoff_fallbacks")
            await fleet.kill(names[0])
            t1 = time.perf_counter()
            status, out, _ms = await one_request(
                host, port, fallback_prompt, 4
            )
            extra["fallback_recovery_ms"] = round(
                (time.perf_counter() - t1) * 1e3, 1
            )
            assert status == 200
            assert out["choices"][0]["text"] == wants[fallback_prompt]
            assert METRICS.get_counter("router.handoff_fallbacks") > fb0
            # The probe is a served, byte-checked request: count it.
            completed += 1
            exact += 1
        await router.stop()
        await fleet.stop()
        return {
            "completed": completed, "exact": exact,
            "goodput_tok_per_s": round(good_tokens / wall, 1),
            "short_ms_mean": round(sum(short_ms) / max(1, len(short_ms)), 1),
            **extra,
        }

    async def drive() -> dict:
        h0 = METRICS.snapshot()["histograms"].get(
            "router.handoff_seconds", {}
        ).get("count", 0)
        colo = await drive_leg(["colocated"], ["c0"], handoff=False)
        disagg = await drive_leg(
            ["prefill", "decode"], ["p0", "d0"], handoff=True
        )
        hist = METRICS.snapshot()["histograms"].get(
            "router.handoff_seconds", {}
        )
        assert hist.get("count", 0) > h0, "no handoff ever completed"
        return {
            # Both legs serve longs+shorts each; the disaggregated leg
            # adds the fallback-recovery probe — completed/exact below
            # count against exactly this total.
            "requests": 2 * (longs + shorts) + 1,
            "longs": longs, "shorts": shorts, "new_tokens": new_tokens,
            "prompt_tokens_long": len(long_prompts[0]),
            "completed": colo["completed"] + disagg["completed"],
            "exact": colo["exact"] + disagg["exact"],
            "short_ms_colocated": colo["short_ms_mean"],
            "short_ms_disagg": disagg["short_ms_mean"],
            "interference_speedup": round(
                colo["short_ms_mean"] / max(1e-9, disagg["short_ms_mean"]), 2
            ),
            "handoff_ms_p50": round(hist["p50"] * 1e3, 1),
            "fallback_recovery_ms": disagg["fallback_recovery_ms"],
            "goodput_tok_per_s": disagg["goodput_tok_per_s"],
        }

    out = asyncio.run(drive())
    out.update({"preset": preset, "platform": jax.devices()[0].platform})
    return out


def _measure_overload_goodput(
    preset: str | None = None, dtype: str = "bfloat16",
    requests: int = 10, new_tokens: int = 48, page_size: int = 16,
) -> dict:
    """Overload-safe serving (PR 3): offered load at ~2x the KV pool's
    token capacity against a small paged pool.  Rows admit with prompt +
    one decode page and GROW on demand; the pool runs dry mid-storm, so
    the engine preempts (recompute, temp-0 exact) while the server's cost
    gate sheds the tail of the burst with 429 + Retry-After.  Reported:
    goodput (completed tokens/s of wall time), the shed fraction, and the
    preemption count — a host-scheduling effect, honestly measurable on
    any platform.  Clients take NO retries (we are measuring the shed
    policy, not retry patience)."""
    import asyncio

    from distributed_llms_tpu.cluster.client import ServingClient
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    slots = 8
    max_len = 8 * page_size
    pool_pages = 21  # 20 usable = 320-token capacity at page 16

    def make_batcher():
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=pool_pages, page_size=page_size,
        )

    # Warm the compiled programs outside the timing.
    warm = make_batcher()
    warm.submit("warm me up", max_new_tokens=new_tokens)
    warm.run()

    prompts = [f"overload req {i:02d}" for i in range(requests)]
    capacity = (pool_pages - 1) * page_size
    offered = sum(len(tok.encode(p)) + new_tokens for p in prompts)

    async def drive() -> dict:
        srv = InferenceServer(
            make_batcher(), model_name="bench", host="127.0.0.1", port=0,
            shed_cost_factor=1.2,
        )
        host, port = await srv.start()
        preempt0 = METRICS.get_counter("batcher.preemptions_total")
        shed0 = METRICS.get_counter("server.requests_shed_total")
        clients = [ServingClient(host, port, max_retries=0)
                   for _ in prompts]
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            c.completions({"prompt": p, "max_tokens": new_tokens})
            for c, p in zip(clients, prompts)
        ])
        wall = time.perf_counter() - t0
        for _ in range(200):  # drain before the audit
            if all(r.rid is None for r in srv.batcher.rows):
                break
            await asyncio.sleep(0.05)
        srv.batcher.assert_pool_consistent()
        await srv.stop()
        completed = [o for s, o in outs if s == 200]
        good_tokens = sum(o["usage"]["completion_tokens"] for o in completed)
        shed = sum(1 for s, _o in outs if s in (429, 503))
        assert len(completed) + shed == requests, outs
        return {
            "requests": requests,
            "new_tokens": new_tokens,
            "pool_capacity_tokens": capacity,
            "offered_x": round(offered / capacity, 2),
            "completed": len(completed),
            "completed_frac": round(len(completed) / requests, 3),
            "shed_frac": round(shed / requests, 3),
            "goodput_tok_per_s": round(good_tokens / wall, 1),
            "preemptions": int(
                METRICS.get_counter("batcher.preemptions_total") - preempt0
            ),
            "requests_shed": int(
                METRICS.get_counter("server.requests_shed_total") - shed0
            ),
            "wall_ms": round(wall * 1e3, 1),
        }

    out = asyncio.run(drive())
    out.update({"preset": preset, "platform": jax.devices()[0].platform})
    return out


def _measure_tenant_qos(
    preset: str | None = None, dtype: str = "bfloat16",
    page_size: int = 16,
) -> dict:
    """Elastic multi-tenant serving (ISSUE 15), two scenes:

    (a) NOISY NEIGHBOR: the traffic harness (runtime/workload.py)
    replays the same two-tenant trace — an aggressor offering 5x its
    token-rate quota in a storm-then-calm diurnal square wave, next to
    a steadily pacing victim — against one server with tenant QoS OFF
    (tenant-blind FIFO) and ON (weighted-fair TenantScheduler +
    per-tenant rate quota).  Stamped: the victim's goodput (SLO-met
    tokens/s), p95 ITL, and SLO attainment under both, plus the
    aggressor's structured-shed fraction — the isolation claim is
    victim goodput ON >= 2x OFF while the aggressor throttles via
    429+Retry-After instead of starving anyone silently.

    (b) ELASTIC CYCLE: a min=1/max=2 fleet under the autoscaler; a
    burst drives one scale-up (recovery = burst start -> second replica
    healthy) and the idle tail one graceful scale-down.  Host-
    scheduling effects, honestly measurable on any platform."""
    import asyncio

    from distributed_llms_tpu.cluster.autoscale import Autoscaler
    from distributed_llms_tpu.cluster.fleet import ReplicaFleet
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import workload
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.router import ReplicaRouter
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    # llama-tiny at the byte vocab (259 = bytes + specials): every
    # sampled id is visible text, so streamed chars == tokens and the
    # harness's TTFT/ITL/goodput are real.  Bigger presets only add
    # decode time on CPU — the queueing/fairness effects this row
    # measures are host-side.
    del preset
    cfg = get_preset("llama-tiny", vocab_size=259, max_seq_len=256,
                     dtype=dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    slots, max_len, pool_pages = 2, 12 * page_size, 26
    weights = {"vic": 2.0, "agg": 1.0}
    window_s = 2.0

    def make_batcher(fair: bool):
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=pool_pages, page_size=page_size,
            tenant_weights=("vic:2,agg:1" if fair else None),
            tenant_max_rows=(1 if fair else None),
        )

    # One trace, replayed against both legs: a STORM phase (the
    # aggressor floods at ~2-3x the engine's loaded service rate, so
    # the tenant-blind queue is pinned at the cost-gate bound the whole
    # phase) then a CALM tail (aggressor near-idle, the backlog drains)
    # — the two-phase square wave a diurnal peak looks like at bench
    # timescale, and the calm tail is the measurement's own CONTROL:
    # the victim demonstrably meets its SLO on an uncrowded engine even
    # with fairness off, so the storm-phase misses are crowding, not
    # model/SLO miscalibration.  The victim paces steadily across both
    # phases.  The quota pins "aggressor at 5x ITS quota" BY
    # CONSTRUCTION: quota = the trace's offered aggressor token rate / 5.
    import dataclasses

    horizon, storm_s = 8.0, 6.0
    agg_spec = workload.TenantSpec(
        "agg", rate_rps=50.0, burst_rate_x=1.5, burst_enter_hz=0.3,
        burst_exit_hz=0.6, prompt_len=(24, 40), output_len=(64, 96),
        shared_frac=0.25,
    )
    storm = workload.generate([agg_spec], storm_s, seed=3)
    calm = workload.generate(
        [dataclasses.replace(agg_spec, rate_rps=1.0)],
        horizon - storm_s, seed=4,
    )
    vic = workload.generate(
        [workload.TenantSpec("vic", rate_rps=3.0, prompt_len=(12, 24),
                             output_len=(6, 10))],
        horizon, seed=3,
    )
    arrivals = (storm
                + [dataclasses.replace(a, t=a.t + storm_s) for a in calm]
                + vic)
    arrivals.sort(key=lambda a: (a.t, a.tenant, a.prompt))
    agg_offered_tokens = sum(
        len(a.prompt) + a.max_tokens for a in arrivals if a.tenant == "agg"
    )
    quota_tps = agg_offered_tokens / horizon / 5.0
    offered_x = agg_offered_tokens / (quota_tps * horizon)  # vs ITS quota
    ttft_slo_s = 0.3

    def make_server(fair: bool):
        return InferenceServer(
            make_batcher(fair), model_name="bench", host="127.0.0.1",
            port=0, batcher_factory=lambda: make_batcher(fair),
            # Same deep queue BOTH legs (the only asymmetry is the
            # tenant knobs): at the 2.0 default the global cost gate
            # caps the backlog near one SLO of work and shields the
            # victim from FIFO queueing — the very effect the OFF leg
            # must exhibit.
            shed_cost_factor=8.0,
            tenant_weights=(dict(weights) if fair else None),
            tenant_quota_tps=(quota_tps if fair else None),
            tenant_rate_window_s=window_s,
        )

    # Warm the compiled programs outside every timing window.
    warm = make_batcher(True)
    warm.submit("warm me up", max_new_tokens=24)
    warm.run()

    async def leg(fair: bool) -> dict:
        srv = make_server(fair)
        host, port = await srv.start()
        try:
            recs = await workload.replay(host, port, arrivals)
        finally:
            for _ in range(200):  # drain before the audit
                if all(r.rid is None for r in srv.batcher.rows):
                    break
                await asyncio.sleep(0.05)
            srv.batcher.assert_pool_consistent()
            await srv.stop()
        return workload.summarize(recs, horizon, ttft_slo_s=ttft_slo_s)

    off = asyncio.run(leg(False))
    on = asyncio.run(leg(True))

    # (b) one autoscale up/down cycle on a live min=1/max=2 fleet.
    async def cycle() -> tuple[float, float]:
        fleet = ReplicaFleet([lambda: make_server(True)],
                             probe_interval_s=0.05)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=tok, page_size=page_size)
        await fleet.start()
        host, port = await router.start()
        scaler = Autoscaler(fleet, min_replicas=1, max_replicas=2,
                            up_load=0.2, down_load=0.05, hysteresis=2,
                            cooldown_s=0.2, drain_timeout_s=20.0,
                            replica_capacity_tokens=(pool_pages - 1)
                            * page_size)
        try:
            await fleet.wait_healthy(timeout_s=60.0)
            burst = asyncio.ensure_future(
                workload.replay(host, port, arrivals[:10])
            )
            t0 = time.perf_counter()
            up_s = down_s = float("nan")
            for _ in range(600):
                await asyncio.sleep(0.02)
                await scaler.tick()
                if len(fleet.replicas) == 2:
                    up_s = time.perf_counter() - t0
                    break
            await burst
            t1 = time.perf_counter()
            # Only time the drain if the fleet actually grew: keying on
            # replica count alone would stamp a bogus ~0s "scale-down"
            # when the burst never drove a scale-up.
            if math.isfinite(up_s):
                for _ in range(600):
                    await asyncio.sleep(0.02)
                    await scaler.tick()
                    if len(fleet.replicas) == 1:
                        down_s = time.perf_counter() - t1
                        break
            return up_s, down_s
        finally:
            await router.stop()
            await fleet.stop()

    up_s, down_s = asyncio.run(cycle())
    vic_on, vic_off = on["vic"], off["vic"]
    agg_on = on["agg"]
    gain = (vic_on["goodput_tok_s"] / vic_off["goodput_tok_s"]
            if vic_off["goodput_tok_s"] > 0 else float("inf"))
    return {
        "preset": "llama-tiny",
        "platform": jax.devices()[0].platform,
        "ttft_slo_s": ttft_slo_s,
        "aggressor_offered_x": round(offered_x, 2),
        "victim_goodput_off": round(vic_off["goodput_tok_s"], 1),
        "victim_goodput_on": round(vic_on["goodput_tok_s"], 1),
        "victim_goodput_gain": (round(gain, 2)
                                if gain != float("inf") else "inf"),
        "victim_slo_off": round(vic_off["slo_attainment"], 3),
        "victim_slo_on": round(vic_on["slo_attainment"], 3),
        "victim_itl_p95_ms_off": (
            round(vic_off["itl_p95_s"] * 1e3, 1)
            if vic_off["itl_p95_s"] is not None else None),
        "victim_itl_p95_ms_on": (
            round(vic_on["itl_p95_s"] * 1e3, 1)
            if vic_on["itl_p95_s"] is not None else None),
        "aggressor_shed_frac": round(
            agg_on["shed"] / max(1, agg_on["offered"]), 3),
        "aggressor_sheds_with_retry_after": agg_on["shed_with_retry_after"],
        # None (renders as JSON null), never NaN: a cycle that timed out
        # would otherwise stamp bare NaN — invalid JSON — into the ladder.
        "scale_up_s": round(up_s, 2) if math.isfinite(up_s) else None,
        "scale_down_s": round(down_s, 2) if math.isfinite(down_s) else None,
        "autoscale_failures": int(
            METRICS.get_counter("autoscale.scale_failures")),
    }


def _measure_fleet_goodput(
    preset: str | None = None, dtype: str = "bfloat16",
    replicas: int = 4, horizon_s: float = 12.0, new_tokens: int = 16,
    page_size: int = 16,
) -> dict:
    """Fleet control plane at 4+ replicas (runtime/router.py +
    cluster/fleet.py): ONE deterministic two-tenant trace from the
    runtime/workload.py harness (MMPP arrivals, seed-pinned prompts)
    replayed open-loop against a COLOCATED 4-replica fleet and against a
    DISAGGREGATED 2-prefill + 2-decode fleet (verified handoff), goodput
    from workload.summarize + byte-exactness both legs.  Then
    cross-replica KV reuse on the colocated fleet: one replica's prompts
    are re-requested while it drains — the fleet digest directory steers
    each pull to the sibling that holds the pages (hit rate + pages
    shipped), and the identical re-requests with the pull plane OFF
    re-prefill locally.  Both probes complete ONE token, so their walls
    read as TTFT: the pull-vs-reprefill delta is what the directory buys
    on a prompt whose pages live on a sibling.  Host-scheduling +
    transfer effects, honestly measurable on any platform."""
    import asyncio

    from distributed_llms_tpu.cluster.fleet import ReplicaFleet
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import workload
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.router import ReplicaRouter
    from distributed_llms_tpu.runtime.server import InferenceServer
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    # Byte-vocab tiny model (tenant-qos idiom): the served tokens ARE
    # bytes, so the streamed text is non-vacuous and byte-exactness
    # against the reference is a real check — a word-vocab checkpoint
    # decodes to '' under the byte tokenizer and every comparison
    # trivially passes while goodput reads zero.
    del preset
    cfg = get_preset("llama-tiny", vocab_size=259, max_seq_len=256,
                     dtype=dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    max_len = 12 * page_size
    slots = 2

    def make_batcher():
        # ignore-eos (serving-bench convention): every request emits
        # exactly its max_tokens, so goodput measures fleet scheduling
        # and transfer — not where this checkpoint happens to stop on
        # the trace's synthetic prompts.
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=-1, pad_id=tok.pad_id,
            batch_slots=slots, max_len=max_len, chunk_steps=4,
            paged_pages=2 * slots * (max_len // page_size) + 1,
            page_size=page_size, prefix_cache=True,
        )

    def make_server(role="colocated"):
        def factory():
            # 4 full engines share one host: generous watchdog and
            # transfer deadlines keep scheduling contention from reading
            # as replica death (failover is replica-failover's row).
            return InferenceServer(
                make_batcher(), model_name="bench", host="127.0.0.1",
                port=0, batcher_factory=make_batcher,
                watchdog_timeout_s=30.0, role=role,
                xfer_attempt_s=10.0,
            )

        return factory

    # The deterministic multi-tenant trace of record: two tenants with
    # pinned seeds, prompt sizes that always span >= 2 KV pages and fit
    # the 192-token slots, output pinned so every arrival has exactly
    # one reference text.  Same (specs, horizon, seed) -> same bytes on
    # every platform, which is what makes the two legs comparable.
    specs = [
        workload.TenantSpec(name="gold", rate_rps=0.45, weight=2.0,
                            prompt_len=(64, 96),
                            output_len=(new_tokens, new_tokens)),
        workload.TenantSpec(name="std", rate_rps=0.45,
                            prompt_len=(64, 96),
                            output_len=(new_tokens, new_tokens)),
    ]
    arrivals = workload.generate(specs, horizon_s=horizon_s, seed=0)
    prompts = list(dict.fromkeys(a.prompt for a in arrivals))
    ref = make_batcher()
    rids = [ref.submit(p, max_new_tokens=new_tokens) for p in prompts]
    ref_res = ref.run()
    wants = {p: tok.decode(ref_res[r]) for p, r in zip(prompts, rids)}
    # Warm the CACHE-HIT admission shape too (the path every pulled or
    # re-requested prompt takes): its first compile on a contended host
    # would otherwise read as a wedged engine mid-measurement.
    ref.submit(prompts[0], max_new_tokens=1)
    ref.run()

    async def storm(host, port):
        records = await workload.replay(host, port, arrivals,
                                        request_timeout_s=120.0)
        summary = workload.summarize(records, horizon_s=horizon_s)
        done = [(a.prompt, r) for a, r in zip(arrivals, records)
                if r.status == 200]
        exact = sum(1 for p, r in done if r.text == wants[p])
        goodput = sum(s["goodput_tok_s"] for s in summary.values())
        return len(done), exact, goodput

    async def colocated_leg() -> dict:
        fleet = ReplicaFleet([make_server()] * replicas,
                             probe_interval_s=0.2, probe_timeout_s=8.0,
                             probe_failures=4)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=tok, page_size=page_size)
        await fleet.start()
        host, port = await router.start()
        assert await fleet.wait_healthy(timeout_s=120.0)
        done, exact, goodput = await storm(host, port)

        def holder(p):
            digs = router._digests(tok.encode(p))
            got = router._affinity.get(digs[-1]) if digs else None
            return got[0] if got else None

        by_holder: dict[str, list[str]] = {}
        for p in prompts:
            if holder(p):
                by_holder.setdefault(holder(p), []).append(p)
        # Drain the SINGLE largest holder and split its prompts: half
        # re-requested with the pull plane ON (a draining replica stays
        # reachable, so the directory steers each pull at it), half with
        # the plane OFF (re-prefill on whichever sibling placement
        # picks).  Robust to any placement skew — an uncontended trace
        # can land every prompt on one replica.
        src = max(by_holder, key=lambda n: len(by_holder[n]))
        held = by_holder[src]
        assert len(held) >= 2, f"holder {src} holds {len(held)} prompt(s)"
        half = (len(held) + 1) // 2

        async def reuse(subset, pull_on):
            router.pull = pull_on
            fleet[src].state = "draining"
            walls = []
            cached = 0
            for p in subset:
                t0 = time.perf_counter()
                status, out = await _serving_post(
                    host, port, {"prompt": p, "max_tokens": 1})
                walls.append(time.perf_counter() - t0)
                if status == 200:
                    cached += out["usage"]["prompt_tokens_details"][
                        "cached_tokens"]
            fleet[src].state = "healthy"
            router.pull = True
            return sum(walls) / len(walls) * 1e3, cached

        lk0 = METRICS.get_counter("directory.lookups")
        hit0 = METRICS.get_counter("directory.hits")
        pg0 = METRICS.get_counter("directory.pulled_pages")
        fb0 = METRICS.get_counter("directory.pull_fallbacks")
        pull_ms, pulled_cached = await reuse(held[:half], pull_on=True)
        lookups = METRICS.get_counter("directory.lookups") - lk0
        hits = METRICS.get_counter("directory.hits") - hit0
        reprefill_ms, _ = await reuse(held[half:], pull_on=False)
        assert pulled_cached > 0, "no pull ever served cached tokens"
        await router.stop()
        await fleet.stop()
        return {
            "completed": done,
            "exact": exact,
            "goodput_tok_per_s_colocated": round(goodput, 1),
            "directory_hit_rate": round(hits / max(1, lookups), 3),
            "pulled_pages": int(
                METRICS.get_counter("directory.pulled_pages") - pg0),
            "pull_fallbacks": int(
                METRICS.get_counter("directory.pull_fallbacks") - fb0),
            "pull_ttft_ms": round(pull_ms, 1),
            "reprefill_ttft_ms": round(reprefill_ms, 1),
            "pull_ttft_speedup": round(reprefill_ms / max(1e-9, pull_ms), 2),
        }

    async def disagg_leg() -> dict:
        n_pre = replicas // 2
        factories = [make_server("prefill")] * n_pre \
            + [make_server("decode")] * (replicas - n_pre)
        names = [f"p{i}" for i in range(n_pre)] \
            + [f"d{i}" for i in range(replicas - n_pre)]
        fleet = ReplicaFleet(factories, names=names, probe_interval_s=0.2,
                             probe_timeout_s=8.0, probe_failures=4)
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               tokenizer=tok, page_size=page_size,
                               handoff=True)
        await fleet.start()
        host, port = await router.start()
        assert await fleet.wait_healthy(timeout_s=120.0)
        h0 = METRICS.get_counter("router.handoffs")
        done, exact, goodput = await storm(host, port)
        await router.stop()
        await fleet.stop()
        return {
            "completed_disagg": done,
            "exact_disagg": exact,
            "goodput_tok_per_s_disagg": round(goodput, 1),
            "handoffs": int(METRICS.get_counter("router.handoffs") - h0),
        }

    out = {"replicas": replicas, "requests": len(arrivals),
           "tenants": len(specs), "horizon_s": horizon_s,
           "new_tokens": new_tokens}
    out.update(asyncio.run(colocated_leg()))
    out.update(asyncio.run(disagg_leg()))
    out.update({"preset": "llama-tiny(byte-vocab)",
                "platform": jax.devices()[0].platform})
    return out


def _measure_kv_tiering(
    preset: str | None = None, dtype: str = "bfloat16", page_size: int = 16,
) -> dict:
    """KV memory tiering (PR 9), three numbers on any platform:

    (a) **capacity factor** — concurrent rows admitted at FIXED pool
        bytes, int8 pages vs bf16 pages (the pool is the binding resource
        for concurrency; >= 1.8x is the acceptance floor at head_dim 64);
    (b) **swap-restore vs recompute** — wall time to bring a preempted
        >= 4-page-prefix victim back to decoding, host-tier raw-page
        restore vs exact prefix recompute;
    (c) **spill-hit TTFT** — time to the first token of a shared-prefix
        request whose cached run was LRU-evicted, host-tier restore vs
        cold re-prefill.
    """
    import statistics

    from distributed_llms_tpu.runtime.batcher import (ContinuousBatcher,
                                                      pool_page_bytes)
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = preset or ("gpt2-125m" if jax.devices()[0].platform == "cpu"
                        else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    blk = page_size
    max_len = 8 * blk

    def mk(pages, **kw):
        kw.setdefault("batch_slots", 16)
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            max_len=max_len, chunk_steps=4, page_size=blk,
            paged_pages=pages, **kw,
        )

    # (a) capacity at fixed pool bytes: every request reserves exactly
    # prompt (1 page) + 1 decode page; count rows resident after one
    # admission round.  The full-width leg is pinned to bf16 pages
    # (kv_dtype knob) so the factor means the same thing on every
    # platform — a CPU f32 compute dtype must not inflate it.
    bytes16 = pool_page_bytes(cfg, blk, 16, "bfloat16")
    bytes8 = pool_page_bytes(cfg, blk, 8)
    pages16 = 13  # 12 usable
    budget_bytes = pages16 * bytes16
    pages8 = budget_bytes // bytes8
    prompt_ids = list(range(2, 2 + blk))  # exactly one full page

    def concurrent_rows(bits, pages):
        b = mk(int(pages), kv_bits=bits, kv_dtype="bfloat16",
               batch_slots=32)
        for _ in range(32):
            b.submit(prompt_ids, max_new_tokens=2 * blk)
        b._admit_pending()
        rows = sum(1 for r in b.rows if r.rid is not None)
        b.assert_pool_consistent()
        return rows

    rows16 = concurrent_rows(16, pages16)
    rows8 = concurrent_rows(8, pages8)
    capacity_factor = rows8 / max(rows16, 1)

    # (b) swap-restore vs recompute for a >= 4-page-prefix victim.
    victim_prompt = list(range(2, 2 + 4 * blk))  # 4 full pages

    def restore_ms(host_pages):
        b = mk(13, batch_slots=2, host_pages=host_pages)
        times = []
        b.submit(victim_prompt, max_new_tokens=8)
        b._admit_pending()  # warm the admission path
        for it in range(4):
            i = next(j for j in range(b.b) if b.rows[j].rid is not None)
            t0 = time.perf_counter()
            b._preempt_row(i, "bench")
            b._admit_pending()  # swap restore OR recompute prefill
            times.append((time.perf_counter() - t0) * 1e3)
        b.run()
        b.assert_pool_consistent()
        return statistics.median(times[1:])  # drop the compile-warm lap

    swap_ms = restore_ms(host_pages=16)
    recompute_ms = restore_ms(host_pages=0)

    # (c) spill-hit TTFT vs cold re-prefill after eviction.
    shared = list(range(2, 2 + 3 * blk)) + [7, 8, 9]

    def ttft_after_eviction_ms(host_pages):
        b = mk(13, batch_slots=2, prefix_cache=True, host_pages=host_pages)
        b.submit(shared, max_new_tokens=4)
        b.run()  # warm + publish the shared pages

        def evict_then_hit():
            for i in range(3):  # evict the shared run
                b.submit([90 + i] * (3 * blk) + [i], max_new_tokens=4)
            b.run()
            first = []
            rid = b.submit(shared, max_new_tokens=4)
            t0 = time.perf_counter()
            b.run(on_tokens=lambda r, t, d, l: first.append(
                time.perf_counter()) if r == rid and t and not first
                else None)
            return (first[0] - t0) * 1e3

        evict_then_hit()  # compile-warm lap (restore + hit-admission jits)
        out = evict_then_hit()
        b.assert_pool_consistent()
        return out

    spill_ttft_ms = ttft_after_eviction_ms(host_pages=32)
    cold_ttft_ms = ttft_after_eviction_ms(host_pages=0)

    return {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "page_size": blk,
        "pool_bytes_mb": round(budget_bytes / 2**20, 2),
        "rows_bf16": rows16,
        "rows_int8": rows8,
        "capacity_factor_int8": round(capacity_factor, 2),
        "swap_restore_ms": round(swap_ms, 1),
        "recompute_restore_ms": round(recompute_ms, 1),
        "swap_speedup": round(recompute_ms / max(swap_ms, 1e-9), 2),
        "spill_hit_ttft_ms": round(spill_ttft_ms, 1),
        "cold_ttft_ms": round(cold_ttft_ms, 1),
    }


def _measure_decode_overlap(dtype: str = "bfloat16") -> dict:
    """Dispatch-ahead engine loop (PR 10): the same steady decode traffic
    served with the engine loop fully synchronous (overlap off — one
    blocking host round-trip per chunk) vs dispatch-ahead (overlap on —
    chunk N+1 dispatched from the device-resident carry while chunk N's
    host work runs).  Stamps per-chunk DEVICE GAP (host time between a
    chunk completing and the next chunk dispatching; 0 by construction
    for dispatched-ahead chunks) and steady decode throughput.  Prefix
    cache + streaming callbacks are ON so the overlapped host window does
    the real per-chunk work (digest hashing, delivery)."""
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = ("gpt2-125m" if jax.devices()[0].platform == "cpu"
              else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    blk = 16
    n_new = 64
    prompts = [f"request {i}: " + "x" * (8 + 3 * i) for i in range(4)]

    def leg(overlap: bool) -> dict:
        b = ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=4, max_len=128, chunk_steps=4, page_size=blk,
            paged_pages=40, prefix_cache=True, overlap=overlap,
        )

        def lap() -> tuple[float, int]:
            got = [0]
            for p in prompts:
                b.submit(p, max_new_tokens=n_new)
            t0 = time.perf_counter()
            b.run(on_tokens=lambda rid, new, done, lps: got.__setitem__(
                0, got[0] + len(new)))
            return time.perf_counter() - t0, got[0]

        lap()  # compile-warm lap
        s0 = dict(b.overlap_stats)
        best = None
        for _ in range(2):
            wall, toks = lap()
            if best is None or wall < best[0]:
                best = (wall, toks)
        s1 = b.overlap_stats
        b.assert_pool_consistent()
        gaps = s1["gap_samples"] - s0["gap_samples"]
        gap_ms = ((s1["device_gap_s"] - s0["device_gap_s"])
                  / max(gaps, 1) * 1e3)
        chunks = s1["chunks"] - s0["chunks"]
        return {
            "tok_per_s": best[1] / best[0],  # best-of-2 lap
            "gap_ms": gap_ms,
            "dispatched_ahead_frac": (
                (s1["dispatched_ahead"] - s0["dispatched_ahead"])
                / max(chunks, 1)
            ),
        }

    off = leg(False)
    on = leg(True)
    return {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "chunk_steps": 4,
        "tok_per_s_overlap_off": round(off["tok_per_s"], 1),
        "tok_per_s_overlap_on": round(on["tok_per_s"], 1),
        "device_gap_ms_off": round(off["gap_ms"], 3),
        "device_gap_ms_on": round(on["gap_ms"], 3),
        # Gap with overlap on is ~0 by construction; floor the divisor at
        # 1 µs so the stamped ratio stays finite and honest.
        "gap_reduction": round(off["gap_ms"] / max(on["gap_ms"], 1e-3), 1),
        "dispatched_ahead_frac": round(on["dispatched_ahead_frac"], 2),
    }


def _measure_mixed_step(dtype: str = "bfloat16") -> dict:
    """Stall-free mixed batching (runtime/scheduler.py + mixed_step):
    resident decode rows' inter-token latency WHILE long prompts chunk-
    prefill, schedule=alternate (each pending prefill advances as its own
    serialized prefill_chunk_step forward per round — up to
    prefill_concurrency x prefill_chunk tokens stall the decode batch
    per round, and the pending prefill parks the dispatch-ahead span)
    vs schedule=mixed at EQUAL token budget (token_budget =
    prefill_chunk: each fused step runs every decode leg plus one
    budget-bounded bite of the HEAD prefill in the same compiled
    program — the mixed policy ENFORCES the budget the alternating loop
    over-spends 2x when two prefills pend, which is the Sarathi-Serve
    point).  Stamps ITL p50/p95 of the resident rows inside the
    interference window (long-prompt arrival -> the first one's first
    token, identically delimited for both legs), TTFT of both long
    prompts, and the stall-bite counts.  A host-scheduling effect,
    meaningful on any platform."""
    from distributed_llms_tpu.core.observability import METRICS
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    preset = ("gpt2-125m" if jax.devices()[0].platform == "cpu"
              else "tinyllama-1.1b")
    cfg, params = _build_params(preset, dtype, None)
    tok = ByteTokenizer()
    # Budget on a bucket boundary: the fused prefill leg pads to ONE
    # policy bucket, so a 128-token budget means a 128-wide leg — no
    # padded waste riding every chunk.  THREE long prompts admit
    # together (prefill_concurrency=3, stamped): the alternating loop
    # then serializes 3 x 128 prefill tokens against every decode round
    # — the unbudgeted over-spend the mixed policy bounds to ONE bite.
    chunk = 128
    n_res, n_long = 3, 3
    residents = [f"resident row {i}: " + "y" * (10 + 3 * i)
                 for i in range(n_res)]
    longs = [f"long prompt {c} " + c * 880 for c in "abc"[:n_long]]

    def leg(schedule: str) -> dict:
        b = ContinuousBatcher(
            cfg, params, tokenizer=tok, eos_id=tok.eos_id, pad_id=tok.pad_id,
            batch_slots=n_res + n_long, max_len=1024, chunk_steps=2,
            prefill_chunk=chunk, prefill_concurrency=n_long,
            schedule=schedule,
            token_budget=(chunk if schedule == "mixed" else None),
        )

        def lap() -> dict:
            stalls0 = METRICS.get_counter("batcher.sched.stall_rounds")
            state: dict = {"t_sub": None, "first": {}, "gaps": [],
                           "last": {}, "long_rids": [], "cancelled": False}
            res_rids = [b.submit(p, max_new_tokens=400) for p in residents]

            def cb(rid, new, done, lps):
                t = time.perf_counter()
                if state["t_sub"] is None and rid == res_rids[0] \
                        and len(b.rows[0].emitted) >= 8:
                    # Steady decode reached: the long prompts arrive NOW.
                    state["t_sub"] = t
                    state["long_rids"] = [
                        b.submit(p, max_new_tokens=4) for p in longs
                    ]
                if new and rid not in state["first"]:
                    state["first"][rid] = t
                lr = state["long_rids"]
                if new and rid in res_rids and state["t_sub"] is not None \
                        and lr and lr[0] not in state["first"]:
                    # ITL samples INSIDE the interference window: from the
                    # long prompts' arrival until the FIRST one's first
                    # token — the rounds where its prefill contends with
                    # the resident rows' decode, identically delimited
                    # for both schedules.  The first two deliveries after
                    # arrival are the admission TRANSITION (carry sync +
                    # transient-row setup, identical mechanics in both
                    # legs, sized by batch state rather than by the
                    # schedule) — the window starts once the prefill is
                    # actually in flight (the transition spans the carry
                    # sync's flushed delivery, the restart, and the first
                    # post-restart fetch: three deliveries).
                    state.setdefault("skip", {})
                    n_seen = state["skip"].get(rid, 0)
                    state["skip"][rid] = n_seen + 1
                    prev = state["last"].get(rid)
                    if prev is not None and n_seen >= 3:
                        state["gaps"].append((t - prev) / len(new))
                state["last"][rid] = t
                if not state["cancelled"] and lr \
                        and all(r in state["first"] for r in lr):
                    # Every long prompt delivered: the measurement is
                    # over — cancel ALL residents (cancel_row is
                    # documented safe from on_tokens, the current rid
                    # included) so the lap ends instead of decoding
                    # hundreds of unmeasured tokens.
                    state["cancelled"] = True
                    for r in res_rids:
                        b.cancel_row(r)

            b.run(on_tokens=cb)
            return {
                "itl": state["gaps"],
                "ttft": [state["first"][r] - state["t_sub"]
                         for r in state["long_rids"]],
                "stalls": METRICS.get_counter("batcher.sched.stall_rounds")
                - stalls0,
            }

        lap()  # compile-warm lap (all buckets + the fused program)
        laps = [lap(), lap()]  # min-of-2: transient host noise out

        def pct(m, q):
            # A fast platform can finish the long prompt's prefill before
            # any resident delivery lands past the transition — stamp 0
            # (with itl_samples saying so) instead of crashing the row.
            itl = sorted(m["itl"])
            if not itl:
                return 0.0
            return itl[min(len(itl) - 1, int(q * len(itl)))]

        # Pick the best lap among those that actually CAPTURED samples —
        # an empty lap's 0.0 p95 must never beat a measured one.
        measured = [m for m in laps if m["itl"]] or laps
        best = min(measured, key=lambda m: pct(m, 0.95))
        return {
            "itl_p95_ms": pct(best, 0.95) * 1e3,
            "itl_p50_ms": pct(best, 0.50) * 1e3,
            "itl_samples": len(best["itl"]),
            "ttft_first_s": best["ttft"][0],
            "ttft_last_s": best["ttft"][-1],
            "stall_rounds": best["stalls"],  # the stamped lap's own count
        }

    alt = leg("alternate")
    mix = leg("mixed")
    return {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "prefill_chunk": chunk,
        "token_budget": chunk,
        "prefill_concurrency": n_long,
        "itl_window": "long-prompt arrival -> first token of the first "
                      "long prompt; admission-transition deliveries "
                      "excluded (identical mechanics both legs)",
        "itl_samples": alt["itl_samples"] + mix["itl_samples"],
        "itl_p95_ms_alternate": round(alt["itl_p95_ms"], 2),
        "itl_p95_ms_mixed": round(mix["itl_p95_ms"], 2),
        # Gain only when both legs measured (0.0 = window empty: honest
        # "no sample", never an absurd divide-by-epsilon ratio).
        "itl_p95_gain": (
            round(alt["itl_p95_ms"] / mix["itl_p95_ms"], 2)
            if alt["itl_p95_ms"] > 0 and mix["itl_p95_ms"] > 0 else 0.0),
        "itl_p50_ms_alternate": round(alt["itl_p50_ms"], 2),
        "itl_p50_ms_mixed": round(mix["itl_p50_ms"], 2),
        "ttft_first_s_alternate": round(alt["ttft_first_s"], 3),
        "ttft_first_s_mixed": round(mix["ttft_first_s"], 3),
        # TTFT acceptance ratio (the tracked long prompt): <= 1.10 passes.
        "ttft_ratio": round(
            mix["ttft_first_s"] / max(alt["ttft_first_s"], 1e-9), 3),
        # The budget trade, stamped honestly: mixed serializes pending
        # prefills (head first), so the LAST long prompt finishes its
        # prefill later than under the alternating loop's concurrent
        # over-spend — bounded per-step work is the product here.
        "ttft_last_s_alternate": round(alt["ttft_last_s"], 3),
        "ttft_last_s_mixed": round(mix["ttft_last_s"], 3),
        "stall_rounds_alternate": int(alt["stall_rounds"]),
        "stall_rounds_mixed": int(mix["stall_rounds"]),
    }


def _measure_constrained_decode(dtype: str = "float32",
                                completions: int = 16) -> dict:
    """Grammar-constrained structured output (runtime/constrain.py):
    (a) token-mask automaton compile wall for a realistic tool-call JSON
    schema, (b) constrained vs free steady decode tok/s on the same
    engine under identical budgets — the traced mask-gather + DFA-advance
    overhead inside the shared decode step — and (c) the parse-valid
    fraction over >= ``completions`` constrained completions, half greedy
    and half sampled (every output must json.loads AND validate against
    the schema).  Sampling/host-scheduling effects: meaningful on any
    platform."""
    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import constrain as constrain_lib
    from distributed_llms_tpu.runtime.batcher import ContinuousBatcher
    from distributed_llms_tpu.runtime.tokenizer import ByteTokenizer

    cfg = get_preset("llama-tiny", vocab_size=512, dtype=dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    schema = {  # the agent/tool-calling shape the feature exists for
        "type": "object",
        "properties": {
            "name": {"enum": ["get_weather", "get_stock", "get_time"]},
            "arguments": {
                "type": "object",
                "properties": {
                    "location": {"type": "string", "maxLength": 12},
                    "unit": {"enum": ["celsius", "fahrenheit"]},
                    "days": {"type": "integer", "minimum": 0},
                },
                "required": ["location", "unit", "days"],
            },
        },
        "required": ["name", "arguments"],
    }
    rf_schema = {"type": "json_schema", "json_schema": {"schema": schema}}
    constrain_lib.clear_cache()  # measure a real compile, not a hit
    t0 = time.perf_counter()
    constrain_lib.compile_request(
        rf_schema, tokenizer=tok, vocab_size=cfg.vocab_size,
        eos_id=tok.eos_id,
    )
    compile_ms = (time.perf_counter() - t0) * 1e3

    def make():
        return ContinuousBatcher(
            cfg, params, tokenizer=tok, batch_slots=4, max_len=128,
            chunk_steps=8, eos_id=tok.eos_id, pad_id=tok.pad_id,
        )

    # Steady throughput: a non-terminating bounded-run mask keeps the
    # constrained leg emitting its FULL budget, so both legs decode the
    # same token count and the delta is pure mask overhead.
    n_new, reqs = 96, 8
    long_rx = {"type": "regex", "regex": "[a-z0-9 ]{1,120}"}

    def run_leg(constrained: bool) -> float:
        best = 0.0
        for _ in range(2):  # min-of-2, warm compile inside the first
            b = make()
            for i in range(reqs):
                b.submit(
                    [32 + i, 40 + i, 50 + i], max_new_tokens=n_new,
                    **({"response_format": long_rx} if constrained else {}),
                )
            t0 = time.perf_counter()
            res = b.run()
            dt = time.perf_counter() - t0
            toks = sum(len(v) for v in res.values())
            best = max(best, toks / dt)
        return best

    tps_free = run_leg(False)
    tps_con = run_leg(True)

    b = make()
    rids = []
    for i in range(completions):
        rids.append(b.submit(
            [60 + i, 61, 62], max_new_tokens=120,
            temperature=(0.0 if i % 2 == 0 else 0.9),
            response_format=rf_schema,
        ))
    res = b.run()
    valid = 0
    for r in rids:
        try:
            obj = json.loads(tok.decode(res[r]))
        except ValueError:
            continue
        valid += bool(constrain_lib.validates(schema, obj))
    return {
        "preset": "llama-tiny",
        "platform": jax.devices()[0].platform,
        "dfa_compile_ms": round(compile_ms, 1),
        "tok_per_s_free": round(tps_free, 1),
        "tok_per_s_constrained": round(tps_con, 1),
        "mask_overhead_pct": round((tps_free / tps_con - 1.0) * 100, 1),
        "parse_valid_frac": round(valid / completions, 3),
        "completions": completions,
    }


def _measure_mesh_paged_impl(dtype: str = "float32") -> dict:
    """Mesh-native paged serving (PR 11): the paged pool sharded over the
    mesh 'model' axis on KV heads.  Two claims stamped, both on the
    forced-device CPU mesh (honest degraded provenance — real chips
    re-stamp): (a) CAPACITY — at a fixed PER-CHIP pool byte budget, a tp2
    engine holds ~2x the concurrently-resident rows of tp1, because each
    chip stores only its head slice of every page; (b) EXACTNESS+SPEED —
    the same storm serves byte-identical tokens at tp1 and tp2, with
    steady decode tok/s recorded for both (on the fake CPU mesh tp2 pays
    jit-dispatch overhead per virtual device; the throughput win needs
    real chips, which is exactly what the degraded stamp says)."""
    import numpy as np

    from distributed_llms_tpu.core.config import MeshConfig
    from distributed_llms_tpu.models import model as model_lib, presets
    from distributed_llms_tpu.parallel.api import make_parallel_model
    from distributed_llms_tpu.runtime.batcher import (ContinuousBatcher,
                                                      pool_page_bytes)

    devices = jax.devices()
    assert len(devices) >= 2, "mesh-paged needs >= 2 devices"
    platform = devices[0].platform
    cfg = presets.get_preset("gpt2-tiny", vocab_size=512, dtype=dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    blk, max_len = 16, 96
    # Per-chip budget = 13 pages' bytes at tp1.  tp1 pool: 13 pages.
    # tp2: each chip holds half of every page, so the SAME per-chip bytes
    # fund 26 global pages.
    budget_pages = 13
    per_chip_bytes = budget_pages * pool_page_bytes(cfg, blk, 16, dtype)

    def mk(tp: int) -> ContinuousBatcher:
        pages = budget_pages * tp
        # Slots must never be the binding constraint — the pool is the
        # subject: 16 slots >> what either pool can hold resident.
        kw = dict(batch_slots=16, max_len=max_len, chunk_steps=4,
                  page_size=blk, paged_pages=pages, prefix_cache=True)
        if tp == 1:
            return ContinuousBatcher(cfg, params, **kw)
        pm = make_parallel_model(cfg, MeshConfig(model=tp),
                                 devices=devices[:tp])
        return ContinuousBatcher(cfg, pm.shard_params(params), parallel=pm,
                                 **kw)

    # (a) capacity: a storm of 2-page rows; peak concurrently-ACTIVE rows
    # is what the pool actually held at once (growth + back-pressure keep
    # it honest — nothing overcommits).
    storm = [([7 + i, 1, 9, 2 + i] * 4, 24) for i in range(16)]

    def drive(b) -> tuple[dict, int, float, int]:
        peak = [0]

        def cb(rid, new, done, lps):
            peak[0] = max(peak[0], int(np.sum(b.active)))

        rids = [b.submit(ids, max_new_tokens=n) for ids, n in storm]
        t0 = time.perf_counter()
        res = b.run(on_tokens=cb)
        wall = time.perf_counter() - t0
        b.assert_pool_consistent()
        toks = sum(len(res[r]) for r in rids)
        return {r: res[r] for r in rids}, peak[0], wall, toks

    b1 = mk(1)
    drive(b1)  # compile-warm lap
    res1, rows1, wall1, toks1 = drive(b1)
    b2 = mk(2)
    assert not b2.cache.k.sharding.is_fully_replicated
    drive(b2)  # compile-warm lap
    res2, rows2, wall2, toks2 = drive(b2)
    exact = sum(a == b for a, b in zip(res1.values(), res2.values()))
    out = {
        "preset": "gpt2-tiny",
        # Honest provenance: a real multi-chip platform stamps itself; the
        # virtual CPU mesh carries the degraded marker.
        "platform": (f"{platform} (fake mesh)" if platform == "cpu"
                     else platform),
    }
    if platform == "cpu":
        out["degraded"] = ("cpu fake-mesh (virtual devices, jit dispatch "
                           "included) — capacity factor is real "
                           "accounting; tok/s needs a TPU re-stamp")
    out.update({
        "page_size": blk,
        "per_chip_pool_kb": round(per_chip_bytes / 1024, 1),
        "rows_per_chip_tp1": rows1,
        "rows_per_chip_tp2": rows2,
        "capacity_factor_tp2": round(rows2 / max(rows1, 1), 2),
        "tok_per_s_tp1": round(toks1 / wall1, 1),
        "tok_per_s_tp2": round(toks2 / wall2, 1),
        "exact": exact,
        "completed": len(storm),
    })
    return out


def _measure_mesh_paged(dtype: str = "float32") -> dict:
    """Run the mesh-paged measurement over a 2-device mesh: inline when
    this process already sees >= 2 devices of ANY platform (a real
    multi-chip TPU host re-stamps the row natively — that is the
    promised TPU re-stamp path), else in a fresh subprocess with a
    forced 2-device virtual CPU platform (the hop-latency fallback
    pattern — xla_force_host_platform_device_count is frozen once the
    parent's backend initialized).  Self-stamps the platform the number
    actually ran on, never the parent's."""
    import datetime

    date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d")
    if len(jax.devices()) >= 2:
        row = _measure_mesh_paged_impl(dtype=dtype)
        return {**row, "measured_on": f"{date} {row['platform']}"}
    out, r = _fake_mesh_subprocess(
        f"_measure_mesh_paged_impl(dtype={dtype!r})", "MESHPAGED",
        n_devices=2, timeout=1200,
    )
    if out is not None:
        return out
    detail = "<subprocess timed out>" if r is None else (
        r.stderr.strip().splitlines() or ["<no output>"])[-1]
    rc = "?" if r is None else r.returncode
    raise RuntimeError(
        f"mesh-paged subprocess produced no row (rc {rc}): {detail[:200]}"
    )


def _measure_compile_stability() -> dict:
    """Compile-key stability of the serving entry points
    (tools/graftcheck GC4, run as a MEASUREMENT): sweep the request-length
    ladder through the real width policies, trace the real jitted
    admission / decode / generate programs, and stamp how many distinct
    compile-cache keys each produces against its declared bucket budget.
    Pure tracing (jax.make_jaxpr) — zero FLOPs, identical on every
    platform — so a recompile regression shows up in the perf trajectory
    (this row) AND fails the gate (test_graftcheck)."""
    from tools.graftcheck.contracts import recompile_scenarios
    from tools.graftcheck.recompile import measure_keys

    out: dict = {"preset": "llama-tiny", "platform": jax.devices()[0].platform}
    t0 = time.perf_counter()
    for sc in recompile_scenarios():
        keys = measure_keys(sc)
        tag = sc.name.rsplit(".", 1)[-1]
        out[f"{tag}_keys"] = len(keys)
        out[f"{tag}_declared"] = sc.max_keys
        if len(keys) > sc.max_keys:  # the gate fails too; stamp it honestly
            out["regressed"] = True
    out["trace_wall_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    return out


def _measure_analysis_wall() -> dict:
    """Wall time of the full tier-1 static-analysis gate (graftlint AST +
    graftcheck abstract tracing + graftflow CFG/dataflow + graftsync
    lockstep taint + graftmodel protocol model checking), each run as a
    fresh subprocess the way the pytest gates pay for it.  The gate's
    cost must stay visible in BASELINE.md: every PR adds rules, and a
    multi-minute gate is a gate people stop running.  Each tool must
    exit 0 — a dirty tree makes the timing meaningless and fails loudly
    here instead of stamping a lie."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out: dict = {"platform": jax.devices()[0].platform}
    total = 0.0
    for tool in ("graftlint", "graftcheck", "graftflow", "graftsync",
                 "graftmodel"):
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", f"tools.{tool}", "--root", repo],
            capture_output=True, text=True, cwd=repo, env=env,
        )
        wall = time.perf_counter() - t0
        if r.returncode != 0:
            # Dirty tree OR tool crash — either way the timing would be a
            # lie; surface whichever stream actually says why.
            detail = (r.stdout.strip().splitlines()
                      or r.stderr.strip().splitlines() or ["<no output>"])
            raise RuntimeError(
                f"{tool} exited {r.returncode} (findings, usage error, or "
                f"crash): {detail[0][:200]}"
            )
        out[f"{tool}_wall_ms"] = round(wall * 1e3, 1)
        total += wall
    out["analysis_wall_ms"] = round(total * 1e3, 1)
    return out


def _measure_prefill_flash(
    preset: str = "tinyllama-1.1b", batch: int = 2, seq: int = 2048,
    dtype: str = "bfloat16", iters: int = 5, window: int | None = None,
) -> dict:
    """Prefill (full-forward) throughput, dot vs Pallas flash attention, on
    the real device — puts ops/flash.py on the record (it otherwise runs only
    in CPU interpret mode in tests) and checks numerics on-device once.
    ``window``: sliding-window variant (Mistral-style) — the kernel skips
    out-of-window tiles without DMAing them, while the dot path pays the
    full dense masked matmul; the speedup at seq >> window is the row's
    subject.  VERDICT r2 weak item 4 / round-1 weak item 7."""
    import dataclasses

    import numpy as np

    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset

    cfg_dot = get_preset(preset, dtype=dtype)
    cfg_dot = dataclasses.replace(cfg_dot, attn_impl="dot",
                                  sliding_window=window)
    cfg_flash = dataclasses.replace(cfg_dot, attn_impl="flash")
    params = model_lib.init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq), 0, cfg_dot.vocab_size, dtype=jnp.int32
    )

    def timed(cfg) -> tuple[float, jax.Array]:
        fwd = jax.jit(lambda p, t: model_lib.forward(p, cfg, t)[0])
        out = np.asarray(fwd(params, tokens))  # compile + numerics capture
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(fwd(params, tokens))
            times.append(time.perf_counter() - t0)
        return min(times), out

    t_dot, out_dot = timed(cfg_dot)
    t_flash, out_flash = timed(cfg_flash)
    # Last-position logits are what generation consumes; bf16 tolerance.
    err = float(
        jnp.max(jnp.abs(out_flash[:, -1].astype(jnp.float32)
                        - out_dot[:, -1].astype(jnp.float32)))
    )
    return {
        "preset": preset, "batch": batch, "seq": seq,
        **({"window": window} if window is not None else {}),
        "platform": jax.devices()[0].platform,
        "prefill_tok_per_s_dot": round(batch * seq / t_dot, 1),
        "prefill_tok_per_s_flash": round(batch * seq / t_flash, 1),
        "flash_speedup": round(t_dot / t_flash, 3),
        "max_logit_err_vs_dot": round(err, 4),
    }


def _measure_hop_latency(d_model: int = 4096, batch: int = 8, iters: int = 50) -> dict | None:
    """p50/p95 latency of one pipeline-stage activation hop: a ppermute
    rotation of a [batch, d_model] bf16 activation over all visible devices
    (SURVEY §6's 'p50 inter-stage hop latency' metric).  None on 1 device."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return None
    mesh = Mesh(np.array(devs), ("pipe",))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, "pipe", perm)

    try:
        shard_map = jax.shard_map  # jax >= 0.5
    except AttributeError:  # 0.4.x keeps it in experimental
        from jax.experimental.shard_map import shard_map
    f = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("pipe"), out_specs=P("pipe"))
    )
    dtype = jnp.float32 if devs[0].platform == "cpu" else jnp.bfloat16
    x = jax.device_put(
        jnp.zeros((n, batch, d_model), dtype),
        jax.sharding.NamedSharding(mesh, P("pipe")),
    )
    jax.block_until_ready(f(x))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        times.append(time.perf_counter() - t0)
    # Interpolated percentiles — a positional index at 0.95 is the sample
    # max at small --iters (same defect class as the serving-latency p95).
    p50, p95 = np.percentile(np.asarray(times), [50.0, 95.0])
    return {
        "hop_bytes": batch * d_model * jnp.dtype(dtype).itemsize,
        "n_devices": n,
        "p50_us": round(float(p50) * 1e6, 1),
        "p95_us": round(float(p95) * 1e6, 1),
        "note": "jit dispatch included; one full ring rotation per sample",
    }


def _fake_mesh_subprocess(
    call: str, marker: str, n_devices: int, timeout: int = 600,
) -> "tuple[dict | None, subprocess.CompletedProcess | None]":
    """Run ``bench.<call>`` over an n-device VIRTUAL CPU mesh in a fresh
    subprocess (XLA parses xla_force_host_platform_device_count once per
    process, so the already-initialized parent can't grow devices) and
    parse the ``MARKER=<json>`` line it prints.  The one forced-CPU-mesh
    harness both self-stamping fallback rows (hop-latency, mesh-paged)
    share — marker parsing, flag handling, and provenance policy live
    here ONCE.  Returns (parsed row or None, CompletedProcess or None);
    a parsed row carries the self-stamped 'cpu (fake mesh)'
    provenance."""
    code = (
        "import os, json\n"
        "os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +"
        f" ' --xla_force_host_platform_device_count={n_devices}')\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import bench\n"
        f"print({marker + '='!r} + json.dumps(bench.{call}))\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, None
    prefix = marker + "="
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith(prefix):
            try:
                out = json.loads(line[len(prefix):])
            except json.JSONDecodeError:
                return None, r
            if out is not None:
                import datetime

                date = datetime.datetime.now(
                    datetime.timezone.utc
                ).strftime("%Y-%m-%d")
                out["platform"] = "cpu (fake mesh)"
                # Self-stamp: the parent's _stamp() reports the PARENT's
                # platform, which may be a real chip this number never ran on.
                out["measured_on"] = f"{date} cpu (fake mesh)"
            return out, r
    return None, r


def _measure_hop_latency_cpu_fallback(n_devices: int = 4) -> dict | None:
    """_measure_hop_latency over the forced virtual CPU mesh: an upper
    bound on a real interconnect hop — jit dispatch included — but a
    recorded number beats prose quoting an artifact-less one."""
    out, _ = _fake_mesh_subprocess(
        "_measure_hop_latency()", "HOP", n_devices
    )
    return out


def _stamp() -> str:
    """Per-row measurement provenance: UTC date + platform.  VERDICT r3 weak
    #2: a ladder row must say when/where it was measured so instrumented-but-
    never-run configs can't read as results."""
    import datetime

    date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d")
    return f"{date} {jax.devices()[0].platform}"


def _write_rows(path: str, rows: list[dict]) -> None:
    # Atomic (tmp + rename): emit() runs after every ladder row, and a
    # crash mid-json.dump must never leave the artifact of record truncated
    # — the merge logic would later read the wreck as "no prior rows".
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump({"rows": rows}, f, indent=1)
    os.replace(tmp, path)


def _measure_quant_matmul_bw(
    batch: int = 4, d: int = 4096, f: int = 11008, inner: int = 16,
    iters: int = 5,
) -> dict:
    """Isolated fused dequant-matmul bandwidth at north-star decode shapes.

    The serving-path 3-int8 row measures the whole stack; this row times
    ONLY the weight-streaming matmuls, distinguishing "the kernel is slow"
    from "the stack around it is slow".  Three paths in the identical
    harness: the Pallas kernel, the XLA dequant+einsum fallback it
    replaces, and a dense bf16 matmul (the HBM-bandwidth roofline).  The
    harness scans an MLP up/down projection pair over ``inner`` stacked
    per-layer weights — exactly the serving loop's structure, which also
    stops XLA hoisting a loop-invariant dequantize out of the measurement
    (a chained-loop-over-one-weight harness would let it).  A per-call
    measurement would be useless here: ~80 ms tunnel dispatch vs ~56 us of
    compute; scan amortizes dispatch over 2*inner matmuls."""
    from distributed_llms_tpu.checkpoint.quantize import (
        QuantizedTensor, dequantize, quantize,
    )
    from distributed_llms_tpu.ops.quant_matmul import quant_contract

    _PARAMS_CACHE.clear()  # headroom: stacked bf16 weights are ~3 GB
    key = jax.random.key(7)
    kx, ku, kd = jax.random.split(key, 3)
    x0 = jax.random.normal(kx, (batch, d), jnp.bfloat16)

    def gen(base, i, shape, fan_in):
        k = jax.random.fold_in(base, i)
        return jax.random.normal(k, shape, jnp.float32) * fan_in**-0.5

    def stacked_quant(bits):
        qs = []
        for base, shape, fan in ((ku, (d, f), d), (kd, (f, d), f)):
            per = [quantize(gen(base, i, shape, fan), bits=bits)
                   for i in range(inner)]
            qs.append(QuantizedTensor(
                data=jnp.stack([q.data for q in per]),
                scale=jnp.stack([q.scale for q in per]),
                bits=bits, orig_shape=(inner, *shape), pack_axis=-2,
            ))
        return tuple(qs)

    def rms(y):
        sq = jnp.mean(jnp.square(y.astype(jnp.float32))) + 1e-6
        return (y.astype(jnp.float32) * jax.lax.rsqrt(sq)).astype(y.dtype)

    def harness(step):
        def body(y, per_layer):
            return rms(step(y, per_layer)), None

        return jax.jit(lambda y, ws: jax.lax.scan(body, y, ws)[0])

    def qt_bytes(qts):
        return sum(q.data.size + q.scale.size * 4 for q in qts) // inner

    out = {"batch": batch, "d": d, "f": f, "layers_scanned": inner,
           "platform": jax.devices()[0].platform}
    jobs = []
    for bits, tag in ((8, "int8"), (4, "int4")):
        ws = stacked_quant(bits)
        jobs.append((f"kernel_{tag}", harness(
            lambda y, w: quant_contract(quant_contract(y, w[0], k_lead=1),
                                        w[1], k_lead=1)), ws, qt_bytes(ws)))
        jobs.append((f"dequant_{tag}", harness(
            lambda y, w: (y @ dequantize(w[0], y.dtype))
            @ dequantize(w[1], y.dtype)), ws, qt_bytes(ws)))
    dense = tuple(
        jnp.stack([gen(base, i, shape, fan).astype(jnp.bfloat16)
                   for i in range(inner)])
        for base, shape, fan in ((ku, (d, f), d), (kd, (f, d), f))
    )
    jobs.append(("dense_bf16", harness(
        lambda y, w: (y @ w[0]) @ w[1]), dense, 2 * 2 * d * f))
    for name, fn, ws, nbytes in jobs:
        y = np.asarray(fn(x0, ws))  # compile + numerics guard
        if not np.isfinite(y).all():
            raise FloatingPointError(f"{name}: non-finite output")
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(fn(x0, ws))
            ts.append(time.perf_counter() - t0)
        out[f"gbps_{name}"] = round(nbytes * inner / min(ts) / 1e9, 1)
    del jobs, dense
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for k, peak in PEAK_HBM_BW.items():
        if k in kind:
            out["hbm_util_kernel_int8"] = round(
                out["gbps_kernel_int8"] * 1e9 / peak, 3
            )
            break
    return out


def _merge_rows(prior: list[dict], fresh: list[dict]) -> list[dict]:
    """Replace prior rows by config name (prior order kept), append new.

    A fresh SKIP never clobbers a prior MEASURED row: a tunnel death
    mid-measurement is caught and recorded as a skip, and round 4 lost its
    only measured 3-int8 number exactly that way — the artifact of record
    must keep the last real measurement (with its original stamp) and
    carry the failed refresh as ``refresh_skipped`` instead."""
    by_cfg = {str(r.get("config")): r for r in fresh}
    merged = []
    for r in prior:
        f = by_cfg.pop(str(r.get("config")), None)
        if f is None:
            merged.append(r)
        elif "skipped" in f and "skipped" not in r:
            merged.append(r | {"refresh_skipped": f["skipped"]})
        else:
            merged.append(f)
    merged.extend(by_cfg.values())
    return merged


class _RowSkip(Exception):
    """A ladder row that cannot run in this environment (doesn't fit)."""


def run_ladder(args, degraded: str | None) -> list[dict]:
    from distributed_llms_tpu.models.presets import get_preset

    dtype = "float32" if degraded is not None else args.dtype
    on_cpu = jax.devices()[0].platform == "cpu"
    # --rows: refresh only the named rows and MERGE into the existing
    # artifact — a kernel fix must not cost a multi-hour full re-run, and
    # untouched rows keep their original measured_on stamps.
    only = (
        {s.strip() for s in args.rows.split(",") if s.strip()}
        if args.rows else None
    )
    if only is not None:
        known = {str(e["config"]) for e in LADDER} | {
            "serving-latency", "continuous-batching", "paged-batching",
            "ragged-decode-8k", "ragged-decode-win-8k", "quant-matmul-bw",
            "prefill-flash-2048", "prefill-flash-8192",
            "prefill-flash-win-8192", "hop-latency",
            "spec-decode", "spec-decode-7b-int8", "spec-batching",
            "local-proc-batching", "chunked-prefill", "prefix-cache-ttft",
            "fault-recovery", "overload-goodput", "compile-stability",
            "replica-failover", "disagg-handoff", "analysis-wall",
            "kv-tiering", "decode-overlap", "constrained-decode",
            "mesh-paged", "mixed-step", "spec-paged", "tenant-qos",
            "fleet-goodput",
        }
        unknown = only - known
        if unknown:  # a typo must not masquerade as a clean zero-row run
            raise SystemExit(
                f"--rows: unknown config name(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )

    def want(name) -> bool:
        return only is None or str(name) in only

    # ALWAYS merge into the existing artifact (not only under --rows): the
    # incremental writes below would otherwise replace a complete artifact
    # with a truncated one the moment row 1 lands, and a mid-run crash
    # (tunnel wedge, OOM) would erase every not-yet-reached row — round 4's
    # first run lost its config-4 skip rows exactly this way.  A completed
    # run replaces every row it measured; unreachable rows keep their last
    # recorded state and stamp.
    prior: list[dict] = []
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f).get("rows", [])
        except (json.JSONDecodeError, OSError) as exc:
            # Never silently discard the artifact of record: preserve the
            # unreadable file and say so, or a --rows refresh would measure
            # one row and overwrite everything else with it.
            backup = f"{args.out}.corrupt"
            try:
                os.replace(args.out, backup)
            except OSError:
                backup = "unrecoverable"
            print(f"# WARNING: {args.out} unreadable ({exc}); preserved as "
                  f"{backup}; starting a fresh rows list", file=sys.stderr)

    rows: list[dict] = []

    def emit() -> list[dict]:
        merged = _merge_rows(prior, rows)
        _write_rows(args.out, merged)  # incremental: a crash keeps these
        return merged

    for entry in LADDER:
        if not want(entry["config"]):
            continue
        cfg = get_preset(entry["preset"])
        if on_cpu and _param_count(cfg) > 0.5e9:
            rows.append({
                "config": entry["config"], "preset": entry["preset"],
                "skipped": "cpu fallback: >0.5B-param decode is minutes/token",
            })
            print(f"# config {entry['config']} ({entry['preset']}): SKIP — cpu fallback",
                  file=sys.stderr)
            continue
        quant = entry.get("quant")
        ok, why = _fits(cfg, entry["batch"], entry["prompt"] + 2 * entry["new"],
                        dtype, quant)
        if not ok:
            rows.append({"config": entry["config"], "preset": entry["preset"],
                         "skipped": why})
            print(f"# config {entry['config']} ({entry['preset']}): SKIP — {why}",
                  file=sys.stderr)
            continue
        print(f"# config {entry['config']} ({entry['preset']}): measuring ({why})",
              file=sys.stderr)
        row = {"config": entry["config"]}
        try:
            row.update(_measure_decode(
                entry["preset"], entry["batch"], entry["prompt"], entry["new"],
                dtype, args.iters, quant=quant,
            ))
            row["measured_on"] = _stamp()
            if degraded is not None:
                row["degraded"] = degraded
        except Exception as exc:  # one config's OOM must not kill the ladder
            row.update({
                "preset": entry["preset"],
                "skipped": f"{type(exc).__name__}: "
                           f"{(str(exc).splitlines() or ['?'])[0][:200]}",
                "error": True,  # exception, not a doesn't-fit skip
            })
        rows.append(row)
        print(f"#   -> {row}", file=sys.stderr)
        emit()

    # Aux rows, one uniform measure/record/emit loop.  serving-latency and
    # continuous-batching use the north-star config on an accelerator and
    # the CPU fallback config otherwise; the kernel rows (paged, ragged,
    # flash prefill) run on real hardware only — CPU interpret mode would
    # measure the emulator, not the kernel.
    srv = FALLBACK if on_cpu else NORTH_STAR
    srv_cfg = get_preset(srv["preset"])

    def _serving():
        ok, why = _fits(srv_cfg, srv["batch"], srv["prompt"] + srv["new"],
                        dtype, srv.get("quant"))
        if not ok:
            raise _RowSkip(why)
        return _measure_serving_latency(
            srv["preset"], srv["batch"], srv["prompt"], dtype,
            quant=srv.get("quant"), new_tokens=srv["new"],
        )

    aux = [
        ("serving-latency", _serving),
        ("continuous-batching", lambda: _measure_continuous_batching(
            srv["preset"], dtype, quant=srv.get("quant"))),
        # Cluster path end-to-end (coordinator + worker subprocesses) —
        # workers pin CPU, so this row runs (and means the same thing) on
        # every platform without contending for the chip.
        ("local-proc-batching", lambda: _measure_local_proc_batching(
            dtype=dtype)),
        # Chunked-prefill QoS: short-request latency under long-prompt
        # interference — a scheduling effect, meaningful on any platform.
        ("chunked-prefill", lambda: _measure_chunked_prefill(
            dtype=dtype, iters=args.iters)),
        # Automatic prefix caching: TTFT with hash-block KV reuse ON vs OFF
        # on 75%-shared-prefix traffic (the chat shape) — a prefill-compute
        # effect, meaningful on any platform.
        ("prefix-cache-ttft", lambda: _measure_prefix_cache_ttft(
            dtype=dtype)),
        # Crash-safe serving: decode-step crash injected under concurrent
        # load; stamps supervisor recovery latency and the fraction of
        # requests that still complete — a host-scheduling effect,
        # meaningful on any platform.
        ("fault-recovery", lambda: _measure_fault_recovery(dtype=dtype)),
        # Overload-safe serving: ~2x pool-capacity offered load against a
        # small paged pool; stamps goodput, the shed fraction (cost-gate
        # 429s with Retry-After), and how many preemptions the on-demand
        # growth plane took — a host-scheduling effect, meaningful on any
        # platform.
        ("overload-goodput", lambda: _measure_overload_goodput(dtype=dtype)),
        # Elastic multi-tenant serving: the traffic harness replays one
        # bursty aggressor+victim trace with tenant QoS off vs on
        # (weighted-fair + per-tenant rate quota) — victim goodput/p95
        # ITL/SLO attainment both ways, aggressor structured-shed
        # fraction — plus one autoscale up/down cycle's recovery times
        # on a live min=1/max=2 fleet.  Host-scheduling effects,
        # meaningful on any platform.
        ("tenant-qos", lambda: _measure_tenant_qos(dtype=dtype)),
        # KV memory tiering: concurrent capacity per pool byte at int8 vs
        # bf16, swap-restore vs recompute latency for a long-prefix
        # preemption victim, and spill-hit TTFT after eviction — memory
        # accounting + host-scheduling effects, meaningful on any
        # platform.
        ("kv-tiering", lambda: _measure_kv_tiering(dtype=dtype)),
        # Dispatch-ahead engine loop: per-chunk device gap (host time the
        # device sits idle between chunks) and steady decode throughput,
        # overlap off vs on — a host-scheduling effect, meaningful on any
        # platform (JAX CPU dispatch is async too).
        ("decode-overlap", lambda: _measure_decode_overlap(dtype=dtype)),
        # Stall-free mixed batching: resident decode rows' ITL p95 while
        # long prompts chunk-prefill, schedule=alternate (serialized
        # bites stall the batch) vs schedule=mixed (fused token-budget
        # step) at equal budget, plus both long prompts' TTFT — a
        # host-scheduling effect, meaningful on any platform.
        ("mixed-step", lambda: _measure_mixed_step(dtype=dtype)),
        # Paged speculative serving: spec-on vs spec-off at equal pool
        # budget, acceptance fraction, and the capacity arithmetic that
        # shows paged spec dropping the contiguous max_len+spec_k+1
        # reservation.  Exactness + capacity are platform-independent;
        # tok/s carries the CPU degraded marker for TPU re-stamp.
        ("spec-paged", lambda: _measure_spec_paged(dtype=dtype)),
        # Grammar-constrained structured output: token-DFA compile wall
        # for a realistic tool-call schema, constrained-vs-free steady
        # tok/s (the traced mask overhead), and the parse-valid fraction
        # over >= 16 completions — meaningful on any platform.
        ("constrained-decode", lambda: _measure_constrained_decode(
            dtype="float32")),
        # Mesh-native paged serving: per-chip row capacity at a fixed
        # per-chip pool byte budget, tp1 vs tp2 (the pool shards KV heads
        # over 'model'), plus byte-exactness and steady tok/s for both
        # legs.  Runs over a forced 2-device virtual CPU mesh in a
        # subprocess and self-stamps that provenance — the throughput
        # number needs real chips, the capacity factor does not.
        ("mesh-paged", lambda: _measure_mesh_paged(dtype="float32")),
        # Replica-fleet serving: N replicas behind the health-aware
        # router, one killed abruptly mid-storm; stamps failover recovery
        # latency, goodput, and the byte-exactness count of every
        # completed request — a host-scheduling effect, meaningful on any
        # platform.
        ("replica-failover", lambda: _measure_replica_failover(dtype=dtype)),
        # Fleet control plane at 4 replicas: the same storm colocated vs
        # disaggregated (2 prefill + 2 decode), plus cross-replica KV
        # reuse — directory hit rate and 1-token pull-vs-reprefill TTFT
        # while the page-holding replica drains.  Host-scheduling +
        # transfer effects, meaningful on any platform.
        ("fleet-goodput", lambda: _measure_fleet_goodput(dtype=dtype)),
        # Disaggregated prefill/decode: the same long+short storm served
        # colocated then disaggregated — short-request latency under
        # long-prompt interference, verified-handoff latency, and the
        # fallback-to-colocated recovery time when the prefill tier is
        # killed.  A host-scheduling effect, meaningful on any platform.
        ("disagg-handoff", lambda: _measure_disagg_handoff(dtype=dtype)),
        # Compile-key stability (tools/graftcheck GC4 as a measurement):
        # distinct compile-cache keys per serving entry point across the
        # request-length ladder vs the declared bucket budget — pure
        # tracing, meaningful on any platform.
        ("compile-stability", _measure_compile_stability),
        # Static-analysis gate wall time (graftlint + graftcheck +
        # graftflow + graftsync + graftmodel as subprocesses): the tier-1
        # gate's own
        # cost, stamped
        # so rule growth that slows every CI run shows in the trajectory.
        ("analysis-wall", _measure_analysis_wall),
    ]
    if not on_cpu:
        # Paged vs contiguous batching (pool at ~45% of contiguous KV
        # bytes); ragged vs dense decode at 8k cache width; flash prefill
        # pair (2048 = short-context sanity point, 8192 = long-context where
        # the O(T^2) attention share grows and tiling should beat dot).
        aux += [
            ("paged-batching", lambda: _measure_paged_batching(dtype=dtype)),
            ("ragged-decode-8k", lambda: _measure_ragged_decode(dtype=dtype)),
            # Windowed variant: the kernel reads only each row's window
            # span — the long-context decode win for Mistral-style models.
            ("ragged-decode-win-8k", lambda: _measure_ragged_decode(
                dtype=dtype, window=1024)),
            ("quant-matmul-bw", lambda: _measure_quant_matmul_bw(
                iters=max(args.iters, 5))),
            # Speculative decoding (runtime/speculative.py): small-model
            # sanity row + the north-star shape (7B int8 target, int4
            # self-draft).  Both assert on-device exactness vs plain greedy.
            # Targets are quantized so target and draft share the same
            # on-device-generated base weights (_gen_quantized_on_device
            # keys leaves identically across bit-widths; the bf16 path
            # draws DIFFERENT values, which would make the "self"-draft an
            # unrelated model and the acceptance rate meaningless).
            ("spec-decode", lambda: _measure_speculative(
                "tinyllama-1.1b", dtype, target_quant="int8",
                iters=args.iters)),
            ("spec-decode-7b-int8", lambda: _measure_speculative(
                "llama-2-7b", dtype, target_quant="int8", iters=args.iters)),
            ("spec-batching", lambda: _measure_spec_batching(dtype=dtype)),
        ]
        aux += [
            (f"prefill-flash-{seq}", functools.partial(
                _measure_prefill_flash, batch=b, seq=seq, dtype=dtype,
                iters=args.iters))
            for seq, b in ((2048, 2), (8192, 1))
        ]
        # Windowed prefill (Mistral-style 2048-window at 8k context): the
        # kernel's window band skips out-of-window tiles entirely while
        # the dot path pays the full dense masked matmul.
        aux += [
            ("prefill-flash-win-8192", functools.partial(
                _measure_prefill_flash, batch=1, seq=8192, dtype=dtype,
                iters=args.iters, window=2048)),
        ]
    for name, fn in aux:
        if not want(name):
            continue
        row = {"config": name}
        try:
            row.update(fn())
            # Self-stamping rows (mesh-paged runs over a forced-device
            # virtual CPU mesh in a subprocess) carry their own honest
            # provenance — never overwrite it with the parent platform's.
            row.setdefault("measured_on", _stamp())
            # local-proc-batching pins its workers to CPU BY DESIGN (its
            # subject is the cluster path's own overhead) — a run-wide
            # "accelerator-unavailable" marker would mislabel its native
            # measurement as a fallback.
            if degraded is not None and name != "local-proc-batching":
                row.setdefault("degraded", degraded)
        except _RowSkip as skip:
            row.update({"preset": srv["preset"], "skipped": str(skip)})
        except Exception as exc:
            row["skipped"] = (
                f"{type(exc).__name__}: "
                f"{(str(exc).splitlines() or ['?'])[0][:200]}"
            )
            row["error"] = True
        rows.append(row)
        print(f"# {name}: {row}", file=sys.stderr)
        emit()
    if want("hop-latency"):
        hop = _measure_hop_latency()
        degraded_hop = degraded
        if hop is None:
            # One visible device: measure the CPU fake-mesh upper bound in
            # a SUBPROCESS (xla_force_host_platform_device_count is frozen
            # once this process's backend initialized) so the artifact
            # records a number instead of a skip — BASELINE.md used to
            # quote this bound from prose the JSON lacked.
            hop = _measure_hop_latency_cpu_fallback()
            degraded_hop = ("cpu fake-mesh (virtual devices, jit dispatch "
                            "included) — upper bound only, not an ICI hop")
        if hop is not None:
            row = {"config": "hop-latency", **hop}
            # The fallback stamps itself 'cpu (fake mesh)' — the parent's
            # _stamp() would claim the PARENT's platform (e.g. tpu) for a
            # number measured on virtual CPU devices.
            row.setdefault("measured_on", _stamp())
            if degraded_hop:
                row["degraded"] = degraded_hop
            rows.append(row)
            print(f"# hop latency: {hop}", file=sys.stderr)
        else:
            # SURVEY §6 metric is unmeasurable on one chip and the CPU
            # fallback also failed — record that explicitly rather than
            # omitting the row (VERDICT r2 weak 5).
            rows.append({
                "config": "hop-latency",
                "skipped": "needs >1 device and the cpu fake-mesh "
                           "subprocess fallback failed",
            })
    emit()
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default=None,
                    help="override the measured preset (default: north-star "
                         "llama-2-7b int8 on an accelerator, gpt2-125m on cpu)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--prompt-len", type=int, default=None)
    ap.add_argument("--new-tokens", type=int, default=None)
    ap.add_argument("--quant", default=None, choices=["int8", "int4"])
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--probe-attempts", type=int, default=4)
    ap.add_argument("--measure-timeout", type=float, default=2700.0,
                    help="watchdog deadline for accelerator measurements; a "
                         "mid-measurement tunnel hang prints a CPU-subprocess "
                         "fallback line and exits instead of capturing "
                         "nothing (0 = off)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the CPU backend before init (watchdog child)")
    ap.add_argument("--ladder", action="store_true",
                    help="measure all BASELINE ladder configs that fit")
    ap.add_argument("--out", default="BENCH_LADDER.json",
                    help="ladder results file (with --ladder)")
    ap.add_argument("--rows", default=None,
                    help="comma-separated config names (e.g. "
                         "'3-int8,ragged-decode-8k'): run only these ladder "
                         "rows and merge them into --out, leaving every "
                         "other row untouched")
    args = ap.parse_args()

    if args.force_cpu:
        # Child-process mode for the mid-measurement watchdog: pin CPU
        # before any backend init (the axon plugin ignores JAX_PLATFORMS).
        jax.config.update("jax_platforms", "cpu")
        degraded = "accelerator-unavailable; measured on cpu fallback"
    else:
        degraded = _init_backend(args.probe_timeout, args.probe_attempts)
    # Arm the hang watchdog only when measuring on a (possibly flaky)
    # accelerator — it covers BOTH default and --ladder modes.
    # Default mode only: the watchdog guarantees the driver its ONE JSON
    # line when the tunnel wedges mid-measurement.  A full --ladder run
    # legitimately takes hours, so a flat deadline would kill it mid-flight
    # (round 4's first run died exactly this way at minute 45); ladder runs
    # are crash-isolated per row and deadline-guarded by the runbook's
    # `timeout` instead.
    watchdog_done = _arm_watchdog(
        args.measure_timeout if degraded is None and not args.ladder else 0,
        args,
    )
    if degraded is not None:
        # CPU can't hold bf16 numerics through XLA's collective passes and is
        # slower in bf16 anyway; measure the fallback in f32.
        args.dtype = "float32"

    if args.ladder:
        rows = run_ladder(args, degraded)  # returns THIS run's rows; emit()
        # inside already wrote the merged artifact, and headline selection
        # must not resurface a prior run's row (a CPU --rows refresh would
        # otherwise print a stale TPU headline).
        print(f"# ladder results -> {args.out}", file=sys.stderr)
        # Headline = the north-star config if it was measured, else the
        # first measured row.
        head = next(
            (r for r in rows if r.get("config") == "3-int8" and "tok_per_s" in r),
            next((r for r in rows if "tok_per_s" in r), None),
        )
        if head is None and args.rows:
            # A --rows refresh may touch only non-throughput rows (e.g.
            # quant-matmul-bw); report the artifact's standing headline
            # rather than a false "all configs skipped" collapse.
            try:
                with open(args.out) as f:
                    merged = json.load(f).get("rows", [])
            except (OSError, json.JSONDecodeError):
                merged = []
            head = next(
                (r for r in merged
                 if r.get("config") == "3-int8" and "tok_per_s" in r),
                next((r for r in merged if "tok_per_s" in r), None),
            )
    else:
        # Default: the north-star metric (7B int8) on an accelerator; on the
        # CPU fallback a 7B decode is minutes/token, so degrade to GPT-2.
        # An explicit --preset measures exactly what was asked (plain bf16
        # unless --quant is also given) and never silently degrades.
        explicit = args.preset is not None
        if explicit:
            base = {"preset": args.preset, "batch": args.batch or 8,
                    "prompt": args.prompt_len or 64, "new": args.new_tokens or 64,
                    "quant": args.quant}
        else:
            base = dict(FALLBACK if degraded is not None else NORTH_STAR)
            base["batch"] = args.batch or base["batch"]
            base["prompt"] = args.prompt_len or base["prompt"]
            base["new"] = args.new_tokens or base["new"]
            base["quant"] = args.quant or base["quant"]
        try:
            head = _measure_decode(
                base["preset"], base["batch"], base["prompt"], base["new"],
                args.dtype, args.iters, quant=base.get("quant"),
            )
        except Exception as exc:
            if explicit or degraded is not None:
                raise  # measure what was asked or fail loudly
            # North-star config failed on the accelerator (e.g. OOM on an
            # unexpected chip): degrade to the fallback config, marked.
            degraded = (
                f"north-star {base['preset']} failed "
                f"({type(exc).__name__}); measured fallback"
            )
            head = _measure_decode(
                FALLBACK["preset"], FALLBACK["batch"], FALLBACK["prompt"],
                FALLBACK["new"], args.dtype, args.iters,
            )

    if head is None:  # every ladder config skipped
        result = {
            "metric": "decode tokens/sec", "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0, "degraded": "all ladder configs skipped",
        }
    else:
        desc = head["preset"] + (f" {head['quant']}" if head.get("quant") else "")
        result = {
            "metric": f"decode tokens/sec ({desc}, batch={head['batch']}, "
            f"{head['platform']}x{head['n_chips']})",
            "value": head["tok_per_s"],
            "unit": "tok/s",
            "vs_baseline": round(head["tok_per_s"] / NORTH_STAR_TOKS_PER_S, 4),
        }
        for extra in ("mfu_2N", "hbm_util", "weight_stream_gb_per_s"):
            if extra in head:
                result[extra] = head[extra]
        if degraded is not None:
            result["degraded"] = degraded
    watchdog_done.set()
    print(json.dumps(result))
    if args.ladder and args.rows:
        attempted = [r for r in rows if "config" in r]
        if attempted and all(r.get("error") for r in attempted):
            # Every requested row died on an exception (tunnel wedge, OOM):
            # tell the runbook to retry rather than reading rc 0 as "row
            # recorded".  The artifact keeps prior measured rows either way
            # (_merge_rows).
            raise SystemExit(4)


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # driver contract: ALWAYS emit one JSON line
        print(json.dumps({
            "metric": "decode tokens/sec",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": 0.0,
            "degraded": f"bench crashed: {type(exc).__name__}: {exc}",
        }))
        raise SystemExit(0)
