#!/usr/bin/env python
"""Benchmark harness (driver contract: prints ONE JSON line).

Measures greedy-decode throughput of GPT-2-125M (BASELINE.md ladder config 1)
on the available accelerator.  The reference publishes no numbers
(SURVEY §6: README is a title line, no benchmarks/ dir, placeholder compute),
so ``vs_baseline`` is reported against the driver's north-star target of
1000 tok/s aggregate (BASELINE.json).

Usage: python bench.py [--preset gpt2-125m] [--batch 8] [--prompt-len 64]
       [--new-tokens 64] [--dtype bfloat16]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

import jax
import jax.numpy as jnp

NORTH_STAR_TOKS_PER_S = 1000.0  # BASELINE.json: >=1000 tok/s aggregate


def _probe_accelerator(timeout_s: float) -> str | None:
    """Check in a subprocess (hard-killed on timeout) whether the default JAX
    backend initializes.  The axon TPU plugin, when its tunnel is down, blocks
    ``jax.devices()`` for ~25 minutes before raising UNAVAILABLE — round 1's
    BENCH artifact died exactly this way.  Returns the platform name or None."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def _init_backend(probe_timeout: float, attempts: int) -> str | None:
    """Retry accelerator init with backoff; fall back to CPU on persistent
    failure.  Returns a degraded-marker string, or None if healthy."""
    for i in range(attempts):
        platform = _probe_accelerator(probe_timeout)
        if platform is not None and platform != "cpu":
            return None  # healthy — main process will init the same backend
        if platform == "cpu":
            # No accelerator configured at all: still a CPU measurement.
            return "no accelerator present; measured on cpu"
        if i + 1 < attempts:
            time.sleep(5.0 * (i + 1))
    # Persistent failure: pin the CPU backend before any jax backend use in
    # this process (the axon plugin ignores the JAX_PLATFORMS env var, so this
    # must go through jax.config).
    jax.config.update("jax_platforms", "cpu")
    return "accelerator-unavailable; measured on cpu fallback"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-125m")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--probe-attempts", type=int, default=2)
    args = ap.parse_args()

    degraded = _init_backend(args.probe_timeout, args.probe_attempts)
    if degraded is not None:
        # CPU can't hold bf16 numerics through XLA's collective passes and is
        # slower in bf16 anyway; measure the fallback in f32.
        args.dtype = "float32"

    from distributed_llms_tpu.models import model as model_lib
    from distributed_llms_tpu.models.presets import get_preset
    from distributed_llms_tpu.runtime import generate as gen_lib

    cfg = get_preset(args.preset, dtype=args.dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (args.batch, args.prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )
    lens = jnp.full((args.batch,), args.prompt_len, dtype=jnp.int32)
    rng = jax.random.key(2)

    # The axon-tunneled TPU has ~80ms constant dispatch/transfer overhead and
    # a block_until_ready that does NOT actually block, so we (a) force a host
    # transfer with np.asarray and (b) use a two-point measurement — time
    # decode at N and 2N tokens and take the delta — which cancels the
    # constant overhead and the (shared) prefill cost.
    import numpy as np

    def timed(n_new: int) -> float:
        # compile (separate trace per static n_new)
        np.asarray(
            gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
        )
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            np.asarray(
                gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
            )
            times.append(time.perf_counter() - t0)
        return min(times)

    n1, n2 = args.new_tokens, 2 * args.new_tokens
    t1, t2 = timed(n1), timed(n2)
    if t2 <= t1:  # overhead-dominated; fall back to the single-shot number
        tps = args.batch * n2 / t2
    else:
        tps = args.batch * (n2 - n1) / (t2 - t1)

    n_chips = jax.device_count()
    result = {
        "metric": f"decode tokens/sec ({args.preset}, batch={args.batch}, "
        f"{jax.devices()[0].platform}x{n_chips})",
        "value": round(tps, 2),
        "unit": "tok/s",
        "vs_baseline": round(tps / NORTH_STAR_TOKS_PER_S, 4),
    }
    if degraded is not None:
        result["degraded"] = degraded
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # driver contract: ALWAYS emit one JSON line
        print(json.dumps({
            "metric": "decode tokens/sec",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": 0.0,
            "degraded": f"bench crashed: {type(exc).__name__}: {exc}",
        }))
        raise SystemExit(0)
