#!/usr/bin/env python
"""Benchmark harness (driver contract: prints ONE JSON line).

Measures greedy-decode throughput of GPT-2-125M (BASELINE.md ladder config 1)
on the available accelerator.  The reference publishes no numbers
(SURVEY §6: README is a title line, no benchmarks/ dir, placeholder compute),
so ``vs_baseline`` is reported against the driver's north-star target of
1000 tok/s aggregate (BASELINE.json).

Usage: python bench.py [--preset gpt2-125m] [--batch 8] [--prompt-len 64]
       [--new-tokens 64] [--dtype bfloat16]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from distributed_llms_tpu.models import model as model_lib
from distributed_llms_tpu.models.presets import get_preset
from distributed_llms_tpu.runtime import generate as gen_lib

NORTH_STAR_TOKS_PER_S = 1000.0  # BASELINE.json: >=1000 tok/s aggregate


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-125m")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()

    cfg = get_preset(args.preset, dtype=args.dtype)
    params = model_lib.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (args.batch, args.prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )
    lens = jnp.full((args.batch,), args.prompt_len, dtype=jnp.int32)
    rng = jax.random.key(2)

    # The axon-tunneled TPU has ~80ms constant dispatch/transfer overhead and
    # a block_until_ready that does NOT actually block, so we (a) force a host
    # transfer with np.asarray and (b) use a two-point measurement — time
    # decode at N and 2N tokens and take the delta — which cancels the
    # constant overhead and the (shared) prefill cost.
    import numpy as np

    def timed(n_new: int) -> float:
        # compile (separate trace per static n_new)
        np.asarray(
            gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
        )
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            np.asarray(
                gen_lib.generate_tokens(params, cfg, prompt, lens, rng, max_new_tokens=n_new)
            )
            times.append(time.perf_counter() - t0)
        return min(times)

    n1, n2 = args.new_tokens, 2 * args.new_tokens
    t1, t2 = timed(n1), timed(n2)
    if t2 <= t1:  # overhead-dominated; fall back to the single-shot number
        tps = args.batch * n2 / t2
    else:
        tps = args.batch * (n2 - n1) / (t2 - t1)

    n_chips = jax.device_count()
    result = {
        "metric": f"decode tokens/sec ({args.preset}, batch={args.batch}, "
        f"{jax.devices()[0].platform}x{n_chips})",
        "value": round(tps, 2),
        "unit": "tok/s",
        "vs_baseline": round(tps / NORTH_STAR_TOKS_PER_S, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
