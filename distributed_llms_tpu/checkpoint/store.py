"""Sharded checkpoint store: per-shard .npz files + JSON manifest.

Successor of the reference's shard store (`shard_<i>.pt` + `shard_info.json`
+ copied config.json, src/model/shard_manager.py:63-74) with its defects
fixed by construction: no pickle anywhere (npz + JSON), explicit param names
(no fragile layer-index parsing, D6), safetensors-native upstream (D5).

Layout on disk:
    <dir>/manifest.json   {params: {name: {shard, shape, dtype, quant...}},
                           arrays: {name: {shard[, offset, nbytes, crc32,
                           dtype, shape]}}, storage, num_shards,
                           model_config, quantization}
    <dir>/shard_<i>.bin   storage="raw" (default): tensors concatenated at
                          64-byte-aligned offsets; read by the native C++
                          parallel-pread tier (native/dlt_io.cpp) with
                          per-tensor CRC32 verification, Python fallback
    <dir>/shard_<i>.npz   storage="npz": numpy archives (v1 compatibility)

Packing uses the reference's greedy byte-balanced algorithm
(parallel.stages.pack_greedy).  ``load_shards`` can read a subset of shards
(a pipeline host loads only its stages' params) and ``reconstruct`` merges
everything back — the `reconstruct_model` parity point
(src/model/shard_manager.py:82-93).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import numpy as np

from ..core.config import ModelConfig
from ..parallel.stages import pack_greedy
from .. import native
from . import quantize as quant_lib
from .quantize import QuantizedTensor

SEP = "/"
MANIFEST = "manifest.json"
ALIGN = 64  # raw storage: tensor offsets aligned for mmap/DMA friendliness

# HF tokenizer files copied into the store so serving decodes with the
# model's real vocab (the reference tokenized with the HF tokenizer on the
# master, src/master/node.py:235-245; without this the cluster path fell
# back to byte-level ids — gibberish against a real checkpoint).
TOKENIZER_DIR = "tokenizer"
_TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "vocab.json",
    "merges.txt",
    "special_tokens_map.json",
    "tokenizer.model",
    "added_tokens.json",
    "vocab.txt",
    "spiece.model",
)


def _flatten(params: Any) -> dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )[0]:
        name = SEP.join(str(getattr(p, "key", p)) for p in path)
        flat[name] = leaf
    return flat


def _unflatten(flat: dict[str, Any]) -> dict[str, Any]:
    tree: dict[str, Any] = {}
    for name, leaf in flat.items():
        node = tree
        parts = name.split(SEP)
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def save_shards(
    params: Any,
    out_dir: str,
    num_shards: int = 1,
    model_config: ModelConfig | None = None,
    quantization: str | None = None,  # None | "int8" | "int4"
    quant_block: int = 128,
    storage: str = "raw",  # "raw" (native-IO blobs + CRC) | "npz" (v1)
    tokenizer_src: str | None = None,  # checkpoint dir whose tokenizer files
    #                                    are copied into the store
) -> dict:
    """Write params (optionally quantizing first) into a sharded store.
    Returns the manifest dict."""
    if storage not in ("raw", "npz"):
        raise ValueError(f"unknown storage {storage!r}; raw|npz")
    os.makedirs(out_dir, exist_ok=True)
    tokenizer_rel: str | None = None
    if tokenizer_src is not None:
        import shutil

        found = [
            f for f in _TOKENIZER_FILES
            if os.path.isfile(os.path.join(tokenizer_src, f))
        ]
        if found:
            tok_dir = os.path.join(out_dir, TOKENIZER_DIR)
            # A reused shard_dir may hold a previous model's tokenizer files;
            # stale ones (e.g. an old tokenizer.json next to a new
            # tokenizer.model) would win AutoTokenizer's file preference and
            # serve the wrong vocab — clear before copying.
            shutil.rmtree(tok_dir, ignore_errors=True)
            os.makedirs(tok_dir, exist_ok=True)
            for f in found:
                shutil.copy2(os.path.join(tokenizer_src, f), os.path.join(tok_dir, f))
            tokenizer_rel = TOKENIZER_DIR
        else:
            from ..core.observability import get_logger

            get_logger("store").warning(
                "tokenizer_src %r contains no recognized tokenizer files; "
                "store will fall back to byte-level ids at serve time",
                tokenizer_src,
            )
    if quantization:
        bits = {"int8": 8, "int4": 4}[quantization]
        params = quant_lib.quantize_tree(params, bits=bits, block=quant_block)

    flat = _flatten(params)
    sizes = {}
    for name, leaf in flat.items():
        if isinstance(leaf, QuantizedTensor):
            sizes[name] = leaf.data.size + leaf.scale.size * 4
        else:
            sizes[name] = int(np.asarray(leaf).nbytes)
    assignment = pack_greedy(sizes, num_shards)

    entries: dict[str, dict] = {}
    arrays_meta: dict[str, dict] = {}
    shard_arrays: list[dict[str, np.ndarray]] = [dict() for _ in range(num_shards)]
    for name, leaf in flat.items():
        shard = assignment[name]
        if isinstance(leaf, QuantizedTensor):
            shard_arrays[shard][name + ".q"] = np.asarray(leaf.data)
            shard_arrays[shard][name + ".scale"] = np.asarray(leaf.scale)
            entries[name] = {
                "shard": shard,
                "shape": list(leaf.orig_shape),
                "dtype": "quantized",
                "bits": leaf.bits,
                "pack_axis": leaf.pack_axis,
            }
        else:
            arr = np.asarray(leaf)
            # Neither npz nor numpy dtypes know bfloat16: store raw bytes
            # viewed as uint16.
            if arr.dtype == jax.numpy.bfloat16:
                shard_arrays[shard][name] = arr.view(np.uint16)
                entries[name] = {"shard": shard, "shape": list(arr.shape), "dtype": "bfloat16"}
            else:
                shard_arrays[shard][name] = arr
                entries[name] = {"shard": shard, "shape": list(arr.shape), "dtype": str(arr.dtype)}

    for i, arrays in enumerate(shard_arrays):
        if storage == "npz":
            np.savez(os.path.join(out_dir, f"shard_{i}.npz"), **arrays)
            for aname in arrays:
                arrays_meta[aname] = {"shard": i}
            continue
        # raw: concatenated tensors at 64-byte-aligned offsets + CRC32.
        path = os.path.join(out_dir, f"shard_{i}.bin")
        with open(path, "wb") as f:
            for aname, arr in arrays.items():
                arr = np.ascontiguousarray(arr)
                pad = (-f.tell()) % ALIGN
                f.write(b"\0" * pad)
                offset = f.tell()
                # Zero-copy: stream the array buffer and checksum it in
                # place (no tensor-sized bytes duplicate on the save path).
                arr.tofile(f)
                arrays_meta[aname] = {
                    "shard": i,
                    "offset": offset,
                    "nbytes": int(arr.nbytes),
                    "crc32": native.crc32(arr),
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }

    manifest = {
        "format_version": 2,
        "storage": storage,
        "num_shards": num_shards,
        "quantization": quantization,
        "params": entries,
        "arrays": arrays_meta,
        "model_config": dataclasses.asdict(model_config) if model_config else None,
        "tokenizer": tokenizer_rel,  # store-relative dir of HF tokenizer files
    }
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def load_manifest(store_dir: str) -> dict:
    with open(os.path.join(store_dir, MANIFEST)) as f:
        return json.load(f)


def _load_arrays_npz(
    store_dir: str, manifest: dict, wanted: set[int]
) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for i in wanted:
        path = os.path.join(store_dir, f"shard_{i}.npz")
        if not os.path.exists(path):
            raise FileNotFoundError(f"manifest lists shard {i} but {path} is missing")
        z = np.load(path)
        for aname in z.files:
            out[aname] = z[aname]
    return out


def _load_arrays_raw(
    store_dir: str, manifest: dict, wanted: set[int], io_threads: int
) -> dict[str, np.ndarray]:
    """Raw storage: parallel native pread of every wanted tensor segment,
    CRC32-verified against the manifest."""
    names: list[str] = []
    tasks: list[tuple[str, int, int]] = []
    for aname, meta in manifest["arrays"].items():
        if meta["shard"] not in wanted:
            continue
        path = os.path.join(store_dir, f"shard_{meta['shard']}.bin")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"manifest lists shard {meta['shard']} but {path} is missing"
            )
        names.append(aname)
        tasks.append((path, meta["offset"], meta["nbytes"]))
    bufs, crcs = native.read_segments(tasks, threads=io_threads, with_crc=True)
    out: dict[str, np.ndarray] = {}
    for aname, buf, crc in zip(names, bufs, crcs):
        meta = manifest["arrays"][aname]
        if crc != meta["crc32"]:
            raise IOError(
                f"checksum mismatch for {aname!r} in shard {meta['shard']} "
                f"(expected {meta['crc32']:#010x}, got {crc:#010x}) — store corrupt?"
            )
        out[aname] = buf.view(np.dtype(meta["dtype"])).reshape(meta["shape"])
    return out


def load_shards(
    store_dir: str,
    shards: list[int] | None = None,
    dequantize: bool = False,
    dtype: Any = None,
    io_threads: int = 8,
) -> dict[str, Any]:
    """Load params from the store (optionally only some shards).  Returns the
    nested param tree containing only the params present in those shards."""
    manifest = load_manifest(store_dir)
    wanted = set(range(manifest["num_shards"])) if shards is None else set(shards)
    missing = wanted - set(range(manifest["num_shards"]))
    if missing:
        raise ValueError(f"store has {manifest['num_shards']} shards; no {sorted(missing)}")

    if manifest.get("storage", "npz") == "raw":
        arrays = _load_arrays_raw(store_dir, manifest, wanted, io_threads)
    else:
        arrays = _load_arrays_npz(store_dir, manifest, wanted)

    import jax.numpy as jnp

    flat: dict[str, Any] = {}
    for name, meta in manifest["params"].items():
        if meta["shard"] not in wanted:
            continue
        if meta["dtype"] == "quantized":
            qt = QuantizedTensor(
                data=jnp.asarray(arrays[name + ".q"]),
                scale=jnp.asarray(arrays[name + ".scale"]),
                bits=meta["bits"],
                orig_shape=tuple(meta["shape"]),
                # Legacy stores (written before pack_axis landed) packed int4
                # pairs along the LAST axis; missing key must decode as -1,
                # not the modern default of -2, or unpack runs along the
                # wrong axis and dequantize fails/corrupts.
                pack_axis=meta.get("pack_axis", -1),
            )
            flat[name] = quant_lib.dequantize(qt, dtype or jnp.float32) if dequantize else qt
        elif meta["dtype"] == "bfloat16":
            arr = jnp.asarray(arrays[name].view(jnp.bfloat16))
            flat[name] = arr.astype(dtype) if dtype else arr
        else:
            arr = jnp.asarray(arrays[name])
            flat[name] = arr.astype(dtype) if dtype else arr
    return _unflatten(flat)


def reconstruct(store_dir: str, dtype: Any = None) -> dict[str, Any]:
    """Merge every shard back into a full (dequantized) param tree."""
    return load_shards(store_dir, shards=None, dequantize=True, dtype=dtype)
