"""Model acquisition: HF Hub snapshot download (network-gated) + local paths.

Parity with the reference's downloader (`snapshot_download(repo_id,
cache_dir="./models")`, src/model/downloader.py:4-6), with the offline case
handled explicitly instead of crashing: a local directory path is used as-is,
and a missing-network download raises a clear error naming the fix.
"""

from __future__ import annotations

import os


def fetch_model(model_id_or_path: str, cache_dir: str = "./models") -> str:
    """Return a local directory containing the model checkpoint.

    - existing local path -> returned unchanged
    - otherwise -> huggingface_hub.snapshot_download (requires network)
    """
    if os.path.isdir(model_id_or_path):
        return model_id_or_path
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "huggingface_hub is not installed and "
            f"{model_id_or_path!r} is not a local directory"
        ) from e
    try:
        return snapshot_download(repo_id=model_id_or_path, cache_dir=cache_dir)
    except Exception as e:
        raise RuntimeError(
            f"could not download {model_id_or_path!r} (offline?); pass a local "
            "checkpoint directory instead"
        ) from e
