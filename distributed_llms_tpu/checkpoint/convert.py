"""HF checkpoint -> stacked JAX param-tree conversion.

Successor of the reference's loader + sharder front-end, built to fix its two
checkpoint defects by construction:
- D5: safetensors files were loaded with ``torch.load``
  (src/model/shard_manager.py:21-24) — here safetensors is read natively;
- D6: layer indices were parsed with ``key.split('.')[1].isdigit()``
  (src/model/shard_manager.py:36-42), which matches no real HF name — here
  each family has an explicit name-mapping table, golden-tested against
  transformers reference outputs.

Input is a flat ``{name: numpy array}`` state dict (from safetensors shards or
a torch ``state_dict``); output is the stacked-layer pytree that
``models.model.forward`` consumes.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, Mapping

import numpy as np

from ..core.config import ModelConfig

Array = np.ndarray
StateDict = Mapping[str, Array]


# ---------------------------------------------------------------------------
# State-dict loading (safetensors native, torch .bin fallback)
# ---------------------------------------------------------------------------

def load_state_dict(model_dir: str) -> dict[str, Array]:
    """Load all weight files in a HF snapshot directory into numpy arrays."""
    out: dict[str, Array] = {}
    names = sorted(os.listdir(model_dir))
    st_files = [n for n in names if n.endswith(".safetensors")]
    bin_files = [n for n in names if n.endswith(".bin") and "training" not in n]
    if st_files:
        from safetensors.numpy import load_file

        for name in st_files:
            out.update(load_file(os.path.join(model_dir, name)))
    elif bin_files:
        import torch

        for name in bin_files:
            sd = torch.load(os.path.join(model_dir, name), map_location="cpu", weights_only=True)
            out.update({k: v.float().numpy() for k, v in sd.items()})
    else:
        raise FileNotFoundError(f"no .safetensors or .bin weights under {model_dir}")
    return out


def torch_state_dict_to_numpy(sd: Mapping[str, Any]) -> dict[str, Array]:
    """Convert a live torch state_dict (e.g. a transformers model in a test)
    to numpy, upcasting to float32."""
    return {k: np.asarray(v.detach().to("cpu").float().numpy()) for k, v in sd.items()}


# ---------------------------------------------------------------------------
# Family mapping tables
# ---------------------------------------------------------------------------

def _strip_prefix(sd: StateDict, prefixes: Iterable[str]) -> dict[str, Array]:
    out = {}
    for k, v in sd.items():
        for p in prefixes:
            if k.startswith(p):
                k = k[len(p):]
                break
        out[k] = v
    return out


def _stack(sd: StateDict, template: str, num_layers: int, fn: Callable[[Array], Array]) -> np.ndarray:
    per_layer = []
    for i in range(num_layers):
        key = template.format(i=i)
        if key not in sd:
            raise KeyError(f"missing checkpoint key {key!r}")
        per_layer.append(fn(np.asarray(sd[key])))
    return np.stack(per_layer)


def convert_gpt2(sd: StateDict, cfg: ModelConfig) -> dict[str, Any]:
    """GPT-2 uses Conv1D layers: weights are stored [in, out] already (no
    transpose needed); c_attn fuses q,k,v along the output axis."""
    sd = _strip_prefix(sd, ("transformer.",))
    D, H, HD = cfg.hidden_size, cfg.num_heads, cfg.head_dim_

    def q_of(w):  # [D, 3D] -> [D, H, HD]
        return w[:, :D].reshape(D, H, HD)

    def k_of(w):
        return w[:, D : 2 * D].reshape(D, H, HD)

    def v_of(w):
        return w[:, 2 * D :].reshape(D, H, HD)

    def qb_of(b):  # [3D] -> [H, HD]
        return b[:D].reshape(H, HD)

    def kb_of(b):
        return b[D : 2 * D].reshape(H, HD)

    def vb_of(b):
        return b[2 * D :].reshape(H, HD)

    L = cfg.num_layers
    params = {
        "embed": {
            "wte": np.asarray(sd["wte.weight"]),
            "wpe": np.asarray(sd["wpe.weight"]),
        },
        "final_norm": {
            "scale": np.asarray(sd["ln_f.weight"]),
            "bias": np.asarray(sd["ln_f.bias"]),
        },
        "blocks": {
            "ln1": {
                "scale": _stack(sd, "h.{i}.ln_1.weight", L, lambda x: x),
                "bias": _stack(sd, "h.{i}.ln_1.bias", L, lambda x: x),
            },
            "ln2": {
                "scale": _stack(sd, "h.{i}.ln_2.weight", L, lambda x: x),
                "bias": _stack(sd, "h.{i}.ln_2.bias", L, lambda x: x),
            },
            "attn": {
                "wq": _stack(sd, "h.{i}.attn.c_attn.weight", L, q_of),
                "wk": _stack(sd, "h.{i}.attn.c_attn.weight", L, k_of),
                "wv": _stack(sd, "h.{i}.attn.c_attn.weight", L, v_of),
                "bq": _stack(sd, "h.{i}.attn.c_attn.bias", L, qb_of),
                "bk": _stack(sd, "h.{i}.attn.c_attn.bias", L, kb_of),
                "bv": _stack(sd, "h.{i}.attn.c_attn.bias", L, vb_of),
                "wo": _stack(sd, "h.{i}.attn.c_proj.weight", L, lambda w: w.reshape(H, HD, D)),
                "bo": _stack(sd, "h.{i}.attn.c_proj.bias", L, lambda x: x),
            },
            "mlp": {
                "w_in": _stack(sd, "h.{i}.mlp.c_fc.weight", L, lambda x: x),
                "b_in": _stack(sd, "h.{i}.mlp.c_fc.bias", L, lambda x: x),
                "w_out": _stack(sd, "h.{i}.mlp.c_proj.weight", L, lambda x: x),
                "b_out": _stack(sd, "h.{i}.mlp.c_proj.bias", L, lambda x: x),
            },
        },
    }
    return params


def _stack_experts(
    sd: StateDict, template: str, num_layers: int, num_experts: int,
    fn: Callable[[Array], Array],
) -> np.ndarray:
    """Stack [L, E, ...] from per-layer per-expert keys."""
    per_layer = []
    for i in range(num_layers):
        per_expert = []
        for j in range(num_experts):
            key = template.format(i=i, j=j)
            if key not in sd:
                raise KeyError(f"missing checkpoint key {key!r}")
            per_expert.append(fn(np.asarray(sd[key])))
        per_layer.append(np.stack(per_expert))
    return np.stack(per_layer)


def _split_phi3_fused(sd: StateDict, cfg: ModelConfig) -> StateDict:
    """Phi-3 fuses attention into one ``qkv_proj`` (rows: q | k | v) and the
    gated MLP into one ``gate_up_proj`` (rows: gate | up), both [out, in].
    Split them into the separate llama projection names so convert_llama's
    single mapping serves the family."""
    H, KVH, HD = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    out = dict(sd)
    for i in range(cfg.num_layers):
        qkv = np.asarray(out.pop(f"layers.{i}.self_attn.qkv_proj.weight"))
        q, k, v = np.split(qkv, [H * HD, H * HD + KVH * HD], axis=0)
        out[f"layers.{i}.self_attn.q_proj.weight"] = q
        out[f"layers.{i}.self_attn.k_proj.weight"] = k
        out[f"layers.{i}.self_attn.v_proj.weight"] = v
        gu = np.asarray(out.pop(f"layers.{i}.mlp.gate_up_proj.weight"))
        gate, up = np.split(gu, 2, axis=0)
        out[f"layers.{i}.mlp.gate_proj.weight"] = gate
        out[f"layers.{i}.mlp.up_proj.weight"] = up
    return out


def convert_llama(sd: StateDict, cfg: ModelConfig) -> dict[str, Any]:
    """Llama/TinyLlama/Llama-3 use nn.Linear: stored [out, in] -> transpose.
    With cfg.num_experts > 0 the MLP mapping follows Mixtral's
    ``block_sparse_moe`` layout (gate router + per-expert w1/w2/w3)."""
    sd = _strip_prefix(sd, ("model.",))
    if "layers.0.self_attn.qkv_proj.weight" in sd:  # Phi-3 fused layout
        sd = _split_phi3_fused(sd, cfg)
    D, H, KVH, HD = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    L = cfg.num_layers
    # Gemma's RMSNorm computes with (1 + weight); fold the +1 into the
    # stored scales so the runtime rms_norm stays one implementation.
    norm_of = (lambda w: w + 1.0) if cfg.norm_plus_one else (lambda w: w)
    params = {
        "embed": {"wte": np.asarray(sd["embed_tokens.weight"])},
        "final_norm": {"scale": norm_of(np.asarray(sd["norm.weight"]))},
        "blocks": {
            "ln1": {"scale": _stack(sd, "layers.{i}.input_layernorm.weight", L, norm_of)},
            "ln2": {"scale": _stack(sd, "layers.{i}.post_attention_layernorm.weight", L, norm_of)},
            "attn": {
                "wq": _stack(sd, "layers.{i}.self_attn.q_proj.weight", L, lambda w: w.T.reshape(D, H, HD)),
                "wk": _stack(sd, "layers.{i}.self_attn.k_proj.weight", L, lambda w: w.T.reshape(D, KVH, HD)),
                "wv": _stack(sd, "layers.{i}.self_attn.v_proj.weight", L, lambda w: w.T.reshape(D, KVH, HD)),
                "wo": _stack(sd, "layers.{i}.self_attn.o_proj.weight", L, lambda w: w.T.reshape(H, HD, D)),
                # Qwen2: llama layout plus q/k/v biases (cfg.qkv_bias).
                **(
                    {
                        "bq": _stack(sd, "layers.{i}.self_attn.q_proj.bias", L, lambda b: b.reshape(H, HD)),
                        "bk": _stack(sd, "layers.{i}.self_attn.k_proj.bias", L, lambda b: b.reshape(KVH, HD)),
                        "bv": _stack(sd, "layers.{i}.self_attn.v_proj.bias", L, lambda b: b.reshape(KVH, HD)),
                    }
                    if cfg.qkv_bias
                    else {}
                ),
            },
            "mlp": (
                {
                    # Mixtral: w1 = gate, w3 = up, w2 = down (all [out, in]).
                    "router": _stack(
                        sd, "layers.{i}.block_sparse_moe.gate.weight", L, lambda w: w.T
                    ),
                    "w_gate": _stack_experts(
                        sd, "layers.{i}.block_sparse_moe.experts.{j}.w1.weight",
                        L, cfg.num_experts, lambda w: w.T,
                    ),
                    "w_up": _stack_experts(
                        sd, "layers.{i}.block_sparse_moe.experts.{j}.w3.weight",
                        L, cfg.num_experts, lambda w: w.T,
                    ),
                    "w_down": _stack_experts(
                        sd, "layers.{i}.block_sparse_moe.experts.{j}.w2.weight",
                        L, cfg.num_experts, lambda w: w.T,
                    ),
                }
                if cfg.num_experts > 0
                else {
                    "w_gate": _stack(sd, "layers.{i}.mlp.gate_proj.weight", L, lambda w: w.T),
                    "w_up": _stack(sd, "layers.{i}.mlp.up_proj.weight", L, lambda w: w.T),
                    "w_down": _stack(sd, "layers.{i}.mlp.down_proj.weight", L, lambda w: w.T),
                }
            ),
        },
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = {"w": np.asarray(sd["lm_head.weight"]).T}
        else:
            # Some checkpoints tie even when config says otherwise.
            params["lm_head"] = {"w": np.asarray(sd["embed_tokens.weight"]).T}
    return params


def convert_opt(sd: StateDict, cfg: ModelConfig) -> dict[str, Any]:
    """OPT (the reference's own default model, run_master.py:17): gpt2-layout
    blocks with separate nn.Linear q/k/v/out projections ([out, in] ->
    transpose), ReLU MLP, and a learned position table carrying HF's offset
    of 2 (OPTLearnedPositionalEmbedding) — kept in the table, applied in
    models.model.embed.  Covers the pre-LN, unprojected-embedding variants
    (125m and 1.3b+); 350m's word_embed_proj_dim/post-LN are rejected in
    config_from_hf."""
    sd = _strip_prefix(sd, ("model.decoder.", "decoder.", "model."))
    D, H, HD = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    L = cfg.num_layers

    def w_of(w):  # [D, D] stored [out, in] -> [D(in), H, HD]
        return w.T.reshape(D, H, HD)

    def b_of(b):  # [D] -> [H, HD]
        return b.reshape(H, HD)

    return {
        "embed": {
            "wte": np.asarray(sd["embed_tokens.weight"]),
            "wpe": np.asarray(sd["embed_positions.weight"]),  # rows 0-1 = offset
        },
        "final_norm": {
            "scale": np.asarray(sd["final_layer_norm.weight"]),
            "bias": np.asarray(sd["final_layer_norm.bias"]),
        },
        "blocks": {
            "ln1": {
                "scale": _stack(sd, "layers.{i}.self_attn_layer_norm.weight", L, lambda x: x),
                "bias": _stack(sd, "layers.{i}.self_attn_layer_norm.bias", L, lambda x: x),
            },
            "ln2": {
                "scale": _stack(sd, "layers.{i}.final_layer_norm.weight", L, lambda x: x),
                "bias": _stack(sd, "layers.{i}.final_layer_norm.bias", L, lambda x: x),
            },
            "attn": {
                "wq": _stack(sd, "layers.{i}.self_attn.q_proj.weight", L, w_of),
                "wk": _stack(sd, "layers.{i}.self_attn.k_proj.weight", L, w_of),
                "wv": _stack(sd, "layers.{i}.self_attn.v_proj.weight", L, w_of),
                "bq": _stack(sd, "layers.{i}.self_attn.q_proj.bias", L, b_of),
                "bk": _stack(sd, "layers.{i}.self_attn.k_proj.bias", L, b_of),
                "bv": _stack(sd, "layers.{i}.self_attn.v_proj.bias", L, b_of),
                "wo": _stack(sd, "layers.{i}.self_attn.out_proj.weight", L,
                             lambda w: w.T.reshape(H, HD, D)),
                "bo": _stack(sd, "layers.{i}.self_attn.out_proj.bias", L, lambda x: x),
            },
            "mlp": {
                "w_in": _stack(sd, "layers.{i}.fc1.weight", L, lambda w: w.T),
                "b_in": _stack(sd, "layers.{i}.fc1.bias", L, lambda x: x),
                "w_out": _stack(sd, "layers.{i}.fc2.weight", L, lambda w: w.T),
                "b_out": _stack(sd, "layers.{i}.fc2.bias", L, lambda x: x),
            },
        },
    } | (
        {}
        if cfg.tie_embeddings
        else {
            "lm_head": {
                "w": np.asarray(
                    sd.get("lm_head.weight", sd["embed_tokens.weight"])
                ).T
            }
        }
    )


def convert_neox(sd: StateDict, cfg: ModelConfig) -> dict[str, Any]:
    """GPT-NeoX/Pythia: fused ``query_key_value`` is INTERLEAVED PER HEAD —
    torch weight [(H*3*HD), D] reshapes to (H, 3, HD, D) with q/k/v adjacent
    within each head (GPTNeoXAttention), unlike Phi-3's q|k|v block layout.
    nn.Linear weights are [out, in] -> transpose."""
    sd = _strip_prefix(sd, ("gpt_neox.",))
    D, H, HD = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    L = cfg.num_layers
    if cfg.num_kv_heads != H:
        raise ValueError("neox is multi-head only (num_kv_heads == num_heads)")

    def qkv_w(w, which):  # [(H*3*HD), D] -> [D, H, HD]
        return np.asarray(w).reshape(H, 3, HD, D)[:, which].transpose(2, 0, 1)

    def qkv_b(b, which):  # [(H*3*HD)] -> [H, HD]
        return np.asarray(b).reshape(H, 3, HD)[:, which]

    params = {
        "embed": {"wte": np.asarray(sd["embed_in.weight"])},
        "final_norm": {
            "scale": np.asarray(sd["final_layer_norm.weight"]),
            "bias": np.asarray(sd["final_layer_norm.bias"]),
        },
        "blocks": {
            "ln1": {"scale": _stack(sd, "layers.{i}.input_layernorm.weight", L, lambda w: w),
                    "bias": _stack(sd, "layers.{i}.input_layernorm.bias", L, lambda w: w)},
            "ln2": {"scale": _stack(sd, "layers.{i}.post_attention_layernorm.weight", L, lambda w: w),
                    "bias": _stack(sd, "layers.{i}.post_attention_layernorm.bias", L, lambda w: w)},
            "attn": {
                "wq": _stack(sd, "layers.{i}.attention.query_key_value.weight", L, lambda w: qkv_w(w, 0)),
                "wk": _stack(sd, "layers.{i}.attention.query_key_value.weight", L, lambda w: qkv_w(w, 1)),
                "wv": _stack(sd, "layers.{i}.attention.query_key_value.weight", L, lambda w: qkv_w(w, 2)),
                "bq": _stack(sd, "layers.{i}.attention.query_key_value.bias", L, lambda b: qkv_b(b, 0)),
                "bk": _stack(sd, "layers.{i}.attention.query_key_value.bias", L, lambda b: qkv_b(b, 1)),
                "bv": _stack(sd, "layers.{i}.attention.query_key_value.bias", L, lambda b: qkv_b(b, 2)),
                "wo": _stack(sd, "layers.{i}.attention.dense.weight", L, lambda w: w.T.reshape(H, HD, D)),
                "bo": _stack(sd, "layers.{i}.attention.dense.bias", L, lambda b: b),
            },
            "mlp": {
                "w_in": _stack(sd, "layers.{i}.mlp.dense_h_to_4h.weight", L, lambda w: w.T),
                "b_in": _stack(sd, "layers.{i}.mlp.dense_h_to_4h.bias", L, lambda b: b),
                "w_out": _stack(sd, "layers.{i}.mlp.dense_4h_to_h.weight", L, lambda w: w.T),
                "b_out": _stack(sd, "layers.{i}.mlp.dense_4h_to_h.bias", L, lambda b: b),
            },
        },
        "lm_head": {"w": np.asarray(sd["embed_out.weight"]).T},
    }
    return params


CONVERTERS: dict[str, Callable[[StateDict, ModelConfig], dict[str, Any]]] = {
    "gpt2": convert_gpt2,
    "opt": convert_opt,
    "llama": convert_llama,
    "neox": convert_neox,
}


def convert_state_dict(sd: StateDict, cfg: ModelConfig, dtype: Any = None) -> dict[str, Any]:
    """Convert a HF state dict to our stacked param tree, cast to dtype."""
    import jax.numpy as jnp

    if cfg.family not in CONVERTERS:
        raise ValueError(f"no converter for family {cfg.family!r}")
    tree = CONVERTERS[cfg.family](sd, cfg)
    target = jnp.dtype(dtype or cfg.dtype)
    import jax

    return jax.tree.map(lambda x: jnp.asarray(x, dtype=target), tree)


def _gelu_relu_activation(name: str, what: str) -> str:
    """Map HF activation names onto layers.mlp_gelu's (HF 'gelu' is the
    exact erf form; 'gelu_new'/'gelu_fast' the tanh approximation) — shared
    by the OPT and NeoX config branches so the alias table lives once."""
    table = {"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu",
             "gelu_fast": "gelu"}
    if name not in table:
        raise ValueError(f"unsupported {what} {name!r}")
    return table[name]


def _opt_activation(name: str) -> str:
    return _gelu_relu_activation(name, "OPT activation_function")


def config_from_hf(hf_config: Mapping[str, Any]) -> ModelConfig:
    """Build a ModelConfig from a HF config.json dict (gpt2 or llama-like)."""
    arch = (hf_config.get("architectures") or [""])[0].lower()
    model_type = hf_config.get("model_type", "")
    if model_type == "gpt2" or "gpt2" in arch:
        return ModelConfig(
            family="gpt2",
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["n_embd"],
            intermediate_size=hf_config.get("n_inner") or 4 * hf_config["n_embd"],
            num_layers=hf_config["n_layer"],
            num_heads=hf_config["n_head"],
            num_kv_heads=hf_config["n_head"],
            max_seq_len=hf_config["n_positions"],
            norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=True,
        )
    if model_type == "opt" or "optfor" in arch:
        hidden = hf_config["hidden_size"]
        if not hf_config.get("do_layer_norm_before", True):
            raise ValueError(
                "OPT variant with do_layer_norm_before=False (350m-style "
                "post-LN) is not supported"
            )
        if hf_config.get("word_embed_proj_dim", hidden) != hidden:
            raise ValueError(
                "OPT variant with word_embed_proj_dim != hidden_size "
                "(350m-style embedding projection) is not supported"
            )
        return ModelConfig(
            family="opt",
            vocab_size=hf_config["vocab_size"],
            hidden_size=hidden,
            intermediate_size=hf_config["ffn_dim"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config["num_attention_heads"],
            max_seq_len=hf_config["max_position_embeddings"],
            norm_eps=1e-5,  # torch LayerNorm default; OPTConfig has no knob
            tie_embeddings=hf_config.get("tie_word_embeddings", True),
            # HF "gelu" is the exact erf form; "gelu_new" the tanh approx.
            # Anything else is rejected rather than silently approximated.
            activation=_opt_activation(hf_config.get("activation_function", "relu")),
        )
    if model_type == "qwen2" or "qwen2for" in arch:
        # Qwen2 = llama layout + q/k/v biases.  Sliding-window attention is
        # off for the released dense checkpoints' default configs; reject a
        # config that actually enables it rather than silently attending
        # globally.
        if hf_config.get("use_sliding_window", False):
            raise ValueError(
                "Qwen2 with use_sliding_window=True is not supported "
                "(global attention only)"
            )
        if hf_config.get("rope_scaling"):
            raise ValueError(
                "qwen2 rope_scaling is not supported (plain RoPE only)"
            )
        return ModelConfig(
            family="llama",
            qkv_bias=True,
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get(
                "num_key_value_heads", hf_config["num_attention_heads"]
            ),
            max_seq_len=hf_config.get("max_position_embeddings", 32768),
            rope_theta=hf_config.get("rope_theta", 1e6),
            norm_eps=hf_config.get("rms_norm_eps", 1e-6),
            tie_embeddings=hf_config.get("tie_word_embeddings", False),
        )
    if model_type == "gemma" or "gemmafor" in arch:
        # Gemma-1 = llama layout with GeGLU, (1+w) RMSNorm (folded at
        # convert), sqrt(hidden) embedding scale, explicit head_dim, tied
        # embeddings.  Gemma-2 (model_type "gemma2": logit softcapping,
        # alternating local attention) is a different architecture —
        # rejected by falling through to the ValueError below.
        if hf_config.get("rope_scaling"):
            raise ValueError(
                "gemma rope_scaling is not supported (plain RoPE only)"
            )
        act = hf_config.get("hidden_activation") or hf_config.get("hidden_act")
        if act not in (None, "gelu_pytorch_tanh"):
            # HF honors an explicit exact-erf "gelu" here; reject rather
            # than silently approximate (same convention as _opt_activation).
            raise ValueError(
                f"gemma hidden_activation {act!r} is not supported "
                "(gelu_pytorch_tanh only)"
            )
        hidden = hf_config["hidden_size"]
        return ModelConfig(
            family="llama",
            gate_act="gelu_tanh",
            qkv_bias=bool(hf_config.get("attention_bias", False)),
            norm_plus_one=True,
            embed_scale=float(hidden) ** 0.5,
            vocab_size=hf_config["vocab_size"],
            hidden_size=hidden,
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get(
                "num_key_value_heads", hf_config["num_attention_heads"]
            ),
            head_dim=hf_config.get("head_dim"),
            max_seq_len=hf_config.get("max_position_embeddings", 8192),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            norm_eps=hf_config.get("rms_norm_eps", 1e-6),
            tie_embeddings=hf_config.get("tie_word_embeddings", True),
        )
    if model_type == "mistral" or "mistralfor" in arch:
        # Mistral = llama layout (identical weight names; convert_llama
        # applies) + sliding-window attention.  v0.1 ships window 4096;
        # v0.2+ releases set sliding_window null (global attention) — both
        # map cleanly.  window >= max_position_embeddings degenerates to
        # global causal; keep None there so the mask stays the cheap one.
        if hf_config.get("rope_scaling"):
            raise ValueError(
                "mistral rope_scaling is not supported (plain RoPE only)"
            )
        window = hf_config.get("sliding_window")
        max_len = hf_config.get("max_position_embeddings", 32768)
        if window is not None and window >= max_len:
            window = None
        return ModelConfig(
            family="llama",
            sliding_window=window,
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get(
                "num_key_value_heads", hf_config["num_attention_heads"]
            ),
            head_dim=hf_config.get("head_dim"),
            max_seq_len=max_len,
            rope_theta=hf_config.get("rope_theta", 10000.0),
            norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            tie_embeddings=hf_config.get("tie_word_embeddings", False),
        )
    if model_type == "gpt_neox" or "gptneoxfor" in arch:
        # GPT-NeoX / Pythia: LayerNorm + partial rotary + parallel residual
        # (its own block flavour and converter — models.model.neox_block,
        # convert_neox).
        if hf_config.get("tie_word_embeddings", False):
            # init_params/unembed treat neox as untied (embed_out); a tied
            # checkpoint would carry a dead lm_head tensor in HBM.
            raise ValueError("tied-embedding gpt_neox is not supported")
        if hf_config.get("rope_scaling"):
            raise ValueError(
                "gpt_neox rope_scaling is not supported (plain rotary only)"
            )
        if hf_config.get("attention_bias", True) is False:
            raise ValueError("gpt_neox without attention biases unsupported")
        return ModelConfig(
            family="neox",
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config["num_attention_heads"],
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            rope_theta=float(hf_config.get("rotary_emb_base", 10000)),
            rotary_pct=float(hf_config.get("rotary_pct", 1.0)),
            parallel_residual=bool(
                hf_config.get("use_parallel_residual", True)
            ),
            norm_eps=hf_config.get("layer_norm_eps", 1e-5),
            tie_embeddings=False,
            activation=_gelu_relu_activation(
                hf_config.get("hidden_act", "gelu"), "neox hidden_act"
            ),
        )
    if model_type == "phi3" or "phi3for" in arch:
        # Phi-3 = llama layout with fused qkv/gate_up projections (split at
        # convert) + sliding-window attention.  The 128k "longrope" variants
        # carry rope_scaling — a different position scheme; reject rather
        # than silently serve wrong positions.
        if hf_config.get("rope_scaling"):
            raise ValueError(
                "phi3 rope_scaling (longrope 128k variants) is not supported"
            )
        pr = hf_config.get("partial_rotary_factor", 1.0) or 1.0
        if pr != 1.0:
            raise ValueError(
                f"phi3 partial_rotary_factor {pr} is not supported (full "
                "rotary only)"
            )
        window = hf_config.get("sliding_window")
        max_len = hf_config.get("max_position_embeddings", 4096)
        if window is not None and window >= max_len:
            window = None
        return ModelConfig(
            family="llama",
            sliding_window=window,
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get(
                "num_key_value_heads", hf_config["num_attention_heads"]
            ),
            max_seq_len=max_len,
            rope_theta=hf_config.get("rope_theta", 10000.0),
            norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            tie_embeddings=hf_config.get("tie_word_embeddings", False),
        )
    if model_type in ("llama", "mixtral") or "llama" in arch or "mixtral" in arch:
        rs = hf_config.get("rope_scaling") or {}
        rope_kw = {}
        if rs:
            # Llama-3.1/3.2's "llama3" frequency rescale is implemented
            # (models/layers.rope_frequencies); any other scheme (linear,
            # dynamic NTK, yarn) would silently serve wrong positions.
            rtype = rs.get("rope_type") or rs.get("type")
            if rtype != "llama3":
                raise ValueError(
                    f"unsupported rope_scaling type {rtype!r} "
                    "(llama3 only)"
                )
            if "factor" not in rs:
                raise ValueError("llama3 rope_scaling needs a 'factor'")
            low = float(rs.get("low_freq_factor", 1.0))
            high = float(rs.get("high_freq_factor", 4.0))
            if high <= low:
                # The smooth band divides by (high - low): equal factors
                # would serve NaN frequencies, inverted ones a reversed
                # ramp.  HF merely warns here; reject loudly instead.
                raise ValueError(
                    f"llama3 rope_scaling needs high_freq_factor ({high}) "
                    f"> low_freq_factor ({low})"
                )
            rope_kw = dict(
                rope_scaling_factor=float(rs["factor"]),
                rope_low_freq_factor=low,
                rope_high_freq_factor=high,
                rope_original_max_len=int(
                    rs.get("original_max_position_embeddings", 8192)
                ),
            )
        return ModelConfig(
            family="llama",
            **rope_kw,
            # Community fine-tunes sometimes enable projection biases on the
            # llama architecture; converting them without the bias leaves
            # would be silently wrong logits.
            qkv_bias=bool(hf_config.get("attention_bias", False)),
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get("num_key_value_heads", hf_config["num_attention_heads"]),
            max_seq_len=hf_config.get("max_position_embeddings", 4096),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            tie_embeddings=hf_config.get("tie_word_embeddings", False),
            # Mixtral MoE knobs (0 experts -> dense llama).
            num_experts=hf_config.get("num_local_experts", 0) or 0,
            num_experts_per_token=hf_config.get("num_experts_per_tok", 2) or 2,
        )
    raise ValueError(f"unsupported HF model_type {model_type!r}")
