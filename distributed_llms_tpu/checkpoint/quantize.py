"""Weight quantization: int8 and packed-int4, blockwise absmax scales.

Covers the reference's designed-but-unlanded quantization module
(snippets.md:675-833, plan.md:438-456): its scheme was per-tensor absmax
int8 (scale = absmax/127) with a 4-bit packed variant.  Here the same absmax
scheme is *blockwise* along the LAST axis of each weight — for most weights
that is the reduction axis, but for wq/wk/wv ([D, H, hd]) it is the output
head dim (finer-grained scales lose less precision, and blocks align with TP
shards so scales never straddle a shard boundary — SURVEY §7 hard part 6),
implemented as pure jnp ops.

Policy: only matmul weights (ndim >= 2) quantize; norms/biases stay in the
model dtype.  A quantized tree stores ``QuantizedTensor`` leaves that
``dequantize_tree`` restores — or, on TPU, that the fused dequant-matmul
kernel (ops/quant_matmul.py) consumes directly without ever writing the
full-precision weights back to HBM.

int4 pack layout: two values per byte along ``pack_axis`` — the weight's
*reduction* axis (adjacent rows k, k+1 share a byte; low nibble = even row).
Row-packing (rather than packing along the last axis) is what lets the TPU
kernel unpack with a sublane interleave, which Mosaic supports for any
width; scales always run along the LAST axis regardless.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class QuantizedTensor:
    """Blockwise-quantized array.

    data: int8; for int4, two values packed per byte along ``pack_axis``
    (low nibble = even index, high nibble = odd index along that axis).
    scale: float32, shape = unpacked shape with the last axis divided into
    blocks.
    pack_axis: negative axis index the int4 pairs run along — negative so a
    leading stacked-layer axis can be sliced off (lax.scan) without
    invalidating it.  Unused for int8.
    """

    data: jax.Array
    scale: jax.Array
    bits: int
    orig_shape: tuple[int, ...]
    pack_axis: int = -2

    @property
    def unpacked_shape(self) -> tuple[int, ...]:
        """Shape of the dequantized array — derived from data (NOT
        orig_shape, which goes stale on stacked-layer slices)."""
        shape = list(self.data.shape)
        if self.bits == 4:
            shape[self.pack_axis] *= 2
        return tuple(shape)


# data/scale are pytree children; the rest is static metadata.
jax.tree_util.register_dataclass(
    QuantizedTensor,
    data_fields=["data", "scale"],
    meta_fields=["bits", "orig_shape", "pack_axis"],
)


def quantize(
    x: jax.Array, bits: int = 8, block: int = 128, pack_axis: int = -2
) -> QuantizedTensor:
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    orig_shape = tuple(x.shape)
    block = min(block, x.shape[-1])
    if x.shape[-1] % block:
        # shrink to the largest common divisor so any width quantizes
        import math

        block = math.gcd(x.shape[-1], block)
    n = x.shape[-1]
    xb = jnp.asarray(x, jnp.float32).reshape(*x.shape[:-1], n // block, block)
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(orig_shape)
    scale = scale[..., 0]  # [..., n_blocks]
    if bits == 4:
        if not -x.ndim <= pack_axis < 0:
            raise ValueError(f"pack_axis must be negative, got {pack_axis}")
        a = x.ndim + pack_axis
        if x.shape[a] % 2:
            raise ValueError(
                f"int4 packing requires even size along pack_axis {pack_axis} "
                f"(shape {orig_shape})"
            )
        idx_lo = [slice(None)] * x.ndim
        idx_hi = [slice(None)] * x.ndim
        idx_lo[a] = slice(0, None, 2)
        idx_hi[a] = slice(1, None, 2)
        lo = q[tuple(idx_lo)] & 0x0F
        hi = (q[tuple(idx_hi)] & 0x0F) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedTensor(
        data=q, scale=scale, bits=bits, orig_shape=orig_shape, pack_axis=pack_axis
    )


def dequantize(qt: QuantizedTensor, dtype: Any = jnp.float32) -> jax.Array:
    """Shapes derive from data/scale, NOT orig_shape: a per-layer slice of a
    stacked [L, ...] QuantizedTensor (what lax.scan hands the decoder-block
    body when serving quantized weights) carries stale orig_shape metadata
    but self-consistent data/scale."""
    q = qt.data
    if qt.bits == 4:
        a = q.ndim + qt.pack_axis
        lo = (q << 4).astype(jnp.int8) >> 4  # sign-extend low nibble
        hi = q >> 4  # arithmetic shift sign-extends high nibble
        shape = list(q.shape)
        shape[a] *= 2
        q = jnp.stack([lo, hi], axis=a + 1).reshape(shape)
    qf = q.astype(jnp.float32)
    n = q.shape[-1]
    n_blocks = qt.scale.shape[-1]
    block = n // n_blocks
    qb = qf.reshape(*q.shape[:-1], n_blocks, block)
    out = qb * qt.scale[..., None]
    return out.reshape(q.shape).astype(dtype)


# Weights whose trailing TWO axes are output axes ([D, H, hd]): their
# reduction axis sits at -3, everything else contracts at -2.
_PACK_AXIS_BY_NAME = {"wq": -3, "wk": -3, "wv": -3}


# Bias leaves by exact name — matched explicitly (not by "b" prefix) so a
# future weight whose name starts with "b" is not silently left unquantized.
_BIAS_NAMES = frozenset({"bq", "bk", "bv", "bo", "b_in", "b_out", "b_gate", "b_up", "b_down"})


def _should_quantize(path: str, x: Any) -> bool:
    if not hasattr(x, "ndim") or x.ndim < 2:
        return False
    leaf = path.split("/")[-1]
    if "norm" in path or "ln" in path.split("/")[-2:][0]:
        return False
    if leaf in _BIAS_NAMES:
        return False
    return True


def leaf_plan(path: str, x: Any) -> tuple[bool, int]:
    """(quantize?, pack_axis) for a named leaf — the single source of truth
    for which leaves quantize and how they pack, shared by quantize_tree
    and streaming builders (bench.py generates-and-quantizes on device leaf
    by leaf and must make the exact decisions the serving path makes)."""
    if not _should_quantize(path, x):
        return False, -2
    return True, _PACK_AXIS_BY_NAME.get(path.split("/")[-1], -2)


def quantize_tree(params: Any, bits: int = 8, block: int = 128) -> Any:
    """Quantize matmul weights in a param tree; other leaves pass through."""

    def visit(path, x):
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        should, pack_axis = leaf_plan(key, x)
        if should:
            return quantize(x, bits=bits, block=block, pack_axis=pack_axis)
        return x

    return jax.tree_util.tree_map_with_path(visit, params)


def dequantize_tree(params: Any, dtype: Any = None) -> Any:
    def visit(x):
        if isinstance(x, QuantizedTensor):
            return dequantize(x, dtype or jnp.float32)
        return x

    return jax.tree.map(
        visit, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


# ---------------------------------------------------------------------------
# KV-cache quantization (int8 KV pages, runtime/batcher.py PagePool tiering)
#
# The same absmax scheme as quantize()/dequantize() above, specialised to the
# KV layout: one float32 scale per head-dim VECTOR (block == head_dim along
# the last axis — the finest block the weight path supports), so the decode
# kernel can fold the scale into the attention contraction itself:
# score = (q . k_int8) * k_scale and out = sum((p * v_scale) . v_int8) —
# per-(slot, head) scales sit OUTSIDE the head-dim dot product, which is what
# lets ops/decode_attn.py read the pool at 1 byte/elem and never materialize
# a dequantized page in HBM.
# ---------------------------------------------------------------------------

KV_QMAX = 127.0  # int8 absmax grid, the quantize() scheme's 8-bit constant


def kv_quantize(x: "jax.Array") -> tuple["jax.Array", "jax.Array"]:
    """Quantize KV vectors to int8 with one absmax scale per trailing
    head-dim vector.  ``x`` is [..., HD]; returns (data int8 [..., HD],
    scale float32 [...]).  Exact round-trip property: quantizing the
    output of :func:`kv_dequantize` reproduces the identical int8 data and
    scales (the dequantized absmax IS qmax * scale), which is what makes
    re-quantizing a dequantized handoff payload byte-stable."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / KV_QMAX, 1.0)
    data = jnp.clip(
        jnp.round(xf / scale[..., None]), -KV_QMAX, KV_QMAX
    ).astype(jnp.int8)
    return data, scale


def kv_dequantize(data: "jax.Array", scale: "jax.Array", dtype: Any) -> "jax.Array":
    """Restore int8 KV vectors: ``f32(data) * scale`` cast to ``dtype`` —
    the exact numerics :func:`dequantize` uses, and the reference the
    fused decode-attention int8 leg must match."""
    return (data.astype(jnp.float32) * scale[..., None]).astype(dtype)


def tree_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.data.size * leaf.data.dtype.itemsize
            total += leaf.scale.size * leaf.scale.dtype.itemsize
        else:
            total += leaf.size * np.dtype(leaf.dtype).itemsize
    return total
