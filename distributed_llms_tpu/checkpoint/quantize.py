"""Weight quantization: int8 and packed-int4, blockwise absmax scales.

Covers the reference's designed-but-unlanded quantization module
(snippets.md:675-833, plan.md:438-456): its scheme was per-tensor absmax
int8 (scale = absmax/127) with a 4-bit packed variant.  Here the same absmax
scheme is *blockwise* along the reduction axis (finer-grained scales lose
less precision, and blocks align with TP shards so scales never straddle a
shard boundary — SURVEY §7 hard part 6), implemented as pure jnp ops.

Policy: only matmul weights (ndim >= 2) quantize; norms/biases stay in the
model dtype.  A quantized tree stores ``QuantizedTensor`` leaves that
``dequantize_tree`` restores (host side or on-device — XLA fuses the
dequant multiply into the consumer matmul).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class QuantizedTensor:
    """Blockwise-quantized array.

    data: int8; for int4, two values packed per byte along the LAST axis
    (low nibble = even index, high nibble = odd index).
    scale: float32, shape = data.shape with the last axis divided by blocks.
    """

    data: jax.Array
    scale: jax.Array
    bits: int
    orig_shape: tuple[int, ...]


# data/scale are pytree children; bits/orig_shape are static metadata.
jax.tree_util.register_dataclass(
    QuantizedTensor, data_fields=["data", "scale"], meta_fields=["bits", "orig_shape"]
)


def _block_reshape(x: jnp.ndarray, block: int) -> tuple[jnp.ndarray, int]:
    """[..., N] -> [..., N//block, block]; requires divisibility."""
    n = x.shape[-1]
    if n % block:
        raise ValueError(f"last axis {n} not divisible by quant block {block}")
    return x.reshape(*x.shape[:-1], n // block, block), n // block


def quantize(x: jax.Array, bits: int = 8, block: int = 128) -> QuantizedTensor:
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    orig_shape = tuple(x.shape)
    block = min(block, x.shape[-1])
    if x.shape[-1] % block:
        # shrink to the largest common divisor so any width quantizes
        import math

        block = math.gcd(x.shape[-1], block)
    xb, _ = _block_reshape(jnp.asarray(x, jnp.float32), block)
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(orig_shape)
    scale = scale[..., 0]  # [..., n_blocks]
    if bits == 4:
        # pack pairs along the last axis: [..., N] -> [..., N//2]
        if orig_shape[-1] % 2:
            raise ValueError("int4 packing requires even last axis")
        lo = q[..., 0::2] & 0x0F
        hi = (q[..., 1::2] & 0x0F) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedTensor(data=q, scale=scale, bits=bits, orig_shape=orig_shape)


def dequantize(qt: QuantizedTensor, dtype: Any = jnp.float32) -> jax.Array:
    """Shapes derive from data/scale, NOT orig_shape: a per-layer slice of a
    stacked [L, ...] QuantizedTensor (what lax.scan hands the decoder-block
    body when serving quantized weights) carries stale orig_shape metadata
    but self-consistent data/scale."""
    q = qt.data
    if qt.bits == 4:
        lo = (q << 4).astype(jnp.int8) >> 4  # sign-extend low nibble
        hi = q >> 4  # arithmetic shift sign-extends high nibble
        q = jnp.stack([lo, hi], axis=-1).reshape(*q.shape[:-1], q.shape[-1] * 2)
    qf = q.astype(jnp.float32)
    n = q.shape[-1]
    n_blocks = qt.scale.shape[-1]
    block = n // n_blocks
    qb = qf.reshape(*q.shape[:-1], n_blocks, block)
    out = qb * qt.scale[..., None]
    return out.reshape(q.shape).astype(dtype)


def _should_quantize(path: str, x: Any) -> bool:
    if not hasattr(x, "ndim") or x.ndim < 2:
        return False
    if "norm" in path or "ln" in path.split("/")[-2:][0]:
        return False
    return True


def quantize_tree(params: Any, bits: int = 8, block: int = 128) -> Any:
    """Quantize matmul weights in a param tree; other leaves pass through."""

    def visit(path, x):
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        if _should_quantize(key, x):
            return quantize(x, bits=bits, block=block)
        return x

    return jax.tree_util.tree_map_with_path(visit, params)


def dequantize_tree(params: Any, dtype: Any = None) -> Any:
    def visit(x):
        if isinstance(x, QuantizedTensor):
            return dequantize(x, dtype or jnp.float32)
        return x

    return jax.tree.map(
        visit, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def tree_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.data.size * leaf.data.dtype.itemsize
            total += leaf.scale.size * leaf.scale.dtype.itemsize
        else:
            total += leaf.size * np.dtype(leaf.dtype).itemsize
    return total
