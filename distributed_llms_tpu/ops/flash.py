"""Flash attention: fused blockwise attention as a Pallas TPU kernel.

Net-new relative to the reference, whose only compute was a placeholder
per-parameter ``torch.matmul`` (src/worker/node.py:24-32).  This is the
"native tier" of the new stack (SURVEY §2 intro): the hot O(T²) op written
directly against the TPU memory hierarchy instead of relying on XLA fusion.

Design (standard flash-attention recurrence, TPU-tiled):

- grid ``(B, H, num_q_blocks, num_k_blocks)``; the K-block axis is innermost,
  so VMEM scratch accumulators (running max / numerator / denominator)
  persist across K blocks of one Q block while ``pallas_call`` double-buffers
  the K/V block DMAs;
- each step computes a ``[block_q, block_k]`` score tile on the MXU in f32
  and folds it into the online softmax;
- grouped-query attention is native: the K/V ``BlockSpec`` index maps divide
  the query-head grid index by ``q_per_kv``, so K/V blocks are fetched once
  per KV head — queries in the same group reuse them;
- **static-causal fast path** (the training / prefill hot path, detected when
  positions and validity are the standard contiguous layout): above-diagonal
  tiles are skipped *and their K/V index maps are clamped to the diagonal*,
  so the dead tiles issue no new DMA; fully-visible tiles skip masking
  entirely; only diagonal tiles pay for the iota mask;
- **dynamic path** (ragged prompts, padded KV caches): per-tile masks are
  built from global position / validity vectors, and fully-masked tiles skip
  their MXU work via ``pl.when``.

Differentiation: the kernel carries a ``custom_vjp`` whose backward pass
recomputes attention densely (flash-checkpoint style — nothing but q/k/v is
saved from the forward).  Gradients therefore cost O(T²) memory in the
backward only; a fused backward kernel can replace it without touching
callers.  Interpret mode runs automatically off-TPU so the CPU fake-mesh
tests exercise the same path.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import jaxcompat

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# Shared online-softmax accumulate
# ---------------------------------------------------------------------------

def _accumulate(s, v, acc_ref, m_ref, l_ref):
    """Fold one masked f32 score tile ``s`` [bq, bk] and its V block into the
    running (acc, m, l) scratch state."""
    m_prev = m_ref[:, 0]  # [bq]
    l_prev = l_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    # Rows with every key masked so far sit at finite finfo.min; using that as
    # the softmax shift would make masked entries exp(0)=1.  Shift by 0
    # instead so they underflow to exp(_NEG_INF)=0.
    safe = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, m_new)
    p = jnp.exp(s - safe[:, None])  # [bq, bk] f32
    alpha = jnp.exp(m_prev - safe)  # 0 while unseeded
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:, 0] = m_new
    l_ref[:, 0] = l_new


def _scores(q, k, scale):
    return (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
    )


def _finish(o_ref, acc_ref, l_ref):
    l = jnp.maximum(l_ref[:, 0], 1e-37)
    o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Static-causal kernel (training / prefill hot path)
# ---------------------------------------------------------------------------

def _kernel_static(
    q_ref,  # [1, bq, D]
    k_ref,  # [1, bk, D]
    v_ref,  # [1, bk, D]
    o_ref,  # [1, bq, D]
    acc_ref,  # [bq, D] f32
    m_ref,  # [bq, 128] f32
    l_ref,  # [bq, 128] f32
    *,
    scale: float,
    num_k_blocks: int,
    block_q: int,
    block_k: int,
    window: int | None = None,  # sliding window: keys in (row - window, row]
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Tile classes: fully visible (every (row, col) pair inside the causal —
    # and, when windowed, the window — band), boundary (crosses the diagonal
    # or the window's lower edge: iota-masked), dead (fully outside; index
    # maps clamp its K/V fetch so it costs no DMA and no MXU work).
    visible = k_start + block_k - 1 <= q_start
    dead = k_start > q_start + block_q - 1  # above the diagonal
    if window is not None:
        # Fully visible additionally needs every col > every row - window;
        # fully below the window's lower edge is dead.
        visible = jnp.logical_and(
            visible, k_start > q_start + block_q - 1 - window
        )
        dead = jnp.logical_or(dead, k_start + block_k - 1 <= q_start - window)
    boundary = jnp.logical_not(jnp.logical_or(visible, dead))

    @pl.when(visible)
    def _full():
        s = _scores(q_ref[0], k_ref[0], scale)
        _accumulate(s, v_ref[0], acc_ref, m_ref, l_ref)

    @pl.when(boundary)
    def _edge():
        s = _scores(q_ref[0], k_ref[0], scale)
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        keep = cols <= rows
        if window is not None:
            keep = jnp.logical_and(keep, cols > rows - window)
        s = jnp.where(keep, s, _NEG_INF)
        _accumulate(s, v_ref[0], acc_ref, m_ref, l_ref)

    @pl.when(ki == num_k_blocks - 1)
    def _done():
        _finish(o_ref, acc_ref, l_ref)


# ---------------------------------------------------------------------------
# Dynamic kernel (ragged prompts / padded caches / explicit validity)
# ---------------------------------------------------------------------------

def _kernel_dynamic(
    qpos_ref,  # [1, 1, bq] int32 — global positions of this Q block's rows
    kpos_ref,  # [1, 1, bk] int32 — global positions of this K block's slots
    kval_ref,  # [1, 1, bk] int32 — 1 where the K slot is a real/valid key
    q_ref,  # [1, bq, D]
    k_ref,  # [1, bk, D]
    v_ref,  # [1, bk, D]
    o_ref,  # [1, bq, D]
    acc_ref,
    m_ref,
    l_ref,
    *,
    causal: bool,
    scale: float,
    num_k_blocks: int,
    window: int | None = None,  # sliding window in POSITION space
):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qp = qpos_ref[0, 0, :]  # [bq]
    kp = kpos_ref[0, 0, :]  # [bk]
    kv = kval_ref[0, 0, :]  # [bk]
    mask = (kv != 0)[None, :]  # [1, bk]
    if causal:
        mask = jnp.logical_and(mask, kp[None, :] <= qp[:, None])  # [bq, bk]
    if window is not None:
        # layers.and_window semantics: keys at positions (p - window, p].
        mask = jnp.logical_and(mask, kp[None, :] > qp[:, None] - window)
    mask = jnp.broadcast_to(mask, (qp.shape[0], kp.shape[0]))

    @pl.when(jnp.any(mask))
    def _block():
        s = jnp.where(mask, _scores(q_ref[0], k_ref[0], scale), _NEG_INF)
        _accumulate(s, v_ref[0], acc_ref, m_ref, l_ref)

    @pl.when(ki == num_k_blocks - 1)
    def _done():
        _finish(o_ref, acc_ref, l_ref)


# ---------------------------------------------------------------------------
# Host-side wrapper
# ---------------------------------------------------------------------------

def _pad_to(x: jax.Array, axis: int, mult: int, value) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash(q, k, v, q_positions, k_positions, k_valid, causal, block_q, block_k, interpret, window):
    # Inside shard_map (e.g. the Ulysses body) the inputs carry varying
    # manual axes (vma); the output must declare the same set.
    vma = frozenset().union(*(jaxcompat.vma_of(x) for x in (q, k, v)))
    if interpret and vma:
        # The Pallas HLO *interpreter* (off-TPU test path) loses vma on its
        # internal dynamic_slices; run the numerically-identical dense
        # reference there.  Real TPU lowering takes the kernel.
        return _dense_reference(
            q, k, v, q_positions, k_positions, k_valid, causal, window
        )
    b, tq, h, d = q.shape
    s = k.shape[1]
    kvh = k.shape[2]
    assert h % kvh == 0, (h, kvh)
    g = h // kvh
    scale = d**-0.5

    # Q tile: sublane dim of the score tile (min 8 rows); K tile: lane dim
    # (pad short sequences up to one 128-lane tile).
    bq = min(block_q, _round_up(tq, 8))
    bk = min(block_k, _round_up(s, 128))

    # The hot path: standard contiguous positions, every key slot valid, and
    # query rows aligned with key slots (training forward / full prefill).
    static_causal = (
        causal and q_positions is None and k_positions is None
        and k_valid is None and tq == s
    )

    # [B, H, T, D] layout: contiguous [T, D] tiles per head.
    qt = _pad_to(q.transpose(0, 2, 1, 3), 2, bq, 0)
    kt = _pad_to(k.transpose(0, 2, 1, 3), 2, bk, 0)
    vt = _pad_to(v.transpose(0, 2, 1, 3), 2, bk, 0)
    tq_p, s_p = qt.shape[2], kt.shape[2]
    nq, nk = tq_p // bq, s_p // bk
    grid = (b, h, nq, nk)
    scratch = [
        pltpu.VMEM((bq, d), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
    ]
    q_spec = pl.BlockSpec((1, bq, d), lambda bi, hi, qi, ki: (bi * h + hi, qi, 0))
    o_spec = pl.BlockSpec((1, bq, d), lambda bi, hi, qi, ki: (bi * h + hi, qi, 0))
    out_shape = jaxcompat.shape_dtype_struct((b * h, tq_p, d), q.dtype, vma=vma)
    args = (
        qt.reshape(b * h, tq_p, d),
        kt.reshape(b * kvh, s_p, d),
        vt.reshape(b * kvh, s_p, d),
    )

    if static_causal:
        # Clamp dead tiles' K/V fetches into the live band (above the
        # diagonal, and — when windowed — below the window's lower edge):
        # repeated index => the pipeline issues no new DMA for skipped
        # tiles, so a windowed prefill's work scales with the window, not
        # the sequence.
        def kv_index(bi, hi, qi, ki):
            last_needed = jax.lax.div(qi * bq + bq - 1, bk)
            kk = jnp.minimum(ki, last_needed)
            if window is not None:
                first_col = jnp.maximum(qi * bq - (window - 1), 0)
                kk = jnp.maximum(kk, jax.lax.div(first_col, bk))
            return (bi * kvh + hi // g, kk, 0)

        out = pl.pallas_call(
            functools.partial(
                _kernel_static, scale=scale, num_k_blocks=nk,
                block_q=bq, block_k=bk, window=window,
            ),
            grid=grid,
            in_specs=[
                q_spec,
                pl.BlockSpec((1, bk, d), kv_index),
                pl.BlockSpec((1, bk, d), kv_index),
            ],
            out_specs=o_spec,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
        )(*args)
    else:
        # Freshly created defaults are not device-varying over any manual
        # mesh axis; align them with q/k/v so vma tracking stays consistent
        # inside shard_map bodies (same trick as ops/ring.py).
        align = (
            (lambda x: jaxcompat.pcast(x, tuple(vma), to="varying")) if vma
            else (lambda x: x)
        )
        if q_positions is None:
            q_positions = align(
                jnp.broadcast_to(jnp.arange(tq, dtype=jnp.int32), (b, tq))
            )
        if k_positions is None:
            k_positions = align(
                jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
            )
        kval = (
            align(jnp.ones((b, s), jnp.int32))
            if k_valid is None
            else k_valid.astype(jnp.int32)
        )
        # Padded q rows get position -1 (causal-masks every key -> zero
        # output); padded k slots get valid=0.  Vectors go in as [B*n, 1, blk]
        # (block dims equal array dims => satisfies the (8,128) tiling rule
        # without replicating across sublanes).
        qpos = _pad_to(q_positions.astype(jnp.int32), 1, bq, -1)
        kpos = _pad_to(k_positions.astype(jnp.int32), 1, bk, 2**30)
        kval = _pad_to(kval, 1, bk, 0)
        out = pl.pallas_call(
            functools.partial(
                _kernel_dynamic, causal=causal, scale=scale, num_k_blocks=nk,
                window=window,
            ),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq), lambda bi, hi, qi, ki: (bi * nq + qi, 0, 0)),
                pl.BlockSpec((1, 1, bk), lambda bi, hi, qi, ki: (bi * nk + ki, 0, 0)),
                pl.BlockSpec((1, 1, bk), lambda bi, hi, qi, ki: (bi * nk + ki, 0, 0)),
                q_spec,
                pl.BlockSpec((1, bk, d), lambda bi, hi, qi, ki: (bi * kvh + hi // g, ki, 0)),
                pl.BlockSpec((1, bk, d), lambda bi, hi, qi, ki: (bi * kvh + hi // g, ki, 0)),
            ],
            out_specs=o_spec,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
        )(
            qpos.reshape(b * nq, 1, bq),
            kpos.reshape(b * nk, 1, bk),
            kval.reshape(b * nk, 1, bk),
            *args,
        )
    out = out.reshape(b, h, tq_p, d)[:, :, :tq]
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Autodiff: dense-recompute backward (flash-checkpoint style)
# ---------------------------------------------------------------------------

def _dense_reference(q, k, v, q_positions, k_positions, k_valid, causal,
                     window=None):
    """Same math and masking semantics as the kernel, in plain XLA ops — the
    VJP target for the backward pass."""
    b, tq, h, d = q.shape
    s, kvh = k.shape[1], k.shape[2]
    g = h // kvh
    if g > 1:
        k = jnp.broadcast_to(k[:, :, :, None, :], (b, s, kvh, g, d)).reshape(b, s, h, d)
        v = jnp.broadcast_to(v[:, :, :, None, :], (b, s, kvh, g, d)).reshape(b, s, h, d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * (d**-0.5)
    qp = (
        jnp.broadcast_to(jnp.arange(tq, dtype=jnp.int32), (b, tq))
        if q_positions is None
        else q_positions
    )
    kp = (
        jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        if k_positions is None
        else k_positions
    )
    mask = jnp.ones((b, 1, 1, s), bool) if not causal else (
        kp[:, None, None, :] <= qp[:, None, :, None]
    )
    if k_valid is not None:
        mask = jnp.logical_and(mask, k_valid[:, None, None, :])
    if window is not None:
        # layers.and_window semantics: keys at positions (p - window, p].
        mask = jnp.logical_and(
            mask, kp[:, None, None, :] > qp[:, None, :, None] - window
        )
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def _flash_fwd(q, k, v, q_positions, k_positions, k_valid, causal, block_q, block_k, interpret, window):
    out = _flash(
        q, k, v, q_positions, k_positions, k_valid, causal, block_q, block_k,
        interpret, window,
    )
    return out, (q, k, v, q_positions, k_positions, k_valid)


def _flash_bwd(causal, block_q, block_k, interpret, window, res, g):
    q, k, v, q_positions, k_positions, k_valid = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _dense_reference(
            q_, k_, v_, q_positions, k_positions, k_valid, causal, window
        ),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    zero = lambda x: None if x is None else np.zeros(x.shape, jax.dtypes.float0)
    return dq, dk, dv, zero(q_positions), zero(k_positions), zero(k_valid)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "window"),
)
def flash_attention(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, S, KVH, D]  (KVH divides H — GQA-aware)
    v: jax.Array,  # [B, S, KVH, D]
    q_positions: jax.Array | None = None,  # [B, Tq] int32 global positions
    k_positions: jax.Array | None = None,  # [B, S] int32 global positions
    k_valid: jax.Array | None = None,  # [B, S] bool — False masks the slot
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
    window: int | None = None,  # sliding window (layers.and_window
    #   semantics: keys at positions (p - window, p]); static.  The
    #   static-causal path skips — and never DMAs — tiles fully outside
    #   the window band, so windowed prefill work scales with the window.
) -> jax.Array:
    """Fused attention.  Matches ``layers.dot_product_attention`` with mask
    ``(k_pos <= q_pos if causal) & k_valid [& window band]`` but never
    materializes the [Tq, S] score matrix in the forward.  Differentiable
    (dense-recompute backward).  Returns [B, Tq, H, D] in q.dtype."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    return _flash(
        q, k, v, q_positions, k_positions, k_valid, causal, block_q, block_k,
        interpret, window,
    )
